"""Process groups (docs/GROUPS.md): subgroup communicators in the
negotiation core.

``new_group(ranks)`` registers a group in the native :class:`GroupTable`
and returns a :class:`ProcessGroup` handle that every collective (and
``DistributedOptimizer``) accepts as ``group=``. A group collective
negotiates against the GROUP's member set (readiness bitmaps sized to
the group), caches per group (cache key includes the group id), and
executes over a dedicated ring connecting only the members — ring hops
drop from world-1 to group-1 and disjoint groups' rings run
concurrently.

Discipline (identical to torch.distributed's): EVERY rank — members and
non-members alike — must call ``new_group`` with the identical rank
list in the identical order. Ids come from a per-process counter, so
the same call sequence yields the same ids everywhere; non-members need
the registration too (the response-cache bit protocol treats "not my
group" as vacuously ready, which requires knowing the membership).
Mismatched membership is rejected at negotiation naming the rank.

Groups are per-generation: an elastic re-init clears the native table,
and ``hvd.init(model_parallel=k)`` re-forms the mesh groups after every
(re-)init.
"""


class ProcessGroup:
    """Handle to a registered process group.

    ``id`` is the native group id (0 = the implicit world group);
    ``ranks`` the ascending member world ranks (None for world).
    """

    def __init__(self, group_id, ranks=None):
        self.id = int(group_id)
        self.ranks = tuple(ranks) if ranks is not None else None

    def size(self):
        """Member count (world size for the world group)."""
        from .common.basics import get_basics
        if self.id == 0:
            return get_basics().size()
        if self.ranks is not None:
            return len(self.ranks)
        return int(get_basics().lib.horovod_tpu_group_size(self.id))

    def rank(self):
        """This process's position in the group's ring order, or -1 when
        it is not a member (non-members sit the group's collectives
        out)."""
        from .common.basics import get_basics
        return int(get_basics().lib.horovod_tpu_group_rank(self.id))

    def __contains__(self, world_rank):
        if self.id == 0:
            return True
        return self.ranks is not None and int(world_rank) in self.ranks

    def __eq__(self, other):
        return isinstance(other, ProcessGroup) and other.id == self.id

    def __hash__(self):
        return hash(("ProcessGroup", self.id))

    def __repr__(self):
        if self.id == 0:
            return "ProcessGroup(WORLD)"
        return "ProcessGroup(id=%d, ranks=%r)" % (self.id, list(self.ranks))


#: The implicit world group — ``group=WORLD`` (or ``group=None``) is the
#: pre-groups behavior everywhere.
WORLD = ProcessGroup(0)


def new_group(ranks):
    """Creates a process group over ``ranks`` (world ranks, ascending).

    COLLECTIVE BY CONVENTION: call it on EVERY rank with the identical
    list, in the identical order relative to other ``new_group`` calls.
    Returns a :class:`ProcessGroup`; non-member ranks receive the same
    handle (with ``.rank() == -1``) and must simply not submit the
    group's collectives.
    """
    import ctypes

    from .common.basics import get_basics

    members = sorted(int(r) for r in ranks)
    if len(set(members)) != len(members):
        raise ValueError("duplicate ranks in %r" % (ranks,))
    basics = get_basics()
    if not basics.initialized():
        raise RuntimeError("hvd.init() must run before new_group()")
    arr = (ctypes.c_int32 * len(members))(*members)
    gid = int(basics.lib.horovod_tpu_new_group(arr, len(members)))
    if gid <= 0:
        world = basics.size()
        raise ValueError(
            "invalid process group %r (native error %d): ranks must be "
            "unique world ranks in [0, %d)" % (ranks, gid, world))
    return ProcessGroup(gid, members)


def resolve_group(group):
    """The native group id for a ``group=`` argument: None/WORLD -> 0, a
    ProcessGroup -> its id, a plain int passes through."""
    if group is None:
        return 0
    if isinstance(group, ProcessGroup):
        return group.id
    return int(group)


def group_size(group):
    """Member count behind a ``group=`` argument (world size for None)."""
    from .common.basics import get_basics
    gid = resolve_group(group)
    if gid == 0:
        return get_basics().size()
    if isinstance(group, ProcessGroup) and group.ranks is not None:
        return len(group.ranks)
    n = int(get_basics().lib.horovod_tpu_group_size(gid))
    if n <= 0:
        raise ValueError("unknown process group %d" % gid)
    return n


def assert_sharded_update_world_scope(group=None):
    """Shared guard for every sharded_update wrapper (docs/ZERO.md +
    docs/GROUPS.md): the ZeRO-style sharded weight update shards state
    over the WORLD, so it cannot compose with a group-scoped gradient
    reduction — an explicit non-world ``group=`` or an ACTIVE mesh
    (``hvd.init(model_parallel=k)``) is rejected. Called at wrapper
    construction AND per update: a mesh formed after the optimizer was
    built must fail the next step, not silently reduce-scatter across
    model shards. One definition so the four wrappers can't skew."""
    import horovod_tpu as hvd

    if (group is not None and resolve_group(group) != 0) or \
            (group is None and hvd.batch_group() is not None):
        raise ValueError(
            "sharded_update composes with the world group only; a "
            "group-scoped (mesh) job must use the replicated update "
            "per batch group (docs/GROUPS.md)")


def group_rank(group):
    """This process's group position behind a ``group=`` argument (its
    world rank for None); -1 when not a member."""
    from .common.basics import get_basics
    gid = resolve_group(group)
    if gid == 0:
        return get_basics().rank()
    return int(get_basics().lib.horovod_tpu_group_rank(gid))
