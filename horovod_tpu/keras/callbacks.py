"""Public Keras callbacks (reference: ``horovod/keras/callbacks.py`` —
thin shells binding the shared impls to keras.callbacks.Callback)."""

import keras

from .._keras import callbacks as _impl


class BroadcastGlobalVariablesCallback(
        _impl.BroadcastGlobalVariablesCallbackImpl, keras.callbacks.Callback):
    def __init__(self, root_rank=0):
        super().__init__(keras.backend, root_rank)


class MetricAverageCallback(
        _impl.MetricAverageCallbackImpl, keras.callbacks.Callback):
    def __init__(self):
        super().__init__(keras.backend)


class LearningRateScheduleCallback(
        _impl.LearningRateScheduleCallbackImpl, keras.callbacks.Callback):
    def __init__(self, multiplier, start_epoch=0, end_epoch=None,
                 staircase=True, momentum_correction=True,
                 steps_per_epoch=None):
        super().__init__(keras.backend, multiplier, start_epoch, end_epoch,
                         staircase, momentum_correction, steps_per_epoch)


class LearningRateWarmupCallback(
        _impl.LearningRateWarmupCallbackImpl, keras.callbacks.Callback):
    def __init__(self, warmup_epochs=5, momentum_correction=True,
                 steps_per_epoch=None, verbose=0):
        super().__init__(keras.backend, warmup_epochs, momentum_correction,
                         steps_per_epoch, verbose)
