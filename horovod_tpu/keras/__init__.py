"""Keras binding — public shell over the shared ``horovod_tpu._keras``
implementation (reference: ``horovod/keras/__init__.py`` and
``horovod/tensorflow/keras/__init__.py`` — thin shells over
``horovod/_keras``)."""

import keras

import horovod_tpu as _hvd
from horovod_tpu import (  # noqa: F401
    init, shutdown, is_initialized, rank, local_rank, cross_rank, size,
    local_size, cross_size, is_homogeneous,
    mpi_threads_supported, mpi_enabled, mpi_built, gloo_enabled,
    gloo_built, nccl_built, ddl_built, mlsl_built,
)
from horovod_tpu.tensorflow import (  # noqa: F401
    allreduce, allgather, broadcast, Compression,
)

from .. import _keras as _impl
from . import callbacks  # noqa: F401


def DistributedOptimizer(optimizer, name=None,
                         compression=None, average=True):
    """Wraps a Keras optimizer for synchronous data-parallel training
    (reference: keras/__init__.py:34)."""
    return _impl.create_distributed_optimizer(keras, optimizer, name,
                                              compression, average)


def broadcast_model_weights(model, root_rank=0):
    return _impl.broadcast_model_weights(model, root_rank)


def load_model(filepath, custom_optimizers=None, custom_objects=None):
    """Loads a model saved with a wrapped optimizer, re-wrapping it
    (reference: keras/__init__.py:117, _keras/__init__.py:107-123)."""
    model = keras.models.load_model(filepath,
                                    custom_objects=custom_objects or {})
    if hasattr(model, "optimizer") and model.optimizer is not None and \
            not getattr(model.optimizer, "_HVD_WRAPPED", False):
        model.optimizer = DistributedOptimizer(model.optimizer)
    return model
