"""Keras binding — public shell over the shared ``horovod_tpu._keras``
implementation (reference: ``horovod/keras/__init__.py`` and
``horovod/tensorflow/keras/__init__.py`` — thin shells over
``horovod/_keras``)."""

import keras

import horovod_tpu as _hvd
from horovod_tpu import (  # noqa: F401
    init, shutdown, is_initialized, rank, local_rank, cross_rank, size,
    local_size, cross_size, is_homogeneous,
    mpi_threads_supported, mpi_enabled, mpi_built, gloo_enabled,
    gloo_built, nccl_built, ddl_built, mlsl_built,
)
from horovod_tpu.tensorflow import (  # noqa: F401
    allreduce, allgather, broadcast, Compression,
)

from .. import _keras as _impl
from . import callbacks  # noqa: F401


def DistributedOptimizer(optimizer, name=None,
                         compression=None, average=True, group=None):
    """Wraps a Keras optimizer for synchronous data-parallel training
    (reference: keras/__init__.py:34). ``group`` scopes the gradient
    averaging to a process group (docs/GROUPS.md)."""
    return _impl.create_distributed_optimizer(keras, optimizer, name,
                                              compression, average,
                                              group=group)


def broadcast_model_weights(model, root_rank=0):
    return _impl.broadcast_model_weights(model, root_rank)


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=None):
    """Loads a model saved with a wrapped optimizer, re-wrapping it —
    with the given gradient `compression`, matching the save-time
    configuration (reference: keras/__init__.py:117 `load_model(...,
    compression)`, _keras/__init__.py:107-123).

    A model saved after `DistributedOptimizer` wrapping serializes its
    optimizer as the dynamic `Distributed<Base>` class; this supplies
    those classes to keras deserialization as custom_objects (for every
    stock keras optimizer plus any `custom_optimizers` bases)."""
    co = dict(custom_objects or {})
    bases = list(custom_optimizers or [])
    for nm in dir(keras.optimizers):
        cls = getattr(keras.optimizers, nm)
        if isinstance(cls, type) and \
                issubclass(cls, keras.optimizers.Optimizer) and \
                cls is not keras.optimizers.Optimizer:
            bases.append(cls)
    for base in bases:
        co.setdefault("Distributed%s" % base.__name__,
                      _impl.distributed_optimizer_class(
                          base, compression=compression))
    model = keras.models.load_model(filepath, custom_objects=co)
    if hasattr(model, "optimizer") and model.optimizer is not None and \
            not getattr(model.optimizer, "_HVD_WRAPPED", False):
        model.optimizer = DistributedOptimizer(model.optimizer,
                                               compression=compression)
    return model
