"""Elastic training: survive membership changes without a job restart.

Reference lineage: Horovod Elastic (the successor capability to the
reproduced v0.17 — ``horovod/run/elastic/`` + ``horovod/common/elastic.py``
in later releases). A running job *shrinks* when a worker dies (survivors
roll back to the last committed state and continue at reduced size) and
*grows* when hosts return (the driver spawns replacements that sync state
from rank 0) — instead of the classic kill-all-on-first-exit teardown.

Pieces:

* :class:`ElasticState` (``state.py``) — commits/restores a pytree of
  model + optimizer arrays, and syncs it from rank 0 after every
  membership change.
* :func:`run` (``run.py``) — decorator that catches
  ``HorovodInternalError`` (peer lost mid-collective: roll back, re-init,
  re-sync) and ``HostsUpdatedInterrupt`` (graceful membership change:
  re-init, re-sync, no rollback).
* ``discovery.py`` — host discovery (script-driven or fixed) plus the
  per-host failure blacklist with exponential backoff.
* ``driver.py`` — the launcher-side supervisor: monitors workers,
  blacklists failing hosts, bumps the rendezvous generation, and spawns
  replacements, keeping the world between ``--min-np`` and ``--max-np``.
* ``durable.py`` — async sharded durable snapshots of the committed
  state (``ElasticState.enable_durable`` / ``--ckpt-dir``): CRC32C
  manifests, atomic tmp→fsync→rename writes, torn-write-proof restore,
  and full-job crash recovery (auto-resume in :func:`run`).

See docs/ELASTIC.md for the state-commit semantics, the discovery script
contract, and the failure model.
"""

from .discovery import (  # noqa: F401
    FixedHosts,
    HostDiscovery,
    HostDiscoveryScript,
    HostManager,
)
from .durable import (  # noqa: F401
    CkptFaultInjector,
    DurableCheckpointer,
    last_durable_step,
    latest_valid_manifest,
)
from .run import HostsUpdatedInterrupt, run  # noqa: F401
from .state import ElasticState, State  # noqa: F401
