"""Elastic driver: the launcher-side supervisor loop.

Reference analogue: ``horovod/run/elastic/driver.py`` (ElasticDriver:
worker monitoring, host blacklisting, rank reassignment, respawn); fresh
implementation over this repo's rendezvous KV and middleman-wrapped
process tree.

Replaces the static launcher's kill-all-on-first-exit behavior: a failed
worker shrinks the job (its host goes on the backoff blacklist, the
generation number is bumped, and survivors re-rendezvous at the reduced
size), a recovered host grows it back (a replacement worker is spawned
and absorbed at the next generation) — all without restarting the
surviving worker processes.

Membership is published to the driver-owned rendezvous server at scope
``elastic`` / key ``state``::

    {"generation": g, "size": n,
     "assignment": {"<worker_id>": rank, ...},
     "status": "running" | "shutdown"}

Worker ids are stable per spawned process; ranks are reassigned every
generation in worker-id order, so the longest-lived worker is always the
new rank 0 (the state-sync root).
"""

import collections
import json
import os
import signal
import sys
import threading
import time

from horovod_tpu.run import rendezvous, util

from .discovery import HostManager, plan_spawns
from .state import EXIT_DRAINED, KEY_DRAIN, KEY_STATE, SCOPE_ELASTIC

_Slot = collections.namedtuple("_Slot", ["hostname", "rank"])


class _Worker:
    def __init__(self, worker_id, hostname, proc):
        self.worker_id = worker_id
        self.hostname = hostname
        self.proc = proc
        self.started = time.monotonic()
        self.healthy = False  # outlived the health window at least once


class ElasticDriver:
    """Supervises elastic workers; returns the job's exit code from
    :meth:`run`."""

    def __init__(self, command, discovery, min_np, max_np,
                 np_initial=None, ssh_port=None, start_timeout=60,
                 verbose=False, env=None, ckpt_dir=None,
                 restart_from_ckpt=False, drain_grace=None,
                 health_sink=None, placement="pack"):
        if min_np < 1 or max_np < min_np:
            raise ValueError("need 1 <= min_np <= max_np (got %d..%d)"
                             % (min_np, max_np))
        self._command = list(command)
        self._placement = placement
        self._min_np = min_np
        self._max_np = max_np
        self._np_initial = np_initial
        self._ssh_port = ssh_port
        self._start_timeout = start_timeout
        self._verbose = verbose
        self._base_env = dict(env if env is not None else os.environ)
        self._ckpt_dir = ckpt_dir or self._base_env.get("HVD_TPU_CKPT_DIR")
        self._restart_from_ckpt = restart_from_ckpt and self._ckpt_dir
        self._restarts = 0
        self._max_restarts = int(os.environ.get(
            "HVD_TPU_CKPT_MAX_RESTARTS", "3"))
        cooldown = float(os.environ.get("HVD_TPU_ELASTIC_COOLDOWN", "10"))
        self._hosts = HostManager(discovery, cooldown=cooldown)
        # Optional mirror for host-health evidence (record_failure /
        # record_success): the fleet controller passes its
        # PlacementPool here so one tenant's crashing host blacklists
        # fleet-wide, not just within the observing job.
        self._health_sink = health_sink
        self._discovery_interval = float(
            os.environ.get("HVD_TPU_ELASTIC_DISCOVERY_INTERVAL", "1.0"))

        self._workers = {}  # worker_id -> _Worker
        self._next_worker_id = 0
        self._generation = -1  # first publish makes it 0
        self._published_at = 0.0
        self._published_size = 0
        self._job_done = False
        self._late_rcs = []

        # Graceful drain (docs/FLEET.md): the supervisor-side half of
        # the protocol. `_drain_epoch` numbers the published requests;
        # `_drain_victims` holds the worker ids the current epoch
        # covers (escalated with SIGKILL at `_drain_deadline`);
        # `_draining_all` marks a whole-job drain, whose completion
        # makes run() return EXIT_DRAINED instead of tearing down.
        self._drain_grace = drain_grace
        self._drain_epoch = 0
        self._drain_completed = 0
        self._drain_victims = set()
        self._drain_deadline = None
        self._draining_all = False
        self._term_requested = False
        self._abort = False
        # Guards the drain bookkeeping: request_drain runs on the
        # FLEET CONTROLLER's thread while the run loop's tombstone
        # check runs on the driver thread — without the lock, the loop
        # slipping between the epoch bump and the victim registration
        # would tombstone the brand-new epoch as already-completed, and
        # the live drain record would then never be tombstoned (late
        # replacement workers would keep re-acting on it).
        self._drain_lock = threading.Lock()

        self._secret = rendezvous.make_secret()
        self._server = rendezvous.RendezvousServer(key=self._secret)
        self._addr = None

    # -- worker spawn ------------------------------------------------------
    def _worker_env(self, worker_id):
        env = dict(self._base_env)
        for key in ("HVD_TPU_ADDRS", "HVD_TPU_RANK", "HVD_TPU_SIZE",
                    "HVD_TPU_LOCAL_RANK", "HVD_TPU_LOCAL_SIZE",
                    "HVD_TPU_CROSS_RANK", "HVD_TPU_CROSS_SIZE",
                    "HVD_TPU_GENERATION"):
            env.pop(key, None)
        env.update({
            "HVD_TPU_ELASTIC": "1",
            "HVD_TPU_WORKER_ID": str(worker_id),
            "HVD_TPU_RENDEZVOUS_ADDR": self._addr,
            rendezvous.KEY_ENV: self._secret,
        })
        if self._ckpt_dir:
            # Durable checkpoints (docs/ELASTIC.md "Durability"): every
            # worker — including replacements spawned mid-job and the
            # fresh cohort of a --restart-from-ckpt relaunch — writes
            # to and auto-resumes from the same directory.
            env["HVD_TPU_CKPT_DIR"] = self._ckpt_dir
        env.setdefault("HVD_TPU_START_TIMEOUT", str(self._start_timeout))
        return env

    def _spawn(self, hostname):
        from horovod_tpu.run.run import launch

        wid = self._next_worker_id
        self._next_worker_id += 1
        slot = _Slot(hostname=hostname, rank=wid)
        proc = launch([slot], [self._worker_env(wid)], self._command,
                      ssh_port=self._ssh_port, verbose=self._verbose)[0]
        self._workers[wid] = _Worker(wid, hostname, proc)
        if self._verbose:
            sys.stderr.write("[elastic] spawned worker %d on %s\n"
                             % (wid, hostname))
        return wid

    # -- membership publication --------------------------------------------
    def _publish(self, status="running"):
        self._generation += 1
        assignment = {str(wid): rank for rank, wid in
                      enumerate(sorted(self._workers))}
        self._server.put_local(SCOPE_ELASTIC, KEY_STATE, json.dumps({
            "generation": self._generation,
            "size": len(assignment),
            "assignment": assignment,
            "status": status,
        }))
        self._published_at = time.monotonic()
        self._published_size = len(assignment)
        if self._verbose:
            sys.stderr.write("[elastic] generation %d: %s\n"
                             % (self._generation, assignment))

    def _publish_done(self):
        """Re-publishes the current generation with status \"done\": a
        replacement still waiting in bootstrap/rendezvous when training
        finishes has no generation left to join — it must exit cleanly
        instead of timing out with a failure rc. Generation is NOT
        bumped, so workers mid-training are not interrupted."""
        assignment = {str(wid): rank for rank, wid in
                      enumerate(sorted(self._workers))}
        self._server.put_local(SCOPE_ELASTIC, KEY_STATE, json.dumps({
            "generation": self._generation,
            "size": len(assignment),
            "assignment": assignment,
            "status": "done",
        }))

    def _generation_resolved(self):
        """True when the current generation needs no rendezvous
        (size <= 1) or its rendezvous published a resolved table."""
        if self._published_size <= 1:
            return True
        resolved = self._server.scope_items(
            rendezvous.gen_scope(rendezvous.SCOPE_RESOLVED,
                                 self._generation))
        return "table" in resolved

    def _generation_stalled(self):
        """True when the current generation's rendezvous has not
        converged (no resolved table) within the start timeout — e.g. a
        participant died mid-rendezvous without the driver noticing an
        exit. Bumping the generation unsticks the survivors."""
        if self._published_size <= 1:
            return False  # size-1 generations do not rendezvous
        if time.monotonic() - self._published_at < self._start_timeout:
            return False
        return not self._generation_resolved()

    def _generation_ready(self):
        """Growth gate: True once the CURRENT generation either has a
        resolved rendezvous or has provably stalled (the stall path
        bumps it anyway). Publishing a grow-generation while the
        current one is still rendezvousing strands late-arriving
        survivors in the superseded scope: after a shrink, the
        survivors re-bootstrap a second or two apart (connection-loss
        detection and reconnect windows are not synchronized across
        ranks), and if the blacklist cooldown expires inside that gap
        the respawn used to bump the generation between their
        bootstraps — one survivor then waited in gen N and the other in
        gen N+1 until both timed out. (Within the start-timeout window
        the stalled check short-circuits on time, so an unresolved
        generation costs one scope lookup per tick, not two.)"""
        return self._generation_resolved() or self._generation_stalled()

    def _reinit_requested(self):
        """True when any live worker published a reinit request for the
        current (or a newer) generation — its core lost a peer connection
        without any process exiting."""
        for key, val in self._server.scope_items(SCOPE_ELASTIC).items():
            if not key.startswith("reinit/"):
                continue
            try:
                if int(val.decode()) >= self._generation:
                    return True
            except ValueError:
                continue
        return False

    # -- monitoring --------------------------------------------------------
    def _reap(self):
        """Collects exited workers. Returns True when membership changed
        due to a failure."""
        changed = False
        health_after = min(10.0, self._start_timeout)
        now = time.monotonic()
        for wid, w in list(self._workers.items()):
            rc = w.proc.poll()
            if rc is None:
                if not w.healthy and now - w.started > health_after:
                    w.healthy = True
                    self._hosts.record_success(w.hostname,
                                               started_at=w.started)
                    if self._health_sink is not None:
                        self._health_sink.record_success(
                            w.hostname, started_at=w.started)
                continue
            del self._workers[wid]
            if rc == 0:
                if not self._job_done:
                    self._job_done = True
                    self._publish_done()
                if self._verbose:
                    sys.stderr.write(
                        "[elastic] worker %d finished\n" % wid)
            elif rc == EXIT_DRAINED or wid in self._drain_victims:
                # Voluntary exit (graceful drain / preemption hand-back,
                # incl. a victim the grace escalation had to SIGKILL):
                # the host is healthy by definition — it re-enters the
                # spawnable pool immediately instead of tripping the
                # failure blacklist's backoff cooldown. Membership still
                # changed, so survivors repartition at a new generation.
                self._drain_victims.discard(wid)
                self._hosts.record_release(w.hostname)
                sys.stderr.write(
                    "[elastic] worker %d on %s drained (%s); host "
                    "released without blacklist\n"
                    % (wid, w.hostname,
                       "rc=%d" % rc if rc == EXIT_DRAINED
                       else "escalated, rc=%d" % rc))
                if not self._job_done:
                    changed = True
            elif self._job_done:
                self._late_rcs.append(rc)
            else:
                sys.stderr.write(
                    "[elastic] worker %d on %s failed (rc=%d); "
                    "blacklisting host with backoff\n"
                    % (wid, w.hostname, rc))
                self._hosts.record_failure(w.hostname)
                if self._health_sink is not None:
                    self._health_sink.record_failure(w.hostname)
                changed = True
        return changed

    def _plan_growth(self):
        """Hosts with free, non-blacklisted slots to spawn on (one entry
        per new worker), capped at max_np. The planning rule itself is
        the shared `plan_spawns` — the fleet controller plans multi-job
        placements with the same function."""
        if self._job_done or self._draining_all:
            return []
        live_per_host = collections.Counter(
            w.hostname for w in self._workers.values())
        return plan_spawns(self._hosts.available_hosts_and_slots(),
                           live_per_host,
                           self._max_np - len(self._workers),
                           placement=self._placement)

    def _kill_all(self):
        for w in self._workers.values():
            try:
                os.killpg(os.getpgid(w.proc.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass

    # -- graceful drain (supervisor side; docs/FLEET.md) -------------------
    def request_drain(self, victims="all", grace=None):
        """Publishes a drain request: the victim workers finish their
        in-flight step, force a durable commit of exactly that step,
        and exit with EXIT_DRAINED (elastic/run.py honors it at the
        next commit's agreement allreduce). `victims` is "all" or a
        list of worker ids; `grace` the seconds before the driver
        escalates to SIGKILL. Thread-safe enough for the fleet
        controller's call pattern (one supervisor thread per job plus
        the controller thread requesting drains)."""
        if grace is None:
            grace = self._drain_grace if self._drain_grace else 30.0
        with self._drain_lock:
            self._drain_epoch += 1
            if victims == "all":
                self._drain_victims.update(self._workers)
                self._draining_all = True
                wire_victims = "all"
            else:
                wire_victims = [str(v) for v in victims]
                self._drain_victims.update(int(v) for v in wire_victims)
            self._server.put_local(SCOPE_ELASTIC, KEY_DRAIN, json.dumps({
                "epoch": self._drain_epoch,
                "workers": wire_victims,
                "grace": grace,
            }))
            self._drain_deadline = time.monotonic() + grace
        sys.stderr.write(
            "[elastic] drain epoch %d requested for worker(s) %s "
            "(grace %.0fs)\n" % (self._drain_epoch, wire_victims, grace))

    def draining(self):
        """True while a drain epoch has victims that have not exited."""
        return bool(self._drain_victims)

    def _escalate_drain(self):
        """SIGKILLs drain victims that outlived the grace window (a
        worker wedged in a collective cannot reach its next commit to
        notice the request). Their exits still count as voluntary —
        the ESCALATION was planned, the host is not failure-suspect."""
        if self._drain_deadline is None or \
                time.monotonic() < self._drain_deadline:
            return
        for wid in sorted(self._drain_victims):
            w = self._workers.get(wid)
            if w is None:
                self._drain_victims.discard(wid)
                continue
            sys.stderr.write(
                "[elastic] drain grace expired; escalating to SIGKILL "
                "for worker %d\n" % wid)
            try:
                os.killpg(os.getpgid(w.proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        self._drain_deadline = None

    # -- durable-checkpoint restart (--restart-from-ckpt) -----------------
    def _report_last_durable(self):
        """Names the newest durable step in the teardown summary, so an
        operator knows exactly what a restart recovers (nothing, when
        durability was off or no checkpoint ever published)."""
        if not self._ckpt_dir:
            return
        from .durable import describe_last_durable
        sys.stderr.write(
            "[elastic] %s\n" % describe_last_durable(self._ckpt_dir))

    def _teardown_workers(self, grace=10.0):
        """Kills every remaining worker (SIGTERM, then SIGKILL after
        `grace`) and reaps them WITHOUT blacklisting their hosts — a
        deliberate restart kill is not host evidence."""
        self._kill_all()
        deadline = time.monotonic() + grace
        while self._workers:
            for wid, w in list(self._workers.items()):
                if w.proc.poll() is not None:
                    del self._workers[wid]
            if not self._workers:
                break
            if time.monotonic() > deadline:
                for w in self._workers.values():
                    try:
                        os.killpg(os.getpgid(w.proc.pid), signal.SIGKILL)
                    except (ProcessLookupError, PermissionError):
                        pass
                for w in self._workers.values():
                    try:
                        w.proc.wait(timeout=5)
                    except Exception:
                        pass
                self._workers = {}
                break
            time.sleep(0.1)

    def _try_restart_from_ckpt(self, reason):
        """Full-job restart instead of teardown: kill whatever is left,
        clear the host blacklist, wait for discovery to offer at least
        --min-np slots again, and spawn a fresh cohort that auto-resumes
        from the newest valid durable manifest. Returns True when the
        restart was performed (the caller continues supervising)."""
        if not self._restart_from_ckpt:
            return False
        if self._restarts >= self._max_restarts:
            sys.stderr.write(
                "[elastic] restart budget exhausted (%d/%d); tearing "
                "down for real\n" % (self._restarts, self._max_restarts))
            return False
        self._restarts += 1
        from .durable import last_durable_step
        step, _ = last_durable_step(self._ckpt_dir)
        sys.stderr.write(
            "[elastic] %s; full-job restart %d/%d from durable "
            "checkpoint (last durable step: %s)\n"
            % (reason, self._restarts, self._max_restarts,
               step if step is not None else "none — fresh start"))
        self._teardown_workers()
        self._hosts.reset()
        deadline = time.monotonic() + self._start_timeout
        while True:
            self._hosts.refresh()
            capacity = sum(
                self._hosts.available_hosts_and_slots().values())
            if capacity >= self._min_np:
                break
            if time.monotonic() > deadline:
                sys.stderr.write(
                    "[elastic] restart aborted: discovery offered %d "
                    "slot(s) < --min-np=%d within %ds\n"
                    % (capacity, self._min_np, int(self._start_timeout)))
                return False
            time.sleep(self._discovery_interval)
        target = min(self._np_initial or capacity, self._max_np, capacity)
        for host in self._plan_growth()[:target]:
            self._spawn(host)
        self._publish()
        return True

    # -- fleet-controller surface (horovod_tpu/fleet/controller.py) --------
    def live_per_host(self):
        """{host: live worker count} — the controller's occupancy view
        (snapshot read; safe from another thread under the GIL)."""
        counts = collections.Counter(
            w.hostname for w in self._workers.values())
        return dict(counts)

    def live_workers(self):
        """Sorted live worker ids (chaos kill-victim candidates)."""
        return sorted(self._workers)

    def worker_hosts(self):
        """{worker id: hostname} for live workers — serve endpoint
        discovery needs the HOST each replica landed on, not just its
        id (snapshot read; safe from another thread under the GIL)."""
        return {wid: w.hostname for wid, w in self._workers.items()}

    def worker_pid(self, wid):
        w = self._workers.get(wid)
        return w.proc.pid if w is not None else None

    def resize(self, max_np):
        """Moves the growth ceiling (the fleet controller shrinks it
        before a partial drain so the driver does not regrow into the
        slots it is handing back, and raises it again when capacity is
        leased back)."""
        self._max_np = max(1, int(max_np))

    def terminate(self):
        """Hard teardown from the controller (fleet shutdown): the run
        loop kills the workers and returns 1 at its next tick."""
        self._abort = True

    # -- main loop ---------------------------------------------------------
    def run(self, install_signal_handlers=True):
        """Supervises the job; returns its exit code. The fleet
        controller runs one driver per job in a worker THREAD and
        passes install_signal_handlers=False (signal.signal is
        main-thread-only; the controller owns the process's signals)."""
        local_addr = self._base_env.get("HVD_TPU_RENDEZVOUS_HOST")
        self._hosts.refresh()
        hosts = self._hosts.available_hosts_and_slots()
        if local_addr is None:
            remote = [h for h in hosts if not util.is_local_host(h)]
            local_addr = (rendezvous.routable_ip(remote[0]) if remote
                          else "127.0.0.1")
        self._addr = "%s:%d" % (local_addr, self._server.start())
        if not install_signal_handlers:
            try:
                return self._run_loop()
            finally:
                self._server.stop()

        def on_signal(signum, frame):
            if signum == signal.SIGTERM and self._drain_grace:
                # Preemption-style SIGTERM (fleet controller, cluster
                # manager): drain instead of killing — workers finish
                # the in-flight step, durable-commit it, and exit
                # cleanly; the loop escalates at grace expiry and
                # run() returns EXIT_DRAINED.
                self._term_requested = True
                return
            self._publish(status="shutdown")
            self._kill_all()
            sys.exit(1)

        old_int = signal.signal(signal.SIGINT, on_signal)
        old_term = signal.signal(signal.SIGTERM, on_signal)
        try:
            return self._run_loop()
        finally:
            signal.signal(signal.SIGINT, old_int)
            signal.signal(signal.SIGTERM, old_term)
            self._server.stop()

    def _run_loop(self):
        # Initial cohort: -np (clamped to capacity and max_np); spawning
        # less than min_np up front is a hard error — elasticity begins
        # once a valid job exists.
        capacity = sum(self._hosts.available_hosts_and_slots().values())
        target = min(self._np_initial or capacity, self._max_np, capacity)
        if target < self._min_np:
            raise RuntimeError(
                "elastic launch needs at least --min-np=%d slots but "
                "discovery found %d" % (self._min_np, capacity))
        plan = self._plan_growth()[:target]
        below_min_since = None
        last_discovery = 0.0
        while True:
            if plan and (self._job_done or self._draining_all):
                # Completion (or a whole-job drain) won the race against
                # a planned grow — spawning into a finished/draining job
                # would strand a worker outside the drain epoch.
                plan = []
            if plan:
                # Spawn first (allocating the new worker ids), then
                # publish one assignment covering old + new workers.
                # Ordering is race-free either way: starting workers
                # poll the assignment until their id appears, and live
                # workers notice the bumped generation at their next
                # commit.
                for host in plan:
                    self._spawn(host)
                self._publish()
                plan = []
            time.sleep(0.1)
            if self._abort:
                self._publish(status="shutdown")
                self._teardown_workers()
                return 1
            if self._term_requested and not self._draining_all:
                self.request_drain("all")
            self._escalate_drain()
            changed = self._reap()
            with self._drain_lock:
                if not self._drain_victims:
                    self._drain_deadline = None
                    if self._drain_epoch > self._drain_completed and \
                            not self._draining_all:
                        # Tombstone the completed epoch: a replacement
                        # spawned AFTER a partial drain must fast-forward
                        # past the stale record instead of re-acting on
                        # it (elastic/run.py reads `done` as
                        # already-honored).
                        self._drain_completed = self._drain_epoch
                        self._server.put_local(
                            SCOPE_ELASTIC, KEY_DRAIN, json.dumps({
                                "epoch": self._drain_epoch, "workers": [],
                                "grace": 0, "done": True}))
            if self._job_done:
                if not self._workers:
                    return max(self._late_rcs, default=0)
                continue  # let the rest finish; no more respawns
            if self._draining_all and not self._workers:
                # Whole-job drain complete: every worker durable-
                # committed and handed its host back. EXIT_DRAINED (not
                # 1) tells the supervisor this was the requested
                # preemption, restorable from the durable lineage.
                self._publish(status="shutdown")
                self._report_last_durable()
                sys.stderr.write(
                    "[elastic] drain complete; job preempted cleanly\n")
                return EXIT_DRAINED
            if not changed and self._reinit_requested():
                sys.stderr.write("[elastic] reinit requested by a worker; "
                                 "bumping generation\n")
                changed = True
            if not changed and self._generation_stalled():
                sys.stderr.write("[elastic] generation %d stalled; "
                                 "bumping\n" % self._generation)
                changed = True

            now = time.monotonic()
            if now - last_discovery > self._discovery_interval:
                last_discovery = now
                self._hosts.refresh()
            # Growth only once the current generation has converged (or
            # stalled) — see _generation_ready. Shrink/failure bumps are
            # not gated: a dead worker must repartition immediately.
            plan = self._plan_growth() if self._generation_ready() else []

            if self._draining_all:
                # Victims are exiting by design; the below-min teardown
                # and restart-from-ckpt paths must not fire on the way
                # down (the drain-complete check above owns the exit).
                continue
            if len(self._workers) + len(plan) < self._min_np:
                plan = []
                if not self._workers:
                    if self._try_restart_from_ckpt(
                            "no workers left and no spawnable hosts"):
                        below_min_since = None
                        continue
                    self._publish(status="shutdown")
                    self._report_last_durable()
                    sys.stderr.write(
                        "[elastic] no workers left and no spawnable "
                        "hosts; failing the job\n")
                    return 1
                if below_min_since is None:
                    below_min_since = now
                elif now - below_min_since > self._start_timeout:
                    if self._try_restart_from_ckpt(
                            "stuck below --min-np=%d for %ds"
                            % (self._min_np, int(self._start_timeout))):
                        below_min_since = None
                        continue
                    sys.stderr.write(
                        "[elastic] stuck below --min-np=%d for %ds; "
                        "tearing down\n"
                        % (self._min_np, int(self._start_timeout)))
                    self._publish(status="shutdown")
                    self._report_last_durable()
                    self._kill_all()
                    return 1
                continue
            below_min_since = None
            if changed and not plan:
                self._publish()
            # When plan is non-empty (with or without a membership
            # change), the top of the next iteration spawns the new
            # workers first — allocating their worker ids — and then
            # publishes one combined assignment.


def run_elastic(np_, discovery, command, min_np, max_np, ssh_port=None,
                start_timeout=60, verbose=False, env=None,
                ckpt_dir=None, restart_from_ckpt=False,
                drain_grace=None):
    """Launcher entry: supervise `command` elastically. Returns exit
    code (EXIT_DRAINED after a SIGTERM-driven graceful drain when
    `drain_grace` is set)."""
    driver = ElasticDriver(command, discovery, min_np, max_np,
                           np_initial=np_, ssh_port=ssh_port,
                           start_timeout=start_timeout, verbose=verbose,
                           env=env, ckpt_dir=ckpt_dir,
                           restart_from_ckpt=restart_from_ckpt,
                           drain_grace=drain_grace)
    return driver.run()
