"""Durable elastic checkpoints: async sharded snapshots with
torn-write-proof restore (docs/ELASTIC.md "Durability").

The elastic layer's ``commit()`` snapshots to host memory only — enough
to survive any *partial* failure, but a whole-slice preemption or driver
death loses every step since the user's last manual checkpoint. This
module adds the missing durability layer on top of the existing
commit/rollback machinery:

* **Async**: every Nth ``commit()`` hands its existing host-memory deep
  copy to a background writer thread; the training loop never blocks on
  storage. Only the newest pending snapshot is kept — if storage is
  slower than the commit cadence, intermediate snapshots are skipped,
  never queued without bound.
* **Sharded**: each rank writes only the leaves assigned to it
  (``leaf_index % world_size == rank``), so a large state spreads its
  write bandwidth across hosts. Rank 0 publishes a ``MANIFEST.json``
  listing every shard's path, byte size, and CRC32C once all shards of
  the step exist.
* **Atomic + torn-write-proof**: every file goes to ``*.tmp`` →
  ``fsync`` → ``rename``; the manifest is written last; restore
  validates every shard's size and CRC32C (reusing the native
  transport checksum via ``horovod_tpu_crc32c``, with a pure-Python
  fallback) and silently falls back to the newest *valid* manifest — a
  crash mid-write or a flipped bit can never be restored.
* **Fail-soft**: storage failures retry with capped backoff, then
  degrade to a warning plus ``ckpt_write_failures_total``; a durable
  write can never kill training.

Restore is rank-0-read + broadcast (through ``State.sync()``), exactly
like the elastic state sync — so the restoring job's world size is free
to differ from the saved one (re-sharding is implicit), and only rank 0
needs to see the checkpoint directory.

Storage fault injection (seeded, deterministic — the storage sibling of
``native/fault``'s ``HVD_TPU_FAULT_SPEC``)::

    HVD_TPU_CKPT_FAULT_SPEC := clause (';' clause)*
    clause := 'seed=N' | rule
    rule   := field (',' field)*
    field  := 'op=shard|manifest|any'   which file kind to hit
            | 'rank=N'                  only this rank's writer
            | 'write=N'                 fire at the Nth matching write
            | 'prob=P'                  fire with probability P (seeded)
            | 'count=K'                 max fires (default 1 for write=,
                                        unlimited for prob=)
            | 'action=torn|bitflip|enospc|slowfsync'
            | 'delay_ms=D'              slowfsync duration (default 1000)

Action semantics:

* ``torn``     the file is truncated to half its bytes but still
               renamed into place (a non-atomic store crashing
               mid-write); restore detects the size/CRC mismatch.
* ``bitflip``  one payload byte is flipped after the CRC was computed;
               restore detects the CRC mismatch.
* ``enospc``   the write raises ``OSError(ENOSPC)`` — exercises the
               retry/degrade path.
* ``slowfsync`` fsync sleeps ``delay_ms`` — exercises writer/training
               overlap (commit latency must not inflate).
"""

import errno
import json
import os
import pickle
import random
import re
import shutil
import sys
import threading
import time

from .state import _tree_flatten, _tree_map_leaves

MANIFEST_NAME = "MANIFEST.json"
MANIFEST_FORMAT = 1

# ckpt-<step, zero-padded so lexical order == numeric order>-g<generation>
_CKPT_DIR_RE = re.compile(r"^ckpt-(\d{12})-g(\d+)$")
# shard-<rank>-of-<world>.<crc32c hex8>.<bytes>.bin
_SHARD_RE = re.compile(r"^shard-(\d{5})-of-(\d{5})\.([0-9a-f]{8})\.(\d+)"
                       r"\.bin$")


def _log(msg):
    sys.stderr.write("[durable] %s\n" % msg)
    sys.stderr.flush()


# ---------------------------------------------------------------------------
# CRC32C: native export when the core is loaded, pure-Python fallback.

_PY_TABLE = None


def _py_crc32c(data, crc=0):
    """Pure-Python CRC32C (Castagnoli, reflected 0x82F63B78), bit-exact
    with native/checksum.cc (same ~crc pre/post conditioning, so
    incremental chaining interoperates). Slow (~MB/s) — the fallback
    for environments where the native core cannot build; the writer
    prefers the native export."""
    global _PY_TABLE
    if _PY_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ (0x82F63B78 if c & 1 else 0)
            table.append(c)
        _PY_TABLE = table
    crc ^= 0xFFFFFFFF
    for b in bytes(data):
        crc = _PY_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


_native_crc = None  # False = probed and unavailable


def crc32c(data, crc=0):
    """CRC32C over `data`, chained from `crc` (start at 0). Uses the
    native core's slicing-by-8 export (~GB/s) when loadable, else the
    pure-Python table fallback."""
    global _native_crc
    if _native_crc is None:
        try:
            from horovod_tpu.common.basics import get_basics
            _native_crc = get_basics().crc32c
        except Exception:
            _native_crc = False
    if _native_crc:
        return _native_crc(data, crc)
    return _py_crc32c(data, crc)


# ---------------------------------------------------------------------------
# Storage fault injection

_ACTIONS = ("torn", "bitflip", "enospc", "slowfsync")


class _FaultRule:
    __slots__ = ("op", "rank", "write", "prob", "count", "action",
                 "delay_ms", "seen")

    def __init__(self):
        self.op = None        # 'shard' | 'manifest' | None = any
        self.rank = -1        # -1 = any
        self.write = -1       # fire at Nth matching write (0-based)
        self.prob = 0.0
        self.count = None     # remaining fires; None = default
        self.action = None
        self.delay_ms = 1000
        self.seen = 0


class CkptFaultInjector:
    """Deterministic storage fault injector, configured from
    ``HVD_TPU_CKPT_FAULT_SPEC`` (grammar in the module docstring).
    Mirrors ``native/fault``'s seeded-PRNG design: a given (spec, rank)
    replays the same fault sequence every run."""

    def __init__(self, spec=None, rank=0):
        self._rules = []
        self._rng = random.Random(0)
        self._rank = rank
        self.fires = 0
        if spec:
            self._parse(spec)

    @property
    def active(self):
        return bool(self._rules)

    def _parse(self, spec):
        seed = 0
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            if clause.startswith("seed="):
                seed = int(clause[5:])
                continue
            rule = _FaultRule()
            for field in clause.split(","):
                field = field.strip()
                if not field:
                    continue
                key, _, val = field.partition("=")
                if key == "op":
                    rule.op = None if val == "any" else val
                    if rule.op not in (None, "shard", "manifest"):
                        raise ValueError("bad op=%s" % val)
                elif key == "rank":
                    rule.rank = int(val)
                elif key == "write":
                    rule.write = int(val)
                elif key == "prob":
                    rule.prob = float(val)
                elif key == "count":
                    rule.count = int(val)
                elif key == "action":
                    if val not in _ACTIONS:
                        raise ValueError("bad action=%s" % val)
                    rule.action = val
                elif key == "delay_ms":
                    rule.delay_ms = int(val)
                else:
                    raise ValueError(
                        "unknown ckpt fault field %r" % field)
            if rule.action is None:
                raise ValueError("ckpt fault rule without action=: %r"
                                 % clause)
            if rule.count is None:
                rule.count = 1 if rule.write >= 0 else -1
            self._rules.append(rule)
        self._rng = random.Random(seed * 1000003 + self._rank)

    def on_write(self, op):
        """Returns (action, delay_ms) for this write, or (None, 0).
        `op` is 'shard' or 'manifest'. Counted per rule over matching
        writes, like the transport injector's frame counters."""
        for rule in self._rules:
            if rule.op is not None and rule.op != op:
                continue
            if rule.rank >= 0 and rule.rank != self._rank:
                continue
            idx = rule.seen
            rule.seen += 1
            if rule.count == 0:
                continue
            if rule.write >= 0:
                if idx != rule.write:
                    continue
            elif rule.prob > 0.0:
                if self._rng.random() >= rule.prob:
                    continue
            else:
                continue
            if rule.count > 0:
                rule.count -= 1
            self.fires += 1
            return rule.action, rule.delay_ms
        return None, 0


# ---------------------------------------------------------------------------
# On-disk format helpers

def _ckpt_dirname(step, generation):
    return "ckpt-%012d-g%d" % (step, generation)


def _shard_name(rank, world_size, crc, nbytes):
    return "shard-%05d-of-%05d.%08x.%d.bin" % (rank, world_size, crc,
                                               nbytes)


def _fsync_dir(path):
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass  # some filesystems refuse directory fsync; rename is done


def _atomic_write(path, data, injector=None, op="shard"):
    """data -> path.tmp -> fsync -> rename. Fault-injection hooks sit
    exactly where a real storage failure would: ENOSPC at write time,
    torn content at rename time, slow fsync in between."""
    action, delay_ms = (None, 0)
    if injector is not None and injector.active:
        action, delay_ms = injector.on_write(op)
    if action == "enospc":
        raise OSError(errno.ENOSPC, "injected ENOSPC (%s)" % op)
    if action == "bitflip":
        data = bytearray(data)
        data[len(data) // 2] ^= 0x40
        data = bytes(data)
    if action == "torn":
        data = data[:max(1, len(data) // 2)]
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        if action == "slowfsync":
            time.sleep(delay_ms / 1000.0)
        os.fsync(f.fileno())
    os.rename(tmp, path)
    _fsync_dir(os.path.dirname(path))


def _leaf_items(committed):
    """Flattens a committed attribute dict into an ordered list of
    (path, leaf) — the deterministic order every rank derives shard
    assignment from."""
    return _tree_flatten(committed)


def list_checkpoints(directory):
    """[(step, generation, dirpath)] sorted newest-first, for every
    ckpt-* directory (valid or not)."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        m = _CKPT_DIR_RE.match(name)
        if m:
            out.append((int(m.group(1)), int(m.group(2)),
                        os.path.join(directory, name)))
    out.sort(reverse=True)
    return out


def validate_manifest(ckpt_dir, deep=True):
    """Loads and validates one checkpoint directory: manifest parses,
    every shard exists with the manifested byte size — and, when `deep`
    (the restore path), the manifested CRC32C over the actual bytes.
    `deep=False` (a stat per shard, no data read) is for bookkeeping
    like retention, where re-reading every byte of every kept
    checkpoint on each publish would tax the very storage the writer is
    protecting against. Returns the manifest dict or None (never
    raises)."""
    path = os.path.join(ckpt_dir, MANIFEST_NAME)
    try:
        with open(path, "rb") as f:
            raw = f.read()
        manifest = json.loads(raw.decode("utf-8"))
    except (OSError, ValueError, UnicodeDecodeError):
        return None
    if not isinstance(manifest, dict) or \
            manifest.get("format") != MANIFEST_FORMAT:
        return None
    shards = manifest.get("shards")
    if not isinstance(shards, list) or not shards:
        return None
    for shard in shards:
        try:
            spath = os.path.join(ckpt_dir, shard["file"])
            if not deep:
                if os.stat(spath).st_size != int(shard["bytes"]):
                    return None
                continue
            with open(spath, "rb") as f:
                data = f.read()
            if len(data) != int(shard["bytes"]):
                return None
            if crc32c(data) != int(shard["crc32c"]):
                return None
        except (OSError, KeyError, TypeError, ValueError):
            return None
    return manifest


def latest_valid_manifest(directory, deep=True):
    """Scans newest-first and returns (manifest, ckpt_dir) for the
    newest checkpoint whose manifest AND every shard validate; (None,
    None) when nothing valid exists. A torn manifest, a missing shard,
    or a flipped bit simply moves the scan to the next-older
    candidate. `deep=False` validates names/sizes only (report-style
    callers; the restore path verifies CRCs on its single read via
    load_leaves(verify=True))."""
    for step, gen, path in list_checkpoints(directory):
        manifest = validate_manifest(path, deep=deep)
        if manifest is not None:
            return manifest, path
    return None, None


def load_leaves(manifest, ckpt_dir, verify=False):
    """Reads every shard of a checkpoint and returns the full
    {path: leaf} dict (rank-0 side of the restore). With `verify`,
    checks each shard's manifested byte size and CRC32C on the SAME
    read (raising ValueError on mismatch) — so restore pays one pass
    over the bytes, not a deep-validate pass plus a load pass."""
    leaves = {}
    for shard in manifest["shards"]:
        with open(os.path.join(ckpt_dir, shard["file"]), "rb") as f:
            data = f.read()
        if verify:
            if len(data) != int(shard["bytes"]):
                raise ValueError("shard %s: %d bytes, manifest says %s"
                                 % (shard["file"], len(data),
                                    shard["bytes"]))
            if crc32c(data) != int(shard["crc32c"]):
                raise ValueError("shard %s: CRC mismatch"
                                 % shard["file"])
        leaves.update(pickle.loads(data))
    return leaves


def prune_stale_tmp(directory):
    """Startup hygiene: removes ``*.tmp`` shards/manifests left by a
    crashed writer. Only safe when no writer is live (i.e. at job
    start, before the first durable commit). Returns the count."""
    removed = 0
    for step, gen, path in list_checkpoints(directory):
        try:
            names = os.listdir(path)
        except OSError:
            continue
        for name in names:
            if name.endswith(".tmp"):
                try:
                    os.remove(os.path.join(path, name))
                    removed += 1
                except OSError:
                    pass
    return removed


def prune_unrestorable(directory):
    """Startup hygiene, part two: removes checkpoint directories that do
    not validate (shallow: manifest + shard names/sizes) — unpublished
    leftovers (a crashed run renamed some shards but never the
    manifest) and torn ones. The point is not disk space: a RELAUNCHED
    run that trains back to the same (step, generation) would otherwise
    find a crashed predecessor's name-valid shard already in its
    directory and could splice it into a fresh manifest — a
    mixed-trajectory checkpoint whose every CRC validates. (Shallow is
    enough for the splice hazard: a content-corrupt shard that kept its
    size is caught by restore's verified read, and the publisher
    refuses ambiguous duplicate shards — deep-reading every byte of
    every checkpoint here would double every resume's I/O.) Same
    no-live-writer precondition as prune_stale_tmp; returns the removed
    directory names."""
    removed = []
    for step, gen, path in list_checkpoints(directory):
        if validate_manifest(path, deep=False) is None:
            try:
                shutil.rmtree(path)
                removed.append(os.path.basename(path))
            except OSError:
                pass
    return removed


def apply_retention(directory, keep=None):
    """Keeps the newest `keep` VALID checkpoints (HVD_TPU_CKPT_KEEP,
    default 3) and deletes everything older — including abandoned
    invalid directories older than the oldest kept checkpoint (a
    half-written step newer than the kept set is left alone: its
    writer may still be publishing). Returns removed dir names."""
    if keep is None:
        keep = int(os.environ.get("HVD_TPU_CKPT_KEEP", "3"))
    keep = max(1, keep)
    entries = list_checkpoints(directory)
    valid_seen = 0
    boundary = None  # (step, gen) of the oldest kept valid checkpoint
    removed = []
    for step, gen, path in entries:
        if valid_seen < keep:
            # Shallow check: names/sizes only. Deep-CRC'ing the newest
            # K checkpoints on EVERY publish would re-read ~K full
            # state copies per write against the store being protected.
            if validate_manifest(path, deep=False) is not None:
                valid_seen += 1
                boundary = (step, gen)
            continue
        # Beyond the kept set: every older dir goes, valid or not.
        if boundary is not None and (step, gen) < boundary:
            try:
                shutil.rmtree(path)
                removed.append(os.path.basename(path))
            except OSError:
                pass
    return removed


# ---------------------------------------------------------------------------
# Metrics plumbing (native registry; soft-fails when the core is absent)

def _ckpt_metrics(writes=0, failures=0, nbytes=0, restores=0,
                  restore_failures=0, last_step=-1, write_seconds=-1.0):
    try:
        from horovod_tpu.common.basics import get_basics
        get_basics().ckpt_metrics(writes, failures, nbytes, restores,
                                  restore_failures, last_step,
                                  write_seconds)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# The async sharded writer

class DurableCheckpointer:
    """Background durable-snapshot writer for one rank.

    ``maybe_enqueue`` is called from ``State.commit()`` with the
    *already deep-copied* host snapshot (``State.save()`` replaces the
    committed dict wholesale, so the reference handed here is immutable
    from the trainer's perspective — zero extra copies on the commit
    path). The writer thread serializes this rank's shard, fsyncs,
    renames; rank 0 additionally waits for the other shards and
    publishes the manifest.
    """

    def __init__(self, directory, every_n_commits=None, interval_s=None,
                 fault_spec=None, rank=None, world_size=None,
                 publish_timeout=None):
        self.directory = os.path.abspath(directory)
        if every_n_commits is None:
            every_n_commits = int(os.environ.get(
                "HVD_TPU_CKPT_EVERY_N_COMMITS", "1"))
        if interval_s is None:
            raw = os.environ.get("HVD_TPU_CKPT_INTERVAL_S")
            interval_s = float(raw) if raw else None
        self.every_n_commits = max(1, int(every_n_commits))
        self.interval_s = interval_s
        self._publish_timeout = publish_timeout if publish_timeout \
            is not None else float(os.environ.get(
                "HVD_TPU_CKPT_PUBLISH_TIMEOUT", "120"))
        self._retries = int(os.environ.get("HVD_TPU_CKPT_RETRIES", "3"))
        self._commit_index = 0
        self._sticky_every = max(1, int(os.environ.get(
            "HVD_TPU_CKPT_STICKY_EVERY", "8")))
        self._last_bucket = None
        self._last_step_bucket = None
        self._rank_override = rank
        self._size_override = world_size
        self.last_durable_step = -1

        if fault_spec is None:
            fault_spec = os.environ.get("HVD_TPU_CKPT_FAULT_SPEC", "")
        self._injector = CkptFaultInjector(fault_spec, self._rank())

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # Three-slot queue (bounded at 3 buffered snapshots, never more):
        # the sticky slots hold STICKY snapshots — rank-deterministic
        # 1-in-sticky_every commits that every rank writes and that
        # newer non-sticky snapshots may not displace. Without them,
        # each rank's latest-wins skipping follows its own writer
        # timing, and two ranks under storage slower than the commit
        # cadence can stably anti-align (rank 0 landing only even
        # steps, rank 1 only odd) so that NO manifest ever publishes
        # mid-run. `_sticky_head` is the OLDEST unwritten sticky and is
        # never displaced by anything: its capture is decided at
        # enqueue time (commit-driven, identical on every rank), not by
        # when this rank's writer happens to wake — so the first sticky
        # after any drained period is guaranteed durable on EVERY rank,
        # scheduler timing notwithstanding. `_sticky_next` is
        # latest-wins among the stickies that arrive while the head is
        # still unwritten. `_pending` holds the newest snapshot
        # overall, so the most recent commit still always becomes
        # durable once the writer drains (clean-exit flush included).
        self._sticky_head = None
        self._sticky_next = None
        self._pending = None   # newest (snapshot, step, gen, rank, size)
        self._inflight = False
        self._stop = False
        self._thread = None

    # -- topology ---------------------------------------------------------
    def _rank(self):
        if self._rank_override is not None:
            return self._rank_override
        try:
            import horovod_tpu as hvd
            if hvd.is_initialized():
                return hvd.rank()
        except Exception:
            pass
        return int(os.environ.get("HVD_TPU_RANK", "0") or 0)

    def _size(self):
        if self._size_override is not None:
            return self._size_override
        try:
            import horovod_tpu as hvd
            if hvd.is_initialized():
                return hvd.size()
        except Exception:
            pass
        return int(os.environ.get("HVD_TPU_SIZE", "1") or 1)

    @staticmethod
    def _generation():
        return int(os.environ.get("HVD_TPU_GENERATION", "0") or 0)

    # -- trigger ----------------------------------------------------------
    def _due(self, now, step):
        """(due, sticky) for THIS commit. Both decisions must be
        RANK-UNIFORM — every rank has to write the same durable steps
        (else rank 0's manifests wait on shards nobody writes), and the
        same sticky steps (or the convergence anchor fails in exactly
        the slow-storage regime it exists for). So neither may derive
        from the process-local commit counter: an elastic replacement
        joining mid-run starts its counter at 0 while survivors are
        further along, offsetting the cadences for the rest of the run.
        Counter mode therefore keys on the state's `step` value
        (broadcast by sync(), identical everywhere including mid-job
        joiners); interval mode on absolute wall-clock bucket numbers
        (shared epoch; a boundary disagreement costs one abandoned
        manifest attempt, never a hang or a bad checkpoint). States
        without an integer ``step`` attribute fall back to the commit
        counter and get rank-uniformity only for workers that started
        together — documented in docs/ELASTIC.md."""
        first = self._commit_index == 0
        self._commit_index += 1
        if self.interval_s is not None:
            bucket = int(now / self.interval_s)
            sticky = first or bucket % self._sticky_every == 0
            if self._last_bucket is None:
                self._last_bucket = bucket
                return first, sticky
            if bucket > self._last_bucket:
                self._last_bucket = bucket
                return True, sticky
            return False, False
        # Step-bucket rule, not `step % stride == 0`: a commit cadence
        # whose step values never land on a stride multiple (commits at
        # steps 3, 8, 13, ... with stride 10) would otherwise silently
        # disable durability. A bucket CHANGE fires on the first commit
        # in each stride-sized window of steps — rank-uniform because
        # every rank commits the same step sequence. (A mid-job joiner's
        # very first commit may fire alone mid-bucket; its lone shard
        # becomes a manifest-less dir swept at the next startup prune.)
        bucket = step // self.every_n_commits
        due = bucket != self._last_step_bucket
        sticky = due and (self._last_step_bucket is None or
                          bucket % self._sticky_every == 0)
        if due:
            self._last_step_bucket = bucket
        return due, sticky

    # -- enqueue (trainer thread; never blocks on storage) ----------------
    def maybe_enqueue(self, committed, step):
        """Called under commit(). Hands the snapshot to the writer when
        this commit is due; replaces any not-yet-started pending
        snapshot (storage slower than the commit cadence skips
        intermediate snapshots instead of queueing them). Every
        sticky_every-th due commit goes to the sticky slot instead —
        commit-counter-deterministic, so every rank writes those exact
        steps and rank 0's manifests converge even when rank-local
        skipping anti-aligns (see the slot comments in __init__)."""
        if committed is None:
            return False
        step = int(step)
        due, sticky = self._due(time.time(), step)
        if not due:
            return False
        job = (committed, step, self._generation(), self._rank(),
               self._size(), sticky)
        with self._cv:
            if sticky:
                if self._sticky_head is None:
                    self._sticky_head = job
                else:
                    # The head is pinned until written; newer stickies
                    # are latest-wins among themselves.
                    self._sticky_next = job
            else:
                self._pending = job
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._writer_loop, name="hvd-durable-ckpt",
                    daemon=True)
                self._thread.start()
            self._cv.notify()
        return True

    def force_enqueue(self, committed, step):
        """Unconditional enqueue into the STICKY slot, bypassing the
        ``_due`` cadence — the graceful-drain path (docs/FLEET.md):
        every rank force-writes the drained step's shard regardless of
        its local skip/cadence state, so the manifest for exactly the
        drained commit completes (not an older sticky anchor). Sticky
        placement means the publisher waits its full timeout and a
        racing non-sticky snapshot cannot displace it."""
        if committed is None:
            return False
        step = int(step)
        job = (committed, step, self._generation(), self._rank(),
               self._size(), True)
        with self._cv:
            # The drain is the job's final commit: land it in the
            # latest-wins sticky slot (behind any pinned unwritten
            # anchor, which the writer drains first anyway).
            if self._sticky_head is None:
                self._sticky_head = job
            else:
                self._sticky_next = job
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._writer_loop, name="hvd-durable-ckpt",
                    daemon=True)
                self._thread.start()
            self._cv.notify()
        # Keep the cadence bookkeeping coherent: the drained step's
        # window counts as written, so a post-drain survivor does not
        # immediately double-write it.
        self._last_step_bucket = step // self.every_n_commits
        return True

    def _take_pending_locked(self):
        """Next job for the writer: sticky slots first, oldest first
        (they are always older than the newest snapshot), then the
        newest snapshot."""
        if self._sticky_head is not None:
            job = self._sticky_head
            self._sticky_head = self._sticky_next
            self._sticky_next = None
            return job
        job = self._pending
        self._pending = None
        return job

    def _has_pending_locked(self):
        return self._pending is not None or \
            self._sticky_head is not None

    def flush(self, timeout=None):
        """Blocks until the writer has drained (pending + in-flight).
        Called at clean training exit so the final commit is durable;
        also the test hook. Returns True when drained."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._has_pending_locked() or self._inflight:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                self._cv.wait(remaining if remaining is not None else 1.0)
        return True

    def stop(self):
        with self._cv:
            self._stop = True
            self._cv.notify()

    # -- writer thread -----------------------------------------------------
    def _writer_loop(self):
        while True:
            with self._cv:
                while not self._has_pending_locked() and not self._stop:
                    self._cv.wait(1.0)
                if self._stop and not self._has_pending_locked():
                    return
                job = self._take_pending_locked()
                self._inflight = True
            try:
                self._write_with_retries(*job)
            finally:
                with self._cv:
                    self._inflight = False
                    self._cv.notify_all()

    def _write_with_retries(self, committed, step, generation, rank,
                            world_size, sticky=False):
        backoff = 0.1
        for attempt in range(self._retries + 1):
            try:
                t0 = time.monotonic()
                nbytes, durable = self._write_snapshot(
                    committed, step, generation, rank, world_size,
                    sticky=sticky)
                dt = time.monotonic() - t0
                if not durable:
                    # Abandoned publish: the failure was already logged
                    # and counted inside _publish_manifest; claiming the
                    # write would advance the recovery point past what a
                    # restore can actually find.
                    return False
                # Monotonic max, mirroring the native gauge's CAS: the
                # two-slot queue can legally write a displaced older
                # snapshot AFTER a newer sticky one.
                self.last_durable_step = max(self.last_durable_step,
                                             step)
                _ckpt_metrics(writes=1, nbytes=nbytes, last_step=step,
                              write_seconds=dt)
                return True
            except OSError as e:
                if attempt >= self._retries:
                    _log("durable write for step %d FAILED after %d "
                         "attempts (%s); training continues, last "
                         "durable step remains %d"
                         % (step, attempt + 1, e, self.last_durable_step))
                    _ckpt_metrics(failures=1)
                    return False
                time.sleep(backoff)
                backoff = min(backoff * 2, 5.0)
            except Exception as e:
                # Non-storage failure (e.g. an unpicklable state leaf):
                # deterministic, so retrying cannot help — degrade
                # immediately. Catching it HERE keeps the writer thread
                # alive for later (possibly fixed) snapshots; letting it
                # escape would kill the thread and silently disable
                # durability for the rest of the run.
                _log("durable write for step %d FAILED (%s: %s); "
                     "training continues, last durable step remains %d"
                     % (step, type(e).__name__, e,
                        self.last_durable_step))
                _ckpt_metrics(failures=1)
                return False

    def _write_snapshot(self, committed, step, generation, rank,
                        world_size, sticky=False):
        """One rank's durable write: serialize this rank's leaves,
        atomic-write the shard; on rank 0, wait for the sibling shards
        and publish the manifest. Returns (bytes_written, durable):
        durable is False when rank 0 had to abandon the manifest — the
        step is NOT recoverable and must not advance last_durable_step
        or the write counters (the operator would be told a recovery
        point that does not exist)."""
        ckpt_dir = os.path.join(self.directory,
                                _ckpt_dirname(step, generation))
        os.makedirs(ckpt_dir, exist_ok=True)

        items = _leaf_items(committed)
        mine = {path: leaf for i, (path, leaf) in enumerate(items)
                if i % world_size == rank}
        payload = pickle.dumps(mine, protocol=4)
        crc = crc32c(payload)
        shard = _shard_name(rank, world_size, crc, len(payload))
        _atomic_write(os.path.join(ckpt_dir, shard), payload,
                      injector=self._injector, op="shard")

        durable = True
        if rank == 0:
            # Only the publishing rank can know whether the step became
            # restorable; non-zero ranks report shard-level durability
            # (rank 0's gauge is the authoritative recovery point).
            durable = self._publish_manifest(ckpt_dir, step, generation,
                                             world_size,
                                             sorted(committed),
                                             sticky=sticky)
        return len(payload), durable

    def _publish_manifest(self, ckpt_dir, step, generation, world_size,
                          attrs, sticky=False):
        """Rank 0: wait until all `world_size` shards of this step have
        been renamed into place (their names carry size+CRC, so no
        cross-rank channel is needed), then atomically publish the
        manifest. A missing shard past the timeout — or past the moment
        a NEWER snapshot is already pending (latest-wins applies to
        publishing too: when storage outpacing makes ranks skip
        different steps, waiting the full timeout per divergent step
        would serialize the writer on dead waits) — abandons the
        attempt with a warning; the next durable commit retries from
        scratch. STICKY steps are exempt from the newer-pending early
        abandon: every rank is guaranteed to write them, so waiting is
        productive and their publish is what bounds how long the job
        can run with zero durable progress."""
        deadline = time.monotonic() + self._publish_timeout
        while True:
            shards = {}
            duplicates = []
            try:
                names = os.listdir(ckpt_dir)
            except OSError:
                names = []
            for name in names:
                m = _SHARD_RE.match(name)
                if m and int(m.group(2)) == world_size:
                    r = int(m.group(1))
                    if r in shards:
                        duplicates.append(name)
                        continue
                    shards[r] = {
                        "file": name,
                        "crc32c": int(m.group(3), 16),
                        "bytes": int(m.group(4)),
                    }
            if duplicates:
                # Two same-rank shards with different content can only
                # mean leftovers from another run's trajectory landed in
                # this directory; guessing would publish a manifest
                # mixing trajectories with every CRC valid. Refuse.
                _log("abandoning manifest for %s: ambiguous duplicate "
                     "shard(s) %s" % (os.path.basename(ckpt_dir),
                                      duplicates))
                _ckpt_metrics(failures=1)
                return False
            if len(shards) >= world_size:
                break
            newer_pending = False
            if not sticky:
                with self._lock:
                    newer_pending = self._has_pending_locked()
            if newer_pending or time.monotonic() > deadline:
                missing = sorted(set(range(world_size)) - set(shards))
                _log("abandoning manifest for %s: shard(s) %s missing "
                     "%s" % (os.path.basename(ckpt_dir), missing,
                             "and a newer snapshot is pending"
                             if newer_pending else
                             "after %.0fs" % self._publish_timeout))
                _ckpt_metrics(failures=1)
                return False
            time.sleep(0.05)

        manifest = {
            "format": MANIFEST_FORMAT,
            "step": step,
            "generation": generation,
            "world_size": world_size,
            "attrs": attrs,
            "created_unix": time.time(),
            "shards": [shards[r] for r in sorted(shards)][:world_size],
        }
        data = json.dumps(manifest, indent=1).encode("utf-8")
        _atomic_write(os.path.join(ckpt_dir, MANIFEST_NAME), data,
                      injector=self._injector, op="manifest")
        apply_retention(self.directory)
        return True

    # -- restore (rank 0 reads; caller broadcasts via State.sync) ---------
    def restore_into(self, state):
        """Rank-0 side of auto-resume: loads the newest valid manifest's
        leaves into `state`'s attributes (using the state's CURRENT
        structure as the template) and returns the restored step, or
        None when no valid checkpoint exists / the structure does not
        match. The caller must follow with ``state.sync()`` so every
        other rank — any world size — receives the values over the
        broadcast plane."""
        prune_stale_tmp(self.directory)
        removed = prune_unrestorable(self.directory)
        if removed:
            _log("pruned %d unrestorable checkpoint dir(s) left by a "
                 "previous run: %s" % (len(removed), removed[:5]))
        # Newest-first with CRC verification folded into the single
        # shard read (not a deep-validate pass PLUS a load pass): a
        # content-corrupt checkpoint surfaces as a ValueError here and
        # the scan silently falls back to the next-older candidate.
        for step, gen, ckpt_dir in list_checkpoints(self.directory):
            manifest = validate_manifest(ckpt_dir, deep=False)
            if manifest is None:
                continue
            try:
                leaves = load_leaves(manifest, ckpt_dir, verify=True)
            except Exception as e:
                _log("checkpoint %s failed verification (%s); falling "
                     "back to an older one"
                     % (os.path.basename(ckpt_dir), e))
                _ckpt_metrics(restore_failures=1)
                continue
            try:
                current = state._public()
                flat = _tree_flatten(current)
                missing = [p for p, _ in flat if p not in leaves]
                if missing or len(flat) != len(leaves):
                    # Fall back like any other validation failure: a
                    # foreign/renamed-attribute checkpoint as the newest
                    # entry must not shadow an older one that matches
                    # this state exactly.
                    _log("checkpoint %s does not match the state's "
                         "structure (%d saved leaves vs %d "
                         "registered%s); falling back to an older one"
                         % (os.path.basename(ckpt_dir), len(leaves),
                            len(flat),
                            ", missing %s" % missing[:3]
                            if missing else ""))
                    _ckpt_metrics(restore_failures=1)
                    continue
                rebuilt = _tree_map_leaves(
                    current, iter([leaves[p] for p, _ in flat]))
                for k, v in rebuilt.items():
                    setattr(state, k, v)
                _ckpt_metrics(restores=1,
                              last_step=int(manifest["step"]))
                self.last_durable_step = int(manifest["step"])
                _log("restored step %d from %s (saved world size %d)"
                     % (manifest["step"], os.path.basename(ckpt_dir),
                        manifest["world_size"]))
                return int(manifest["step"])
            except Exception as e:
                # setattr/rebuild blew up half way — the state may hold
                # a partial mix of old and restored attributes, so
                # falling back to restore an OLDER checkpoint on top
                # could compound the damage. Start fresh, loudly.
                _log("restore from %s failed (%s); starting fresh"
                     % (ckpt_dir, e))
                _ckpt_metrics(restore_failures=1)
                return None
        return None


def last_durable_step(directory):
    """(step, ckpt_dir) of the newest valid checkpoint under
    `directory`, or (None, None) — the launcher failure summary's
    "what would a restart recover" report. Shallow validation: this is
    a log-line input, not a restore (which re-verifies CRCs on its own
    read anyway), so it must not re-read every checkpoint byte inside
    a teardown path."""
    manifest, path = latest_valid_manifest(directory, deep=False)
    if manifest is None:
        return None, None
    return int(manifest["step"]), path


def describe_last_durable(directory):
    """One operator-facing sentence: what a relaunch pointed at this
    checkpoint directory recovers. Shared by the static launcher's
    failure summary and the elastic driver's teardown report so the
    wording (and the definition of "durable") cannot drift between
    them."""
    step, path = last_durable_step(directory)
    if step is None:
        return ("no valid durable checkpoint under %s; a relaunch "
                "starts from scratch" % directory)
    return ("last durable checkpoint: step %d (%s); a relaunch with "
            "the same checkpoint directory resumes there"
            % (step, path))
