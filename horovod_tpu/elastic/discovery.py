"""Host discovery + failure blacklisting for elastic jobs.

Reference analogue: ``horovod/run/elastic/discovery.py`` (HostDiscovery /
HostDiscoveryScript / HostManager with host blacklisting); fresh
implementation. The discovery contract: a source of truth (usually a
user script) reports the currently-available hosts as ``host:slots``
lines; the driver diffs successive readings to grow or shrink the job.

Blacklisting differs from the reference's permanent blacklist: failures
here carry an **exponential backoff** (base cooldown doubling per
consecutive failure), because on TPU pods preempted hosts routinely come
back — a permanent blacklist would turn every transient preemption into
a permanent capacity loss.
"""

import subprocess
import time


def plan_spawns(available, live_per_host, room, placement="pack"):
    """Hosts to spawn new workers on, one list entry per worker — the
    pure placement rule shared by the single-job elastic driver's
    growth path and the fleet controller's pool
    (``horovod_tpu/fleet/placement.py`` re-exports it).

    ``available``: {host: slots} — the spawnable inventory (already
    blacklist-filtered). ``live_per_host``: {host: live worker count}.
    ``room``: how many more workers may be added.

    ``placement`` picks the shape (docs/FLEET.md "Placement"):

    * ``"pack"`` (default, the historical rule) fills hosts densely in
      sorted order — training gangs want locality (intra-host data
      plane, shared-memory composites).
    * ``"spread"`` places each worker on the least-occupied host with a
      free slot (ties by name) — serve replicas want failure-domain
      diversity: one host dying must not take the whole pool's
      capacity with it.

    Either way hosts are walked deterministically, so the plan agrees
    across supervisors."""
    if room <= 0:
        return []
    if placement not in ("pack", "spread"):
        raise ValueError("unknown placement %r (pack|spread)"
                         % (placement,))
    plan = []
    if placement == "spread":
        occupancy = dict(live_per_host)
        while len(plan) < room:
            candidates = [(occupancy.get(h, 0), h)
                          for h, slots in sorted(available.items())
                          if occupancy.get(h, 0) < slots]
            if not candidates:
                break
            _, host = min(candidates)
            plan.append(host)
            occupancy[host] = occupancy.get(host, 0) + 1
        return plan
    for host, slots in sorted(available.items()):
        free = slots - live_per_host.get(host, 0)
        for _ in range(max(0, free)):
            if len(plan) >= room:
                return plan
            plan.append(host)
    return plan


class HostDiscovery:
    """Interface: report the currently-available hosts."""

    def find_available_hosts_and_slots(self):
        """Returns {hostname: slots}."""
        raise NotImplementedError


class FixedHosts(HostDiscovery):
    """Static host set (the non-discovery case, e.g. plain ``-H``)."""

    def __init__(self, hosts):
        # hosts: {hostname: slots} or a "h1:2,h2:2" string.
        if isinstance(hosts, str):
            from horovod_tpu.run.util import parse_hosts
            hosts = {h.hostname: h.slots for h in parse_hosts(hosts)}
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self):
        return dict(self._hosts)


class HostDiscoveryScript(HostDiscovery):
    """Runs a user script that prints one ``host`` or ``host:slots`` line
    per available host (the reference's ``--host-discovery-script``
    contract). A non-zero exit or unparseable output reads as "no
    change" (the previous host set is kept) — a flaky discovery script
    must not shrink a healthy job."""

    def __init__(self, script, default_slots=1, timeout=10):
        self._script = script
        self._default_slots = default_slots
        self._timeout = timeout
        self._last = {}

    def find_available_hosts_and_slots(self):
        try:
            out = subprocess.run(
                self._script, shell=True, capture_output=True, text=True,
                timeout=self._timeout)
        except (subprocess.TimeoutExpired, OSError):
            return dict(self._last)
        if out.returncode != 0:
            return dict(self._last)
        hosts = {}
        for line in out.stdout.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if ":" in line:
                name, _, slots = line.rpartition(":")
                try:
                    hosts[name] = int(slots)
                except ValueError:
                    continue
            else:
                hosts[line] = self._default_slots
        if not hosts:
            # Exit 0 with empty/unparseable output gets the same benefit
            # of the doubt as a crash: "no hosts at all" would shrink a
            # healthy job below --min-np and tear it down, and a flaky
            # script racing its data source must not cause that. A truly
            # empty fleet surfaces through worker failures instead.
            return dict(self._last)
        self._last = dict(hosts)
        return hosts


class HostManager:
    """Tracks the available host set and per-host failure blacklisting.

    A host that causes a worker failure is blacklisted for
    ``cooldown * 2**(consecutive_failures - 1)`` seconds (capped at
    ``max_backoff``); it is not retried before the backoff expires, and
    a success (a worker on the host outliving ``success_after``) resets
    the streak. ``clock`` is injectable for deterministic tests."""

    def __init__(self, discovery, cooldown=10.0, max_backoff=600.0,
                 clock=time.monotonic):
        self._discovery = discovery
        self._cooldown = cooldown
        self._max_backoff = max_backoff
        self._clock = clock
        self._current = {}
        # host -> (consecutive_failures, blacklisted_until, failed_at)
        self._failures = {}

    def refresh(self):
        """Re-reads discovery; returns True when the raw host set (before
        blacklist filtering) changed."""
        hosts = self._discovery.find_available_hosts_and_slots()
        changed = hosts != self._current
        self._current = hosts
        return changed

    def record_failure(self, host):
        count, _, _ = self._failures.get(host, (0, 0.0, 0.0))
        count += 1
        now = self._clock()
        backoff = min(self._cooldown * (2 ** (count - 1)),
                      self._max_backoff)
        self._failures[host] = (count, now + backoff, now)

    def record_release(self, host):
        """A worker on `host` exited VOLUNTARILY — planned drain,
        preemption hand-back, controller-requested shrink. Unlike
        :meth:`record_failure` this must NOT start (or extend) the
        backoff blacklist: a drained host is healthy by definition and
        re-enters the spawnable pool immediately. It is not success
        evidence either — a pre-existing failure streak (from an
        earlier real crash) keeps its cooldown untouched, so a flaky
        host can't launder its blacklist through a planned drain."""
        # Deliberately records nothing: voluntary exit is neither
        # failure evidence nor post-failure health proof.

    def record_success(self, host, started_at=None):
        """Clears the failure streak — but only on evidence that
        POSTDATES the last failure: a worker that was already running
        when the host failed proves nothing about the host now (without
        this guard, one long-lived survivor on a multi-slot host would
        wipe a fresh blacklist entry and defeat the backoff)."""
        ent = self._failures.get(host)
        if ent is None:
            return
        if started_at is not None and started_at <= ent[2]:
            return
        self._failures.pop(host, None)

    def reset(self):
        """Clears every failure streak and blacklist entry. Used by the
        driver's full-job checkpoint restart (--restart-from-ckpt): the
        restart is a clean slate — a host whose backoff window was the
        reason the world fell below --min-np must be retriable by the
        relaunched job, exactly as it would be by an operator-driven
        restart."""
        self._failures = {}

    def is_blacklisted(self, host):
        ent = self._failures.get(host)
        return ent is not None and self._clock() < ent[1]

    def blacklisted_until(self, host):
        ent = self._failures.get(host)
        return ent[1] if ent else 0.0

    def available_hosts_and_slots(self):
        """The discovered host set minus currently-blacklisted hosts."""
        return {h: s for h, s in self._current.items()
                if not self.is_blacklisted(h)}
