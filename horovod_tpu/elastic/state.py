"""Elastic state: committable, restorable, rank-0-syncable training state.

Reference analogue: ``horovod/common/elastic.py`` (``State`` /
``ObjectState`` and the framework TensorState subclasses); fresh
implementation over host numpy so it works for every binding (the JAX
flagship hands in pytrees of arrays; jnp arrays round-trip through
``np.asarray``).

Semantics (see docs/ELASTIC.md):

* ``commit()`` — snapshot every registered attribute to host memory
  (deep copy), then check for a pending membership change. A commit is
  the rollback point: after a peer failure the job resumes from the
  LAST COMMIT, so commit frequency trades checkpoint cost against lost
  work (exactly the reference's contract).
* ``restore()`` — load the last committed snapshot back into the
  attributes (called by the ``@run`` wrapper on ``HorovodInternalError``).
* ``sync()`` — broadcast every attribute from rank 0 over the host core
  (called after every (re)initialization so rejoining or fresh workers
  adopt the survivors' state, and survivors agree bit-for-bit).
"""

import copy
import json
import os
import time

import numpy as np

SCOPE_ELASTIC = "elastic"
KEY_STATE = "state"
# Graceful-drain protocol (docs/FLEET.md): the driver/launcher publishes
# a drain request here; workers notice it at their next commit, force a
# durable snapshot of exactly that commit, and the victims exit with
# EXIT_DRAINED so supervisors can tell a planned hand-back from a crash.
KEY_DRAIN = "drain"
EXIT_DRAINED = 83


def _tree_flatten(obj, path=""):
    """Flattens nested dict/list/tuple containers to [(path, leaf)] with a
    deterministic order (dict keys sorted) so every rank names leaves
    identically during sync broadcasts."""
    if isinstance(obj, dict):
        out = []
        for k in sorted(obj, key=str):
            out.extend(_tree_flatten(obj[k], "%s.%s" % (path, k)))
        return out
    if isinstance(obj, (list, tuple)):
        out = []
        for i, v in enumerate(obj):
            out.extend(_tree_flatten(v, "%s.%d" % (path, i)))
        return out
    return [(path, obj)]


def _tree_map_leaves(obj, leaves_iter):
    """Rebuilds `obj`'s structure taking leaves from `leaves_iter` in the
    same deterministic order _tree_flatten produces."""
    if isinstance(obj, dict):
        items = {k: _tree_map_leaves(obj[k], leaves_iter)
                 for k in sorted(obj, key=str)}
        return {k: items[k] for k in obj}  # preserve original key order
    if isinstance(obj, (list, tuple)):
        vals = [_tree_map_leaves(v, leaves_iter) for v in obj]
        if isinstance(obj, tuple):
            # NamedTuples (optax optimizer states, flax structs) take
            # positional fields, not an iterable.
            return type(obj)(*vals) if hasattr(obj, "_fields") \
                else tuple(vals)
        return vals
    return next(leaves_iter)


class DrainRequested(Exception):
    """Raised from ``commit()`` when a graceful-drain request covers
    this process (or a peer): the snapshot for the current step has
    already been saved, the agreement allreduce has confirmed every
    rank raises at the SAME step, and the ``@run`` wrapper now forces
    a durable write of exactly this commit before the victims exit
    with ``EXIT_DRAINED`` (survivors re-initialize without rollback).

    ``victims`` is ``"all"`` or a list of worker-id strings; ``epoch``
    is the drain request's sequence number; ``grace`` the seconds the
    supervisor allows before it escalates to a hard kill."""

    def __init__(self, victims, epoch, grace):
        super().__init__("drain requested (epoch %s, victims %s)"
                         % (epoch, victims))
        self.victims = victims
        self.epoch = epoch
        self.grace = grace


class HostsUpdatedInterrupt(Exception):
    """Raised from ``commit()``/``check_host_updates()`` when the driver
    published a newer generation (a host joined or was removed
    gracefully). The ``@run`` wrapper catches it and re-initializes
    WITHOUT rolling back (current state is still globally consistent)."""

    def __init__(self, generation):
        super().__init__("membership changed: generation %d" % generation)
        self.generation = generation


def _poll_published_generation():
    """The driver-published generation number, or None outside elastic
    mode / on any rendezvous hiccup (a missed poll must never take down
    a healthy training loop)."""
    addr = os.environ.get("HVD_TPU_RENDEZVOUS_ADDR")
    if os.environ.get("HVD_TPU_ELASTIC") != "1" or not addr:
        return None
    from horovod_tpu.run import rendezvous
    try:
        raw = rendezvous.get(addr, SCOPE_ELASTIC, KEY_STATE)
        if raw is None:
            return None
        return int(json.loads(raw.decode())["generation"])
    except Exception:
        return None


class State:
    """Base: non-underscore attributes set on the object are elastic
    state (underscore names are reserved for the machinery)."""

    def __init__(self, **kwargs):
        self._committed = None
        self._durable = None
        self._last_check = 0.0
        self._check_interval = float(
            os.environ.get("HVD_TPU_ELASTIC_CHECK_INTERVAL", "0.5"))
        for k, v in kwargs.items():
            if k.startswith("_"):
                raise ValueError(
                    "elastic state attribute %r: underscore names are "
                    "reserved" % k)
            setattr(self, k, v)

    def _public(self):
        return {k: v for k, v in self.__dict__.items()
                if not k.startswith("_")}

    # -- commit / restore --------------------------------------------------
    def save(self):
        """Snapshots the current attribute values (host copy).

        One owned copy per leaf: array-likes (device or numpy) land in a
        fresh host buffer via np.array, everything else is deepcopied —
        and the containers are rebuilt fresh by _tree_map_leaves, so the
        commit hot path pays a single pass over the state instead of the
        asarray+deepcopy double copy it used to."""
        def conv(leaf):
            if hasattr(leaf, "__array__"):
                return np.array(leaf)
            return copy.deepcopy(leaf)

        def snapshot(value):
            leaves = iter([conv(l) for _, l in _tree_flatten(value)])
            return _tree_map_leaves(value, leaves)

        self._committed = {
            k: snapshot(v) for k, v in self._public().items()}

    def commit(self):
        """save() + check_host_updates() — the reference's commit contract:
        the snapshot lands first, so a membership interrupt raised here
        still resumes from the state just committed. With durability
        enabled (``enable_durable``), every Nth commit also hands the
        snapshot to the background durable writer — by reference, since
        save() replaces the committed dict wholesale, so the commit hot
        path pays nothing beyond the existing deep copy."""
        self.save()
        if self._durable is not None:
            self._durable.maybe_enqueue(self._committed,
                                        self._durable_step())
        self.check_drain()
        self.check_host_updates()

    # -- durability (elastic/durable.py; docs/ELASTIC.md "Durability") -----
    def enable_durable(self, directory=None, every_n_commits=None,
                       interval_s=None, **kwargs):
        """Makes commits durable: every Nth ``commit()`` (or one per
        ``interval_s`` wall-clock window) is written asynchronously to
        `directory` as per-rank CRC-checksummed shards plus a rank-0
        manifest, surviving whole-job loss. `directory` defaults to
        ``HVD_TPU_CKPT_DIR`` (what ``horovodrun_tpu --ckpt-dir``
        plumbs). Returns the DurableCheckpointer."""
        from . import durable
        directory = directory or os.environ.get("HVD_TPU_CKPT_DIR")
        if not directory:
            raise ValueError(
                "enable_durable needs a directory (argument or "
                "HVD_TPU_CKPT_DIR / horovodrun_tpu --ckpt-dir)")
        self._durable = durable.DurableCheckpointer(
            directory, every_n_commits=every_n_commits,
            interval_s=interval_s, **kwargs)
        return self._durable

    @property
    def durable(self):
        """The active DurableCheckpointer, or None."""
        return self._durable

    def _durable_step(self):
        """The step number a durable snapshot is filed under: the
        state's own integer ``step`` attribute when present (the
        documented convention), else a monotonic commit counter."""
        step = getattr(self, "step", None)
        try:
            return int(step)
        except (TypeError, ValueError):
            return self._durable._commit_index

    def restore(self):
        """Loads the last committed snapshot back into the attributes."""
        if self._committed is None:
            return
        for k, v in self._committed.items():
            setattr(self, k, copy.deepcopy(v))

    @staticmethod
    def _to_host(value):
        """Materializes device arrays (jnp etc.) as host numpy; leaves
        plain containers/scalars untouched."""
        def conv(leaf):
            if hasattr(leaf, "__array__") and not isinstance(
                    leaf, np.ndarray):
                return np.asarray(leaf)
            return leaf
        leaves = iter([conv(l) for _, l in _tree_flatten(value)])
        return _tree_map_leaves(value, leaves)

    # -- graceful-drain polling (docs/FLEET.md) ----------------------------
    def check_drain(self):
        """Raises :class:`DrainRequested` when a drain request has been
        agreed across ranks. The agreement runs at EVERY commit of a
        drain-enabled job (``HVD_TPU_ELASTIC=1`` or
        ``HVD_TPU_DRAIN_ENABLE=1``) — a tiny rank-uniform indicator
        allreduce — so every rank raises at the same step and the
        forced durable snapshot is manifest-complete (all ranks write
        the drained step's shard). Commits being rank-uniform by the
        elastic contract is what makes the extra collective safe."""
        # NB: `from . import run` would grab the package attribute
        # `run` — the DECORATOR the package __init__ re-exports — not
        # the submodule; import the function explicitly.
        from .run import poll_drain_agreement
        agreed = poll_drain_agreement()
        if agreed is not None:
            raise DrainRequested(*agreed)

    # -- membership-change polling ----------------------------------------
    def check_host_updates(self):
        """Raises HostsUpdatedInterrupt when the driver published a newer
        generation than the one this process initialized under.
        Rate-limited (HVD_TPU_ELASTIC_CHECK_INTERVAL seconds) so the
        per-step cost is one monotonic-clock read."""
        now = time.monotonic()
        if now - self._last_check < self._check_interval:
            return
        self._last_check = now
        published = _poll_published_generation()
        if published is None:
            return
        current = int(os.environ.get("HVD_TPU_GENERATION", "0") or 0)
        if published > current:
            raise HostsUpdatedInterrupt(published)

    # -- cross-rank sync ---------------------------------------------------
    def sync(self, root_rank=0):
        """Broadcasts every registered attribute from `root_rank` over the
        host core. No-op at size 1. All ranks must hold structurally
        identical state (same tree, same leaf shapes/dtypes) — true by
        construction when every worker builds the state the same way."""
        import horovod_tpu as hvd
        from horovod_tpu.common import ops as _ops

        if not hvd.is_initialized() or hvd.size() <= 1:
            return
        state = self._public()
        flat = _tree_flatten(state)
        handles = []
        for path, leaf in flat:
            arr = np.ascontiguousarray(np.asarray(leaf))
            handles.append((path, leaf, arr, _ops.broadcast_async(
                arr, root_rank, "elastic_sync%s" % path)))
        synced = []
        for path, leaf, arr, h in handles:
            out = _ops.synchronize(h)
            if isinstance(leaf, np.ndarray) or (
                    hasattr(leaf, "__array__")
                    and not np.isscalar(leaf)):
                synced.append(np.asarray(out).reshape(np.shape(leaf)))
            elif isinstance(leaf, bool):
                synced.append(bool(np.asarray(out).reshape(())))
            elif isinstance(leaf, int):
                synced.append(int(np.asarray(out).reshape(())))
            elif isinstance(leaf, float):
                synced.append(float(np.asarray(out).reshape(())))
            else:
                synced.append(out)
        rebuilt = _tree_map_leaves(state, iter(synced))
        for k, v in rebuilt.items():
            setattr(self, k, v)


class ElasticState(State):
    """The concrete state users hand to ``@hvd.elastic.run``: any pytree
    of numpy/JAX arrays and python scalars passed as keyword arguments
    becomes a committable attribute, e.g.::

        state = hvd.elastic.ElasticState(params=params,
                                         opt_state=opt_state, step=0)

        @hvd.elastic.run
        def train(state):
            while state.step < total_steps:
                ...
                state.step += 1
                if state.step % 10 == 0:
                    state.commit()
    """
