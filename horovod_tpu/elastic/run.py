"""The ``@hvd.elastic.run`` decorator + worker-side elastic plumbing.

Reference analogue: ``horovod/common/elastic.py::run_fn`` (catch
HorovodInternalError -> restore committed state -> reinitialize -> resync;
catch HostsUpdatedInterrupt -> reinitialize without rollback); fresh
implementation over this repo's generation-numbered rendezvous.

Worker-side protocol (driver side in ``elastic/driver.py``):

* The driver publishes the current membership to the rendezvous KV at
  scope ``elastic`` key ``state``:
  ``{"generation": g, "size": n, "assignment": {worker_id: rank},
  "status": "running"|"shutdown"}``.
* ``bootstrap_topology()`` (called from ``hvd.init()`` when
  ``HVD_TPU_ELASTIC=1`` and no rank env is present) polls that key until
  this worker's id appears, then sets ``HVD_TPU_RANK/SIZE/GENERATION``;
  the normal dynamic rendezvous then runs in the generation's own scope.
* On ``HorovodInternalError`` the wrapper publishes a reinit request
  (scope ``elastic``, key ``reinit/<worker_id>``) so the driver bumps the
  generation promptly even when no process exited (e.g. a transport
  error), then waits for a generation NEWER than the one that failed.
"""

import functools
import json
import os
import sys
import time

from horovod_tpu.common.ops import HorovodInternalError

from .state import (EXIT_DRAINED, KEY_DRAIN, KEY_STATE, SCOPE_ELASTIC,
                    DrainRequested, HostsUpdatedInterrupt)

# Env keys owned by a single generation's topology; scrubbed before
# re-rendezvous so nothing stale leaks into the next generation.
_GENERATION_ENV_KEYS = (
    "HVD_TPU_RANK", "HVD_TPU_SIZE", "HVD_TPU_LOCAL_RANK",
    "HVD_TPU_LOCAL_SIZE", "HVD_TPU_CROSS_RANK", "HVD_TPU_CROSS_SIZE",
    "HVD_TPU_ADDRS",
)


def _log(msg):
    sys.stderr.write("[elastic] %s\n" % msg)
    sys.stderr.flush()


class JobCompleted(Exception):
    """The driver published status \"done\" (another worker finished the
    training) while this worker was waiting to (re)join a generation —
    there is nothing left to join. The ``@run`` wrapper treats it as a
    clean exit and returns None from the wrapped function."""


def _is_elastic():
    return os.environ.get("HVD_TPU_ELASTIC") == "1" and \
        os.environ.get("HVD_TPU_RENDEZVOUS_ADDR")


# ---------------------------------------------------------------------------
# Graceful drain (docs/FLEET.md): the supervisor (elastic driver, fleet
# controller, or the static launcher under --drain-grace) publishes a
# drain request at scope ``elastic`` key ``drain``::
#
#     {"epoch": n, "workers": "all" | ["3", "7"], "grace": seconds}
#
# Workers notice it at their next commit. Because ranks poll on their own
# clocks, the ACTION is synchronized with a 1-element indicator allreduce
# inside every commit of a drain-enabled job: every rank raises
# DrainRequested at the same step, every rank force-writes that step's
# durable shard (so the manifest completes), then the victims exit with
# EXIT_DRAINED and the survivors re-initialize without rollback.

_drain_state = {"done_epoch": 0, "last_poll": 0.0, "pending": None}


def _drain_poll_enabled():
    """Rank-uniform gate for the per-commit agreement allreduce: set at
    spawn time by the launcher/driver (never from a locally-observed
    event, which would be rank-divergent)."""
    return (os.environ.get("HVD_TPU_ELASTIC") == "1"
            or os.environ.get("HVD_TPU_DRAIN_ENABLE") == "1") and \
        bool(os.environ.get("HVD_TPU_RENDEZVOUS_ADDR"))


def _read_drain_record():
    addr = os.environ.get("HVD_TPU_RENDEZVOUS_ADDR")
    if not addr:
        return None
    from horovod_tpu.run import rendezvous
    try:
        raw = rendezvous.get(addr, SCOPE_ELASTIC, KEY_DRAIN)
        if raw is None:
            return None
        rec = json.loads(raw.decode())
        epoch = int(rec.get("epoch", 0))
        if rec.get("done"):
            # Tombstone of a completed epoch (the driver publishes it
            # once every victim exited): fast-forward so a replacement
            # that never lived through the drain does not act on it.
            _drain_state["done_epoch"] = max(_drain_state["done_epoch"],
                                             epoch)
            return None
        if epoch <= _drain_state["done_epoch"]:
            return None  # already honored (this process survived it)
        return rec
    except Exception:
        return None


def drain_requested():
    """Lightweight local poll: True when an unhonored drain request
    covering THIS worker is currently published. For custom training
    loops that cannot use ``ElasticState.commit()``; the commit path
    uses the synchronized agreement in :func:`poll_drain_agreement`."""
    if not _drain_poll_enabled():
        return False
    rec = _read_drain_record()
    if rec is None:
        return False
    victims = rec.get("workers", "all")
    wid = os.environ.get("HVD_TPU_WORKER_ID")
    return victims == "all" or (wid is not None and
                                str(wid) in [str(v) for v in victims])


def _drain_metrics(requested=0, draining=-2):
    """Best-effort native drain accounting (drains_requested_total
    counter + draining gauge ride the summary wire into /job and the
    hvd-top ``drn`` column). ``draining`` is absolute: 1 victim,
    0 survivor, -1 reset, < -1 leave unchanged."""
    try:
        from horovod_tpu.common.basics import get_basics
        get_basics().drain_metrics(requested, draining)
    except Exception:
        pass


def poll_drain_agreement():
    """Called from ``State.commit()``. Returns ``(victims, epoch,
    grace)`` when a drain has been agreed across ranks, else None.

    The local KV read is rate-limited (HVD_TPU_ELASTIC_CHECK_INTERVAL),
    but the indicator allreduce runs at EVERY commit when drain polling
    is enabled — it must be rank-uniform, and commits are the elastic
    contract's rank-uniform points. An agreement where this rank has
    not yet seen the record itself re-reads the KV synchronously (a
    peer proved the record exists)."""
    if not _drain_poll_enabled():
        return None
    st = _drain_state
    now = time.monotonic()
    interval = float(os.environ.get("HVD_TPU_ELASTIC_CHECK_INTERVAL",
                                    "0.5"))
    if st["pending"] is None and now - st["last_poll"] >= interval:
        st["last_poll"] = now
        st["pending"] = _read_drain_record()
    local = 1.0 if st["pending"] is not None else 0.0
    agreed = local
    import horovod_tpu as hvd
    if hvd.is_initialized() and hvd.size() > 1:
        import numpy as np
        out = hvd.allreduce(np.array([local], dtype=np.float64),
                            "_hvd_drain_poll")
        agreed = float(np.asarray(out).reshape(-1)[0])
    if agreed < 0.5:
        return None
    rec = st["pending"]
    if rec is None:
        # A peer saw the request first; the record is committed to the
        # KV (peers only learn of drains by reading it), so a short
        # bounded re-read closes the gap.
        deadline = time.monotonic() + 5.0
        while rec is None and time.monotonic() < deadline:
            rec = _read_drain_record()
            if rec is None:
                time.sleep(0.05)
    if rec is None:
        # Degraded: agreement fired but the record is unreadable. Not
        # acting keeps this rank safe either way — as a victim the
        # supervisor escalates at grace expiry, as a survivor the
        # peers' exits surface as a recoverable connection loss.
        _log("drain agreed by peers but the drain record is "
             "unreadable; continuing until the supervisor escalates")
        return None
    st["pending"] = None
    epoch = int(rec.get("epoch", 1))
    st["done_epoch"] = max(st["done_epoch"], epoch)
    return (rec.get("workers", "all"), epoch,
            float(rec.get("grace", 30.0)))


def current_generation():
    return int(os.environ.get("HVD_TPU_GENERATION", "0") or 0)


def _elastic_timeout():
    return float(os.environ.get(
        "HVD_TPU_ELASTIC_TIMEOUT",
        os.environ.get("HVD_TPU_START_TIMEOUT", "120")))


def fetch_assignment(addr, timeout, min_generation=0, worker_id=None):
    """Polls the driver-published membership until its generation reaches
    `min_generation` (and, when given, `worker_id` is assigned a rank).
    Raises RuntimeError on driver shutdown, TimeoutError on expiry."""
    from horovod_tpu.run import rendezvous

    deadline = time.monotonic() + timeout
    while True:
        info = None
        try:
            raw = rendezvous.get(addr, SCOPE_ELASTIC, KEY_STATE)
            if raw is not None:
                info = json.loads(raw.decode())
        except Exception:
            info = None
        if info is not None:
            if info.get("status") == "shutdown":
                raise RuntimeError(
                    "elastic driver is shutting down the job")
            if info.get("status") == "done":
                raise JobCompleted(
                    "training finished while waiting for generation "
                    ">= %d" % min_generation)
            if int(info["generation"]) >= min_generation and (
                    worker_id is None or
                    str(worker_id) in info["assignment"]):
                return info
        if time.monotonic() > deadline:
            raise TimeoutError(
                "timed out after %.0fs waiting for elastic generation "
                ">= %d (worker %s)" % (timeout, min_generation, worker_id))
        time.sleep(0.1)


def bootstrap_topology(min_generation=0, timeout=None):
    """Sets HVD_TPU_RANK/SIZE/GENERATION from the driver-published
    assignment (this worker identified by HVD_TPU_WORKER_ID). The
    subsequent dynamic rendezvous then runs in the generation's scope."""
    addr = os.environ.get("HVD_TPU_RENDEZVOUS_ADDR")
    wid = os.environ.get("HVD_TPU_WORKER_ID")
    if not addr or wid is None:
        raise RuntimeError(
            "HVD_TPU_ELASTIC=1 requires HVD_TPU_RENDEZVOUS_ADDR and "
            "HVD_TPU_WORKER_ID (spawn workers through the elastic "
            "launcher: horovodrun_tpu --min-np ...)")
    info = fetch_assignment(
        addr, _elastic_timeout() if timeout is None else timeout,
        min_generation=min_generation, worker_id=wid)
    for key in _GENERATION_ENV_KEYS:
        os.environ.pop(key, None)
    os.environ["HVD_TPU_RANK"] = str(info["assignment"][str(wid)])
    os.environ["HVD_TPU_SIZE"] = str(info["size"])
    os.environ["HVD_TPU_GENERATION"] = str(info["generation"])
    return info


def _request_reinit(failed_generation):
    """Tells the driver this worker's core hit a connection loss in
    `failed_generation`, so it bumps the generation even when no process
    exit was observed. Best-effort."""
    addr = os.environ.get("HVD_TPU_RENDEZVOUS_ADDR")
    wid = os.environ.get("HVD_TPU_WORKER_ID", "?")
    if not addr:
        return
    from horovod_tpu.run import rendezvous
    try:
        rendezvous.put(addr, SCOPE_ELASTIC, "reinit/%s" % wid,
                       str(failed_generation), timeout=5)
    except Exception:
        pass


def _reinitialize(min_generation):
    """Tears the core down and re-initializes for a new generation,
    retrying with ever-newer generations until the elastic timeout."""
    import horovod_tpu as hvd

    deadline = time.monotonic() + _elastic_timeout()
    while True:
        hvd.shutdown()
        if not _is_elastic():
            # Same-topology restart (size-1 tests / manual recovery).
            hvd.init()
            return
        try:
            bootstrap_topology(min_generation=min_generation,
                               timeout=max(1.0,
                                           deadline - time.monotonic()))
            hvd.init()
            return
        except JobCompleted:
            raise
        except (TimeoutError, RuntimeError, OSError) as e:
            if time.monotonic() > deadline:
                raise
            # The generation we tried may itself have failed (e.g. the
            # replacement died during startup). Require a newer one.
            min_generation = max(min_generation, current_generation() + 1)
            _log("re-init failed (%s); waiting for generation >= %d"
                 % (e, min_generation))


def _maybe_auto_resume(state):
    """Durable auto-resume (docs/ELASTIC.md "Durability"): on the FIRST
    entry of a process — a fresh job, or a full-job restart after a
    crash — rank 0 restores the newest valid durable manifest into the
    state; the ``state.sync()`` that follows broadcasts it to every
    rank, whatever the new world size. Durability is auto-enabled from
    ``HVD_TPU_CKPT_DIR`` (``horovodrun_tpu --ckpt-dir``) when the user
    did not call ``enable_durable`` themselves. Never raises: a broken
    checkpoint directory degrades to a fresh start with a warning."""
    try:
        if getattr(state, "_durable", None) is None:
            if not os.environ.get("HVD_TPU_CKPT_DIR") or \
                    not hasattr(state, "enable_durable"):
                return
            state.enable_durable()
        import horovod_tpu as hvd
        if hvd.rank() == 0:
            step = state._durable.restore_into(state)
            if step is not None:
                _log("auto-resume: restored durable step %d; syncing "
                     "to %d rank(s)" % (step, hvd.size()))
    except Exception as e:
        _log("auto-resume skipped (%s); starting fresh" % e)


def _flush_durable(state, timeout=None):
    """Drains the durable writer at clean training exit so the final
    committed state is on disk before the process goes away."""
    durable = getattr(state, "_durable", None)
    if durable is None:
        return
    if timeout is None:
        timeout = float(os.environ.get("HVD_TPU_CKPT_FLUSH_TIMEOUT",
                                       "120"))
    if not durable.flush(timeout=timeout):
        _log("durable writer did not drain within %.0fs at exit; "
             "newest snapshot may not be durable" % timeout)


def run(func):
    """Decorator making ``func(state, *args, **kwargs)`` elastic:

    * ``HorovodInternalError`` (peer lost mid-collective): restore the
      last committed state, re-initialize at the next generation, re-sync
      from the new rank 0, and call ``func`` again.
    * ``HostsUpdatedInterrupt`` (graceful membership change noticed at a
      ``state.commit()``): re-initialize and re-sync WITHOUT rollback.

    ``func`` must be resumable: it should read its progress (step/epoch)
    from the state object, which survives across retries."""

    @functools.wraps(func)
    def wrapper(state, *args, **kwargs):
        import horovod_tpu as hvd

        reset = None  # None = first entry, else "error" | "update"
        min_generation = 0
        while True:
            try:
                if reset is None:
                    if not hvd.is_initialized():
                        hvd.init()
                else:
                    _reinitialize(min_generation)
                    if reset == "error":
                        state.restore()
                    _log("resuming at generation %d size %d (rank %d)"
                         % (current_generation(), hvd.size(), hvd.rank()))
                reset = None
                if getattr(state, "_committed", None) is None:
                    # Nothing committed in THIS process yet — a fresh
                    # job or full-job restart picks up the newest valid
                    # durable checkpoint before the initial sync
                    # distributes it. Gating on the in-memory commit
                    # (not a one-shot flag) matters: if the first sync
                    # fails and the ranks reshuffle, the NEW rank 0
                    # re-attempts the restore instead of silently
                    # broadcasting its fresh step-0 state. Once any
                    # commit exists, rollbacks use it, never the disk
                    # copy.
                    _maybe_auto_resume(state)
                state.sync()
                result = func(state, *args, **kwargs)
                _flush_durable(state)
                return result
            except HorovodInternalError as e:
                if "protocol divergence" in str(e):
                    # Not a fault but a program bug (rank-conditional
                    # collective etc., see docs/LINT.md): deterministic,
                    # so rollback+retry would loop until the elastic
                    # timeout reproducing it every generation. Surface it.
                    raise
                _log("collective failed (%s); rolling back to last commit"
                     % e)
                reset = "error"
                min_generation = current_generation() + 1
                _request_reinit(current_generation())
            except DrainRequested as e:
                # Every rank reaches this handler at the SAME step (the
                # agreement allreduce in commit()), so the forced
                # durable write below is manifest-complete: rank 0's
                # publisher finds every sibling shard for the drained
                # step instead of timing out on a skewed one.
                wid = os.environ.get("HVD_TPU_WORKER_ID")
                victims = e.victims
                is_victim = victims == "all" or (
                    wid is not None and
                    str(wid) in [str(v) for v in victims])
                durable = getattr(state, "_durable", None)
                step = getattr(state, "step", None)
                if durable is not None:
                    durable.force_enqueue(state._committed,
                                          state._durable_step())
                _drain_metrics(requested=1,
                               draining=1 if is_victim else 0)
                if is_victim:
                    _log("drain (epoch %d): writing durable snapshot "
                         "of step %s, then exiting with EXIT_DRAINED"
                         % (e.epoch, step))
                    if durable is not None:
                        _flush_durable(state, timeout=e.grace)
                    sys.exit(EXIT_DRAINED)
                _log("drain (epoch %d): peer worker(s) %s leaving; "
                     "re-initializing at the post-drain generation "
                     "without rollback" % (e.epoch, victims))
                reset = "update"
                min_generation = current_generation() + 1
            except HostsUpdatedInterrupt as e:
                _log("membership changed (generation %d); re-initializing"
                     % e.generation)
                reset = "update"
                min_generation = e.generation
            except JobCompleted as e:
                # A replacement spawned just before the job finished has
                # no generation left to join — that is success elsewhere,
                # not a failure here.
                _log(str(e))
                _flush_durable(state)
                return None

    return wrapper
