"""Shard merging, clock correction, critical-path and causal analysis.

Input: the per-rank JSONL shards the native recorder writes under
HVD_TPU_TRACE_DIR (``trace_rank<r>.jsonl``, schema in native/trace.cc).
Every timestamp in a shard is on that rank's private monotonic clock;
the shard's clock lines carry the NTP-style offset to rank 0 estimated
on the control plane, so the merge lands every span on ONE clock —
rank 0's — and cross-rank comparisons (who enqueued last, did the recv
end after the send started) become plain subtraction.
"""

import json
import os
import re

PHASE_NAMES = {
    0: "enqueue",
    1: "negotiate",
    2: "fuse",
    3: "exec",
    4: "wire",
    5: "encode",
    6: "decode",
    7: "callback",
    8: "request",
}
PHASE_ENQUEUE = 0
PHASE_NEGOTIATE = 1
PHASE_WIRE = 4

_SHARD_RE = re.compile(r"trace_rank(\d+)\.jsonl$")


class ShardError(ValueError):
    """A shard file is unreadable or not a trace shard."""


class CausalViolation(object):
    """One wire hop whose corrected send start is after the recv end."""

    def __init__(self, channel, hop, send_rank, recv_rank, send_start_ns,
                 recv_end_ns):
        self.channel = channel
        self.hop = hop
        self.send_rank = send_rank
        self.recv_rank = recv_rank
        self.send_start_ns = send_start_ns
        self.recv_end_ns = recv_end_ns

    def __repr__(self):
        return ("CausalViolation(%s hop %d: rank %d sent at %d ns but "
                "rank %d finished receiving at %d ns)" %
                (self.channel, self.hop, self.send_rank, self.recv_rank,
                 self.send_start_ns, self.recv_end_ns))


def load_shard(path):
    """Parses one shard file.

    Returns ``(header, clock, spans)``: the header dict, the LAST clock
    sample emitted (the recorder only re-emits on improvement, so last =
    best known; ``None`` when the rank never estimated — rank 0 by
    definition has offset 0), and the span dicts in write order. A
    truncated final line (the rank died mid-drain) is dropped, not
    fatal — that is exactly the crashed-run case this tooling exists
    for.
    """
    header = None
    clock = None
    spans = []
    with open(path, "r") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail of a killed rank
            if "hvd_trace_shard" in rec:
                header = rec
            elif "clock" in rec:
                clock = rec["clock"]
            elif "p" in rec:
                spans.append(rec)
    if header is None:
        raise ShardError("%s is not an hvd trace shard (no header line)"
                         % path)
    return header, clock, spans


def find_shards(path):
    """Expands a trace directory to its shard paths (rank order)."""
    if os.path.isdir(path):
        found = []
        for name in os.listdir(path):
            m = _SHARD_RE.search(name)
            if m:
                found.append((int(m.group(1)), os.path.join(path, name)))
        return [p for _, p in sorted(found)]
    return [path]


class MergedTrace(object):
    """All ranks' spans on rank 0's clock."""

    def __init__(self):
        self.ranks = {}       # rank -> {"header", "offset_ns",
                              #          "uncertainty_ns", "spans"}
        self.world_size = 0

    def corrected(self, rank, ts_ns):
        """A rank-local timestamp moved onto rank 0's clock."""
        return ts_ns + self.ranks[rank]["offset_ns"]

    def spans(self):
        """Yields ``(rank, span)`` over every rank in rank order."""
        for rank in sorted(self.ranks):
            for span in self.ranks[rank]["spans"]:
                yield rank, span

    def to_chrome(self):
        """The merged trace as a chrome-tracing / Perfetto JSON object.

        One chrome "process" per rank, one "thread" per span phase;
        every event a complete ("X") event with microsecond timestamps
        on rank 0's clock. ``json.dump`` of the return value is a valid
        trace file.
        """
        events = []
        for rank in sorted(self.ranks):
            events.append({"name": "process_name", "ph": "M", "pid": rank,
                           "args": {"name": "rank %d" % rank}})
            events.append({"name": "process_sort_index", "ph": "M",
                           "pid": rank, "args": {"sort_index": rank}})
            for pid_phase, pname in PHASE_NAMES.items():
                events.append({"name": "thread_name", "ph": "M",
                               "pid": rank, "tid": pid_phase,
                               "args": {"name": pname}})
        for rank, s in self.spans():
            start = self.corrected(rank, s["s"])
            events.append({
                "name": s["n"],
                "ph": "X",
                "pid": rank,
                "tid": s["p"],
                "ts": start / 1000.0,
                "dur": max(0, s["e"] - s["s"]) / 1000.0,
                "args": {"bytes": s.get("b", 0), "group": s.get("g", 0),
                         "peer": s.get("pe", -1), "hop": s.get("c", 0),
                         "shm": bool(s.get("f", 0) & 1)},
            })
        meta = {
            "hvd_trace": 1,
            "ranks": sorted(self.ranks),
            "clock": {
                str(r): {
                    "offset_ns": self.ranks[r]["offset_ns"],
                    "uncertainty_ns": self.ranks[r]["uncertainty_ns"],
                } for r in self.ranks
            },
        }
        return {"traceEvents": events, "otherData": meta,
                "displayTimeUnit": "ms"}

    def check_causal(self):
        """Causal-order audit of global-ring wire hops.

        PairExchange stamps every exchange with a per-channel hop
        sequence that advances in lockstep around the ring, so hop N on
        rank r pairs with hop N on rank (r+1) %% world. The receiver
        cannot finish before the sender started: after clock correction,
        ``send.start <= recv.end`` (padded by the two ranks' combined
        offset uncertainty) must hold for every pair. Returns the list
        of violations (empty = causally consistent). Only ``hop.ring``
        spans are audited — group sub-rings advance hop sequences on
        member ranks only, so their pairing is not rank-derivable here.
        """
        hops = {}  # (rank, hop_seq) -> span
        for rank, s in self.spans():
            if s["p"] == PHASE_WIRE and s["n"] == "hop.ring":
                hops[(rank, s["c"])] = s
        violations = []
        n = self.world_size
        if n < 2:
            return violations
        for (rank, hop), s in sorted(hops.items()):
            peer = s.get("pe", -1)
            if peer < 0:
                continue
            r = hops.get((peer, hop))
            if r is None:
                continue
            tol = (self.ranks[rank]["uncertainty_ns"] +
                   self.ranks[peer]["uncertainty_ns"])
            send_start = self.corrected(rank, s["s"])
            recv_end = self.corrected(peer, r["e"])
            if send_start > recv_end + tol:
                violations.append(CausalViolation(
                    s["n"], hop, rank, peer, send_start, recv_end))
        return violations


def merge_shards(paths):
    """Loads shards (files or one directory) into a MergedTrace."""
    shard_paths = []
    for p in paths:
        shard_paths.extend(find_shards(p))
    if not shard_paths:
        raise ShardError("no trace_rank*.jsonl shards found in %s"
                         % list(paths))
    merged = MergedTrace()
    for path in shard_paths:
        header, clock, spans = load_shard(path)
        rank = int(header.get("rank", -1))
        merged.ranks[rank] = {
            "header": header,
            # Rank 0 is the reference: offset identically 0. A worker
            # whose shard carries no clock line (died before the first
            # full negotiation cycle) merges uncorrected, flagged by a
            # huge uncertainty so the causal audit skips its pairs.
            "offset_ns": clock["offset_ns"] if clock else 0,
            "uncertainty_ns": (clock["uncertainty_ns"] if clock
                               else (0 if rank == 0 else 1 << 60)),
            "spans": spans,
        }
        merged.world_size = max(merged.world_size,
                                int(header.get("size", 0)))
    return merged


def critical_path_table(merged):
    """Per-tensor critical-path rows from a MergedTrace.

    For every tensor that negotiated, reports which phase dominated its
    total recorded time, which rank was the straggler — the one whose
    corrected enqueue landed LAST, holding the collective open — and
    how much negotiation wait it inflicted: the longest negotiate span
    among the OTHER ranks (they sat in the pending table for exactly as
    long as the straggler was late, plus one cycle).

    Returns a list of row dicts sorted by inflicted wait, descending.
    """
    by_tensor = {}
    for rank, s in merged.spans():
        if s["p"] in (PHASE_WIRE,):
            continue  # hops are channel-keyed, not tensor-keyed
        t = by_tensor.setdefault(s["n"], {"enqueue": {}, "negotiate": {},
                                          "phase_ns": {}})
        dur = max(0, s["e"] - s["s"])
        t["phase_ns"][s["p"]] = t["phase_ns"].get(s["p"], 0) + dur
        if s["p"] == PHASE_ENQUEUE:
            # Latest enqueue per rank: a tensor reused across steps keeps
            # its worst epoch.
            ts = merged.corrected(rank, s["s"])
            if ts > t["enqueue"].get(rank, -(1 << 62)):
                t["enqueue"][rank] = ts
        elif s["p"] == PHASE_NEGOTIATE:
            if dur > t["negotiate"].get(rank, -1):
                t["negotiate"][rank] = dur
    rows = []
    for name, t in by_tensor.items():
        if not t["phase_ns"]:
            continue
        dominant = max(t["phase_ns"].items(), key=lambda kv: kv[1])
        straggler = None
        inflicted = 0
        spread = 0
        if len(t["enqueue"]) >= 2:
            straggler = max(t["enqueue"], key=t["enqueue"].get)
            spread = (t["enqueue"][straggler] -
                      min(t["enqueue"].values()))
            others = [v for r, v in t["negotiate"].items()
                      if r != straggler]
            inflicted = max(others) if others else 0
        rows.append({
            "tensor": name,
            "dominant_phase": PHASE_NAMES.get(dominant[0],
                                              str(dominant[0])),
            "dominant_ns": dominant[1],
            "straggler_rank": straggler,
            "enqueue_spread_ns": spread,
            "negotiation_wait_ns": inflicted,
        })
    rows.sort(key=lambda r: r["negotiation_wait_ns"], reverse=True)
    return rows


def repair_timeline(path, write=True):
    """Closes the JSON array of a truncated chrome-tracing timeline.

    A rank killed mid-run leaves HVD_TPU_TIMELINE output (and pre-trace
    legacy files) as an unterminated array, often ending in a partial
    record. Cuts back to the last point where the file parses as a
    complete array and rewrites it in place (``write=False`` to probe).
    Returns True when the file was (or would be) modified, False when it
    already parses.
    """
    with open(path, "r") as f:
        raw = f.read()
    try:
        json.loads(raw)
        return False
    except ValueError:
        pass
    body = raw.rstrip()
    if body.endswith("]"):
        body = body[:-1]
    # Walk record boundaries backwards until the prefix closes cleanly.
    # The parse check is what proves a cut point is a boundary, so a
    # '}' inside a quoted string can't fool it.
    idx = len(body)
    repaired = None
    while True:
        idx = body.rfind("}", 0, idx)
        if idx < 0:
            repaired = "[\n]\n"
            break
        candidate = body[:idx + 1] + "\n]\n"
        try:
            json.loads(candidate)
            repaired = candidate
            break
        except ValueError:
            continue
    if write:
        with open(path, "w") as f:
            f.write(repaired)
    return True
