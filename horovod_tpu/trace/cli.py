"""hvd-trace command line: merge shards, print critical paths, repair.

``hvd-trace <trace-dir | shards...>`` merges per-rank shards into one
chrome-tracing JSON (open in Perfetto / chrome://tracing) and prints the
per-tensor critical-path table. ``--check-causal`` additionally audits
that every global-ring wire hop is causally ordered after clock
correction (non-zero exit on violation, for use in tests and CI).
``--repair FILE`` fixes a truncated legacy HVD_TPU_TIMELINE file in
place instead.
"""

import argparse
import json
import os
import sys

from horovod_tpu.trace.merge import (
    ShardError,
    critical_path_table,
    merge_shards,
    repair_timeline,
)


def _fmt_ms(ns):
    return "%.3f" % (ns / 1e6)


def print_table(rows, out=sys.stdout, limit=20):
    if not rows:
        out.write("no tensor spans found\n")
        return
    cols = ("tensor", "dominant", "dom ms", "straggler", "spread ms",
            "neg wait ms")
    widths = [max(len(cols[0]), max(len(r["tensor"]) for r in rows[:limit])),
              10, 12, 9, 12, 12]
    fmt = "  ".join("%%-%ds" % w for w in widths) + "\n"
    out.write(fmt % cols)
    for r in rows[:limit]:
        out.write(fmt % (
            r["tensor"],
            r["dominant_phase"],
            _fmt_ms(r["dominant_ns"]),
            "-" if r["straggler_rank"] is None else str(r["straggler_rank"]),
            _fmt_ms(r["enqueue_spread_ns"]),
            _fmt_ms(r["negotiation_wait_ns"]),
        ))
    if len(rows) > limit:
        out.write("  ... %d more tensors (use --limit)\n"
                  % (len(rows) - limit))


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="hvd-trace",
        description="Merge hvd trace shards into a Perfetto-loadable "
                    "JSON and report per-tensor critical paths.")
    parser.add_argument("paths", nargs="*",
                        help="trace directory (HVD_TPU_TRACE_DIR) or "
                             "individual trace_rank*.jsonl shards")
    parser.add_argument("-o", "--output", default=None,
                        help="write merged chrome-tracing JSON here "
                             "(default: <first path>/trace_merged.json)")
    parser.add_argument("--no-table", action="store_true",
                        help="skip the critical-path table")
    parser.add_argument("--limit", type=int, default=20,
                        help="max table rows (default 20)")
    parser.add_argument("--check-causal", action="store_true",
                        help="verify corrected send-start < recv-end for "
                             "every paired ring hop; exit 3 on violation")
    parser.add_argument("--repair", metavar="FILE", default=None,
                        help="repair a truncated timeline/trace JSON "
                             "array in place and exit")
    args = parser.parse_args(argv)

    if args.repair is not None:
        try:
            changed = repair_timeline(args.repair)
        except (IOError, OSError) as e:
            sys.stderr.write("hvd-trace: %s\n" % e)
            return 2
        print("%s: %s" % (args.repair,
                          "repaired" if changed else "already valid"))
        return 0

    if not args.paths:
        parser.error("need a trace directory or shard files "
                     "(or --repair FILE)")
    try:
        merged = merge_shards(args.paths)
    except (ShardError, IOError, OSError) as e:
        sys.stderr.write("hvd-trace: %s\n" % e)
        return 2

    out_path = args.output
    if out_path is None:
        base = args.paths[0]
        if not os.path.isdir(base):
            base = os.path.dirname(base) or "."
        out_path = os.path.join(base, "trace_merged.json")
    with open(out_path, "w") as f:
        json.dump(merged.to_chrome(), f)
    n_spans = sum(len(r["spans"]) for r in merged.ranks.values())
    print("merged %d spans from %d ranks -> %s"
          % (n_spans, len(merged.ranks), out_path))
    for rank in sorted(merged.ranks):
        r = merged.ranks[rank]
        print("  rank %d: %d spans, clock offset %+d ns (+/- %d ns)"
              % (rank, len(r["spans"]), r["offset_ns"],
                 min(r["uncertainty_ns"], 1 << 60)))

    if not args.no_table:
        print()
        print_table(critical_path_table(merged), limit=args.limit)

    if args.check_causal:
        violations = merged.check_causal()
        if violations:
            for v in violations:
                sys.stderr.write("causal violation: %r\n" % v)
            sys.stderr.write("hvd-trace: %d causal violation(s)\n"
                             % len(violations))
            return 3
        print("causal check: all paired ring hops ordered")
    return 0


if __name__ == "__main__":
    sys.exit(main())
