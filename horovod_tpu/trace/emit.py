"""Pure-Python span emitter sharing the native shard schema.

Serve replicas never call hvd.init() — the native recorder is not
loaded in their process — but their request spans still belong in the
same merged trace as the training plane. This emitter writes the same
JSONL shard format as native/trace.cc (header line, then span lines
with the n/p/g/c/pe/b/s/e/f keys), so ``hvd-trace`` merges serve shards
with no special casing. No clock lines are written: a serve process has
no control plane to piggyback NTP samples on, so its spans merge
uncorrected (offset 0) — fine for intra-process latency analysis, which
is what per-request spans are for.

Gated on HVD_TPU_TRACE_DIR like the native side; with the env unset,
``shard_for()`` returns a no-op emitter so call sites stay unconditional.
"""

import json
import os
import threading
import time

TRACE_REQUEST = 8

_lock = threading.Lock()
_shards = {}


class _NullEmitter(object):
    enabled = False

    def span(self, name, start_ns, end_ns, phase=TRACE_REQUEST, nbytes=0,
             group=0, cycle=0):
        pass


class ShardEmitter(object):
    """Appends span lines to one shard file; thread-safe, line-buffered."""

    enabled = True

    def __init__(self, path, rank, size):
        self._lock = threading.Lock()
        fresh = not os.path.exists(path)
        self._f = open(path, "a")
        if fresh:
            self._f.write(json.dumps({
                "hvd_trace_shard": 1, "rank": rank, "size": size,
                "generation": 0, "pid": os.getpid(), "ring": 0,
            }) + "\n")
            self._f.flush()

    def span(self, name, start_ns, end_ns, phase=TRACE_REQUEST, nbytes=0,
             group=0, cycle=0):
        line = json.dumps({"n": name, "p": phase, "g": group, "c": cycle,
                           "pe": -1, "b": nbytes, "s": start_ns,
                           "e": end_ns, "f": 0}) + "\n"
        with self._lock:
            self._f.write(line)
            self._f.flush()


def now_ns():
    """Monotonic nanoseconds, same clock family as the native recorder."""
    return time.monotonic_ns()


def shard_for(tag, rank=0, size=0):
    """The process-wide emitter for a shard named ``trace_<tag>.jsonl``.

    Returns a shared no-op object when HVD_TPU_TRACE_DIR is unset.
    ``tag`` should be filesystem-safe and unique per process (e.g.
    ``"serve_r2"`` for replica 2) so co-located processes never
    interleave writes in one file.
    """
    trace_dir = os.environ.get("HVD_TPU_TRACE_DIR", "")
    if not trace_dir:
        return _NullEmitter()
    with _lock:
        em = _shards.get(tag)
        if em is None:
            try:
                os.makedirs(trace_dir, exist_ok=True)
                path = os.path.join(trace_dir, "trace_%s.jsonl" % tag)
                em = ShardEmitter(path, rank, size)
            except (IOError, OSError):
                em = _NullEmitter()
            _shards[tag] = em
        return em
