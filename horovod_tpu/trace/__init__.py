"""Distributed-trace tooling (docs/TRACING.md).

The native core's span recorder (native/trace.h) writes one JSONL shard
per rank; this package merges them into a single Perfetto/chrome-tracing
JSON on rank 0's clock, prints per-tensor critical-path tables, checks
causal ordering of wire hops after clock correction, and repairs
truncated legacy timeline files. ``emit`` is the pure-Python span
emitter the serve plane uses (replicas never load the native core).
"""

from horovod_tpu.trace.merge import (  # noqa: F401
    CausalViolation,
    MergedTrace,
    critical_path_table,
    load_shard,
    merge_shards,
    repair_timeline,
)
