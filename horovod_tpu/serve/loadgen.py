"""Seeded open-loop load generator for the serve plane.

OPEN loop: arrivals fire on a fixed schedule derived from the target
rate and the seed, whether or not earlier requests have finished — so
queueing delay shows up in the measured latency instead of silently
throttling the offered load (the closed-loop trap). Each worker thread
owns a :class:`~horovod_tpu.serve.client.ServeClient` and a disjoint
slice of the schedule; results land in one summary with p50/p99 from
the actual sorted samples (no histogram estimate on the bench path).

Every request's input is derived from the seed, so the expected answer
is recomputable: pass ``leaves_by_crc`` mapping a weights fingerprint
to its leaves and every response is checked against the numpy forward
for the weight set it CLAIMS (by fingerprint) to have used — the
rolling-swap e2e and ``bench.py --serve`` both lean on this to turn
"zero dropped, right answers, right weights" into an assert.
"""

import threading
import time

import numpy as np

from . import model as _model
from .client import ServeClient, ServeError


class LoadResult:
    def __init__(self):
        self.lock = threading.Lock()
        self.latencies = []       # seconds, successes only
        self.ok = 0
        self.errors = []          # (rid, cause, message)
        self.mismatches = []      # (rid, why)
        self.by_crc = {}          # weights_crc -> response count

    def record_ok(self, latency, crc):
        with self.lock:
            self.ok += 1
            self.latencies.append(latency)
            self.by_crc[crc] = self.by_crc.get(crc, 0) + 1

    def record_error(self, rid, cause, message):
        with self.lock:
            self.errors.append((rid, cause, str(message)))

    def record_mismatch(self, rid, why):
        with self.lock:
            self.mismatches.append((rid, why))

    def quantile(self, q):
        with self.lock:
            if not self.latencies:
                return None
            samples = sorted(self.latencies)
        idx = min(len(samples) - 1, int(q * len(samples)))
        return samples[idx]

    def summary(self, wall):
        p50, p99 = self.quantile(0.50), self.quantile(0.99)
        with self.lock:
            return {
                "ok": self.ok,
                "errors": len(self.errors),
                "mismatches": len(self.mismatches),
                "rps_achieved": self.ok / wall if wall > 0 else 0.0,
                "p50_ms": None if p50 is None else round(p50 * 1e3, 3),
                "p99_ms": None if p99 is None else round(p99 * 1e3, 3),
                "by_crc": dict(self.by_crc),
            }


def request_input(seed, rid, dim):
    """The seeded, recomputable input vector for request ``rid``."""
    rng = np.random.RandomState((int(seed) * 1000003 + int(rid))
                                % (2 ** 31 - 1))
    return rng.standard_normal(dim).astype(np.float32)


def check_response(doc, x, model_name, leaves_by_crc, atol=1e-3):
    """Verifies a response against the numpy forward for the weight set
    its fingerprint names. Returns None when consistent, else a short
    reason. Unknown fingerprints only fail when the caller claims to
    know every live weight set (leaves_by_crc non-empty)."""
    crc = doc.get("weights_crc")
    if leaves_by_crc:
        if crc not in leaves_by_crc:
            return "unknown weights fingerprint %r" % (crc,)
        expect = _model.forward(model_name, leaves_by_crc[crc], x)
        got = np.asarray(doc["y"], np.float32)
        if got.shape != expect.shape:
            return "shape %s != expected %s" % (got.shape, expect.shape)
        if not np.allclose(got, expect, atol=atol):
            return ("answer does not match the %s weights it claims "
                    "(max err %.3g)" % (crc, float(np.max(np.abs(
                        got - expect)))))
    return None


def run_load(endpoints, rate, duration, dim, seed=0, model_name="affine",
             leaves_by_crc=None, workers=4, total_deadline=10.0,
             rid_base=0):
    """Drives ``rate`` req/s for ``duration`` seconds open-loop against
    ``endpoints``; returns (LoadResult, wall_seconds). Request ids are
    ``rid_base + k`` so back-to-back phases (bench traffic steps) keep
    ids — and therefore seeded inputs — disjoint."""
    n = max(1, int(rate * duration))
    interval = duration / n
    start = time.monotonic() + 0.05
    result = LoadResult()
    leaves_by_crc = leaves_by_crc or {}

    def worker(offset):
        client = ServeClient(endpoints, total_deadline=total_deadline)
        for k in range(offset, n, workers):
            rid = rid_base + k
            wake = start + k * interval
            delay = wake - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            x = request_input(seed, rid, dim)
            t0 = time.monotonic()
            try:
                doc = client.infer(x, rid=str(rid))
            except ServeError as e:
                result.record_error(rid, e.cause, e)
                continue
            latency = time.monotonic() - t0
            why = check_response(doc, x, model_name, leaves_by_crc)
            if why is not None:
                result.record_mismatch(rid, why)
            else:
                result.record_ok(latency, doc.get("weights_crc"))

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(workers)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return result, time.monotonic() - t0
