"""serve_* metrics: the replica-side mirror registry (docs/SERVE.md,
docs/METRICS.md).

A serve replica never calls ``hvd.init()`` (no collectives on the
request path — that is the whole point), so like the fleet controller
it keeps a small Python mirror of the native registry: monotonic
counters, gauges, and fixed-bucket histograms rendered by the SAME
Prometheus renderer the worker endpoints use (``_metrics.py``). One
scrape config covers training workers, the fleet controller, and every
serve replica.

Thread model: the batch loop, the HTTP handler threads, and the swap
watcher all write — everything mutates under one lock (request rates
on a replica are nowhere near lock-contention territory).
"""

import threading

# Request latency ladder: HTTP admission to response split, seconds.
# Sub-millisecond (a warm forward on a tiny model) up to the 10s
# request deadline — anything beyond the top bucket is a hang the
# client-side deadline converts into a named error.
_REQUEST_BOUNDS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

# Queue-depth ladder: sampled at every batch assembly. The top of the
# ladder is the default admission bound — a sample up there means the
# replica is about to start rejecting (serve_rejects_total).
_DEPTH_BOUNDS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                 256.0)

# Batch-fill ladder mirrors the pad-to-bucket shapes (batcher.py).
_BATCH_BOUNDS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)

COUNTERS = (
    "serve_requests_total",        # admitted into the queue
    "serve_responses_total",       # answered 200
    "serve_batches_total",         # forward passes executed
    "serve_rejects_total",         # refused at admission (full/draining)
    "serve_errors_total",          # answered with a cause-named error
    "serve_cancelled_total",       # deadline-expired tickets dropped
                                   # before spending a forward row
    "serve_frame_corrupt_total",   # batch-frame CRC mismatches detected
    "serve_swaps_total",           # weight swaps flipped in
    "serve_swap_rejects_total",    # newer-but-invalid manifests refused
    "serve_swap_aborts_total",     # swaps abandoned (drain won the race)
    "serve_drains_total",          # drain requests honored
)

GAUGES = (
    "serve_queue_depth",     # admitted-not-yet-batched requests
    "serve_inflight",        # requests inside a running forward
    "serve_draining",        # 1 while the replica is draining
    "serve_model_step",      # lineage step of the serving weights
)

HISTOGRAMS = {
    "serve_request_seconds": _REQUEST_BOUNDS,
    "serve_queue_depth_sampled": _DEPTH_BOUNDS,
    "serve_batch_fill": _BATCH_BOUNDS,
}


class _Histogram:
    """Fixed-bucket histogram, snapshot-compatible with the native
    registry's JSON shape (bounds / counts / sum / count)."""

    def __init__(self, bounds):
        self.bounds = list(bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v):
        i = 0
        while i < len(self.bounds) and v > self.bounds[i]:
            i += 1
        self.counts[i] += 1
        self.sum += v
        self.count += 1

    def snapshot(self):
        return {"bounds": list(self.bounds), "counts": list(self.counts),
                "sum": self.sum, "count": self.count}


def histogram_quantile(snap, q):
    """Quantile estimate from a bucket snapshot (upper bound of the
    bucket the q-th observation falls in — the conservative read a
    latency SLO wants). None when the histogram is empty."""
    count = snap.get("count", 0)
    if not count:
        return None
    target = q * count
    bounds = snap.get("bounds", [])
    seen = 0
    for i, c in enumerate(snap.get("counts", [])):
        seen += c
        if seen >= target and c:
            if i < len(bounds):
                return float(bounds[i])
            # Overflow bucket: only the mean is honest up there.
            return snap.get("sum", 0.0) / count
    return float(bounds[-1]) if bounds else None


class ServeMetrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {name: 0 for name in COUNTERS}
        self._gauges = {name: 0 for name in GAUGES}
        self._histograms = {name: _Histogram(bounds)
                            for name, bounds in HISTOGRAMS.items()}

    def inc(self, name, n=1):
        with self._lock:
            self._counters[name] += n

    def get(self, name):
        with self._lock:
            return self._counters.get(name, self._gauges.get(name, 0))

    def set_gauge(self, name, v):
        with self._lock:
            self._gauges[name] = v

    def add_gauge(self, name, n):
        with self._lock:
            self._gauges[name] += n

    def observe(self, name, v):
        with self._lock:
            self._histograms[name].observe(v)

    def snapshot(self):
        """Native-registry-shaped dict, accepted verbatim by
        ``_metrics.render_prometheus``."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {n: h.snapshot()
                               for n, h in self._histograms.items()},
            }

    def latency_quantiles(self):
        """(p50, p99) of serve_request_seconds, in seconds (None when
        no request has completed yet)."""
        with self._lock:
            snap = self._histograms["serve_request_seconds"].snapshot()
        return (histogram_quantile(snap, 0.50),
                histogram_quantile(snap, 0.99))


def render_prometheus(metrics):
    from horovod_tpu._metrics import render_prometheus as _render
    return _render(metrics.snapshot())
