"""The serve plane's model registry (docs/SERVE.md).

A serve model is two pure functions over a flat dict of numpy leaves —
exactly what :func:`horovod_tpu.elastic.durable.load_leaves` hands back
from a checkpoint lineage:

* ``init_leaves(dim, seed)`` — deterministic initial weights (what a
  replica serves before the first lineage checkpoint lands);
* ``forward(leaves, x)`` — the batched forward pass, [B, D] -> [B, D].

:func:`make_forward` wraps the forward in ``jax.jit`` when jax is
importable (pad-to-bucket batch shapes keep the compile count bounded
— one compile per bucket, see batcher.py) and falls back to the
bit-identical numpy math otherwise (``HVD_TPU_SERVE_JIT=0`` forces the
fallback; the sanitizer churn runs use it so the preloaded interpreter
never pulls jax in).

Every response carries :func:`fingerprint` of the serving leaves — the
CRC32C chain over sorted leaf names and bytes. The rolling-swap e2e
asserts post-swap responses carry the NEW lineage's fingerprint, which
is how "provably computed from the new weights" is checked without
trusting a step counter someone could forget to bump.
"""

import os

import numpy as np

from horovod_tpu.elastic import durable

# Registered model -> (init_leaves, forward). The serving data path is
# model-agnostic: anything mapping a leaves dict + [B, D] batch to
# [B, D] outputs slots in here.
_REGISTRY = {}


def register_model(name, init_fn, forward_fn):
    _REGISTRY[name] = (init_fn, forward_fn)


def _affine_init(dim, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "w": rng.standard_normal((dim, dim)).astype(np.float32),
        "b": rng.standard_normal((dim,)).astype(np.float32),
    }


def _affine_forward(leaves, x):
    return x @ leaves["w"] + leaves["b"]


def _mlp_init(dim, seed=0):
    rng = np.random.RandomState(seed)
    hidden = 4 * dim
    return {
        "w0": (rng.standard_normal((dim, hidden)) /
               np.sqrt(dim)).astype(np.float32),
        "b0": np.zeros((hidden,), np.float32),
        "w1": (rng.standard_normal((hidden, dim)) /
               np.sqrt(hidden)).astype(np.float32),
        "b1": np.zeros((dim,), np.float32),
    }


def _mlp_forward(leaves, x):
    h = x @ leaves["w0"] + leaves["b0"]
    h = np.maximum(h, 0.0) if isinstance(h, np.ndarray) else _relu(h)
    return h @ leaves["w1"] + leaves["b1"]


def _relu(h):
    import jax.numpy as jnp
    return jnp.maximum(h, 0.0)


register_model("affine", _affine_init, _affine_forward)
register_model("mlp", _mlp_init, _mlp_forward)


def init_leaves(name, dim, seed=0):
    if name not in _REGISTRY:
        raise ValueError("unknown serve model %r (have: %s)"
                         % (name, sorted(_REGISTRY)))
    return _REGISTRY[name][0](dim, seed)


def forward(name, leaves, x):
    """The un-jitted (numpy) forward — the parity reference the e2e
    tests recompute answers with."""
    if name not in _REGISTRY:
        raise ValueError("unknown serve model %r (have: %s)"
                         % (name, sorted(_REGISTRY)))
    return np.asarray(_REGISTRY[name][1](leaves, np.asarray(x)))


def fingerprint(leaves):
    """CRC32C chain over sorted leaf names + bytes, hex8 — the identity
    of a weight set on the response wire."""
    crc = 0
    for key in sorted(leaves):
        crc = durable.crc32c(key.encode("utf-8"), crc)
        crc = durable.crc32c(
            np.ascontiguousarray(leaves[key]).tobytes(), crc)
    return "%08x" % crc


def extract_leaves(raw, template):
    """Maps a raw lineage leaf dict (``load_leaves`` output, flattened
    paths like ``.w`` / ``.opt.0.mu``) onto a model's leaf names by
    basename match — so a TRAINING job's durable lineage serves
    directly, optimizer slots and step counters ignored. Returns the
    {name: float32 array} dict or None when any model leaf is missing
    or shape-mismatched (the replica then falls back to its current
    weights)."""
    out = {}
    for want, ref in template.items():
        cands = sorted(
            (k for k in raw if k == want or str(k).endswith("." + want)),
            key=lambda k: (len(str(k)), str(k)))
        picked = None
        for k in cands:
            arr = np.asarray(raw[k])
            if arr.shape == ref.shape:
                picked = arr.astype(np.float32)
                break
        if picked is None:
            return None
        out[want] = picked
    return out


def make_forward(name, leaves):
    """Callable batch -> outputs over a FIXED leaves dict. Jitted when
    jax is available (weights are closed over as constants — a weight
    swap builds a fresh jitted callable for the shadow leaves, so the
    flip is one reference swap and in-flight batches finish on the old
    closure); numpy fallback otherwise."""
    if name not in _REGISTRY:
        raise ValueError("unknown serve model %r (have: %s)"
                         % (name, sorted(_REGISTRY)))
    fwd = _REGISTRY[name][1]
    if os.environ.get("HVD_TPU_SERVE_JIT", "1") != "0":
        try:
            import jax
            import jax.numpy as jnp

            jleaves = {k: jnp.asarray(v) for k, v in leaves.items()}
            jitted = jax.jit(lambda x: fwd(jleaves, x))

            def run(x):
                return np.asarray(jitted(np.asarray(x)))

            return run
        except Exception:
            pass  # no jax in this interpreter: serve the numpy math

    def run(x):
        return np.asarray(fwd(leaves, np.asarray(x)))

    return run
