"""Stdlib HTTP client for the serve plane (docs/SERVE.md).

:class:`ServeClient` owns the re-queue half of the serving plane's
fault contract: a replica that answers with a RETRYABLE cause-named
error (``draining``, ``overload``) or that dies mid-request
(connection refused / reset / timed out) costs the caller one retry on
the next endpoint in the rotation, not an error — the request is
re-queued to a surviving replica. Only a request-terminal cause
(``bad-request``, ``shape``, ``frame-corrupt``, ``forward``) or the
total deadline surfaces a :class:`ServeError`, and it names the cause.
"""

import json
import time
import urllib.error
import urllib.request


class ServeError(Exception):
    """A request that ended without an answer; ``cause`` names why."""

    def __init__(self, message, cause="error", attempts=0):
        super().__init__(message)
        self.cause = cause
        self.attempts = attempts


class ServeClient:
    """Round-robin client over a set of replica endpoints.

    ``endpoints`` is a list of ``host:port`` strings (or a callable
    returning one, so a supervisor-backed client tracks autoscaling).
    ``total_deadline`` bounds one logical request across all retries.
    """

    def __init__(self, endpoints, total_deadline=15.0,
                 attempt_timeout=12.0, backoff=0.05):
        self._endpoints = endpoints
        self.total_deadline = float(total_deadline)
        self.attempt_timeout = float(attempt_timeout)
        self.backoff = float(backoff)
        self._rr = 0

    def endpoints(self):
        eps = self._endpoints() if callable(self._endpoints) \
            else self._endpoints
        return list(eps)

    def _post(self, endpoint, doc, timeout):
        req = urllib.request.Request(
            "http://%s/infer" % endpoint,
            data=json.dumps(doc).encode("utf-8"),
            headers={"Content-Type": "application/json"},
            method="POST")
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read().decode("utf-8"))
            except Exception:
                body = {"error": "HTTP %d" % e.code, "cause": "http"}
            body.setdefault("cause", "http")
            body["_status"] = e.code
            return body

    def infer(self, x, rid=""):
        """One logical inference: returns the response doc (``y``,
        ``model_step``, ``weights_crc``, ``replica``) or raises
        :class:`ServeError` with a named cause. Replica death and
        re-queueable rejections are absorbed by retrying the rotation
        until ``total_deadline``."""
        deadline = time.monotonic() + self.total_deadline
        attempts = 0
        last = ("no replica endpoints", "no-endpoints")
        while time.monotonic() < deadline:
            eps = self.endpoints()
            if not eps:
                time.sleep(self.backoff)
                continue
            endpoint = eps[self._rr % len(eps)]
            self._rr += 1
            attempts += 1
            remain = deadline - time.monotonic()
            if remain <= 0:
                break
            try:
                doc = self._post(endpoint,
                                 {"id": rid,
                                  "x": [float(v) for v in x]},
                                 timeout=min(self.attempt_timeout,
                                             max(remain, 0.05)))
            except (OSError, urllib.error.URLError) as e:
                # Replica gone mid-request (SIGKILL chaos, connection
                # refused/reset): re-queue to the next endpoint.
                last = ("replica %s unreachable: %s" % (endpoint, e),
                        "replica-lost")
                time.sleep(self.backoff)
                continue
            if "y" in doc:
                return doc
            cause = doc.get("cause", "error")
            status = doc.get("_status", 0)
            if status == 503 or cause in ("draining", "overload",
                                          "deadline"):
                last = (doc.get("error", "rejected"), cause)
                time.sleep(self.backoff)
                continue
            raise ServeError(doc.get("error", "request failed"),
                             cause=cause, attempts=attempts)
        raise ServeError(
            "deadline (%.1fs) expired after %d attempt(s); last: %s"
            % (self.total_deadline, attempts, last[0]),
            cause=last[1] if attempts else "deadline",
            attempts=attempts)
