"""The replica's stdlib-only HTTP/JSON front door (docs/SERVE.md).

Same ThreadingHTTPServer discipline as the metrics plane
(``_metrics.py``): daemon handler threads, ``log_message`` suppressed,
and NOTHING a request does may kill the replica — every handler error
becomes a cause-named JSON error response. The handler threads only
park on ticket events; the forward pass runs in the replica's main
thread (the batch loop), so the server keeps answering health checks
and admitting requests while a batch is on the chip.

Routes:

* ``POST /infer``  — ``{"id": ..., "x": [...]}`` -> ``{"y": [...],
  "model_step": N, "weights_crc": "...", "replica": W, "batch": B}``;
  errors are ``{"error": msg, "cause": slug}`` with 503 for
  re-queueable causes (draining/overload — the client retries a
  surviving replica) and 400/500 for request-terminal ones.
* ``GET /healthz`` — liveness + drain posture.
* ``GET /serve``   — the per-replica stats document (the supervisor
  aggregates these; ``hvd-top --serve`` renders the aggregate).
* ``GET /metrics`` — Prometheus text exposition of the serve registry.
"""

import json
import threading
import time

from .batcher import QueueFull
from .metrics import render_prometheus

# Re-queueable causes answer 503: "try another replica, promptly".
_RETRYABLE = {"draining", "overload"}


class ReplicaContext:
    """What the front door needs to see of the replica: the batcher,
    the metrics registry, and the (lock-guarded) serving-weights
    identity. ``replica.py`` owns the mutation side."""

    def __init__(self, batcher, metrics, worker_id=0,
                 request_deadline=10.0):
        self.batcher = batcher
        self.metrics = metrics
        self.worker_id = int(worker_id)
        self.request_deadline = float(request_deadline)
        self._lock = threading.Lock()
        self._step = -1
        self._crc = None
        self._draining = False
        self.started = time.monotonic()

    # -- weights identity (set by replica.py under its flip lock) ------
    def set_weights(self, step, crc):
        with self._lock:
            self._step, self._crc = int(step), crc

    def weights(self):
        with self._lock:
            return self._step, self._crc

    def begin_drain(self):
        with self._lock:
            self._draining = True

    @property
    def draining(self):
        with self._lock:
            return self._draining

    def view(self):
        """The /serve per-replica document. Every field rides the same
        mixed-version tolerance contract as the summary wire: readers
        render '-' for anything absent, so fields only ever get ADDED
        here."""
        snap = self.metrics.snapshot()
        p50, p99 = self.metrics.latency_quantiles()
        step, crc = self.weights()
        c = snap["counters"]
        return {
            "state": "draining" if self.draining else "serving",
            "replica": self.worker_id,
            "uptime_seconds": time.monotonic() - self.started,
            "model_step": step,
            "weights_crc": crc,
            "queue_depth": snap["gauges"]["serve_queue_depth"],
            "inflight": snap["gauges"]["serve_inflight"],
            "requests_total": c["serve_requests_total"],
            "responses_total": c["serve_responses_total"],
            "batches_total": c["serve_batches_total"],
            "rejects_total": c["serve_rejects_total"],
            "errors_total": c["serve_errors_total"],
            "cancelled_total": c["serve_cancelled_total"],
            "frame_corrupt_total": c["serve_frame_corrupt_total"],
            "swaps_total": c["serve_swaps_total"],
            "swap_rejects_total": c["serve_swap_rejects_total"],
            "swap_aborts_total": c["serve_swap_aborts_total"],
            "p50_ms": None if p50 is None else round(p50 * 1e3, 3),
            "p99_ms": None if p99 is None else round(p99 * 1e3, 3),
        }


def _make_handler(ctx):
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_GET(self):
            path = self.path.split("?")[0].rstrip("/") or "/"
            try:
                if path == "/healthz":
                    self._json(200, {"ok": True,
                                     "draining": ctx.draining,
                                     "replica": ctx.worker_id})
                elif path == "/serve":
                    self._json(200, ctx.view())
                elif path in ("/", "/metrics"):
                    self._reply(200, render_prometheus(ctx.metrics),
                                "text/plain; version=0.0.4; "
                                "charset=utf-8")
                else:
                    self._json(404, {"error": "not found",
                                     "cause": "not-found"})
            except Exception as e:  # a scrape must never kill serving
                self._best_effort_error(e)

        def do_POST(self):
            path = self.path.split("?")[0].rstrip("/")
            if path != "/infer":
                self._json(404, {"error": "not found",
                                 "cause": "not-found"})
                return
            try:
                self._infer()
            except Exception as e:
                self._best_effort_error(e)

        def _infer(self):
            if ctx.draining:
                # Prompt, cause-named, re-queueable: the client takes
                # this to a surviving replica (never silently dropped).
                ctx.metrics.inc("serve_rejects_total")
                self._json(503, {"error": "replica draining",
                                 "cause": "draining"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                doc = json.loads(self.rfile.read(length).decode("utf-8"))
                x = doc["x"]
                rid = str(doc.get("id", ""))
            except (ValueError, KeyError, UnicodeDecodeError) as e:
                self._json(400, {"error": "bad request: %s" % e,
                                 "cause": "bad-request"})
                return
            try:
                ticket = ctx.batcher.submit(rid, x)
            except QueueFull as e:
                self._json(503, {"error": str(e), "cause": "overload"})
                return
            except (TypeError, ValueError) as e:
                self._json(400, {"error": "bad input tensor: %s" % e,
                                 "cause": "bad-request"})
                return
            if not ticket.event.wait(ctx.request_deadline):
                # Mark the ticket abandoned so the batch loop drops it
                # instead of computing an answer nobody is waiting for
                # (expired requests must not keep amplifying overload).
                ticket.cancel()
                ctx.metrics.inc("serve_errors_total")
                self._json(504, {"error": "request deadline (%.1fs) "
                                          "expired in the batch queue"
                                          % ctx.request_deadline,
                                 "cause": "deadline"})
                return
            if ticket.error is not None:
                code = 503 if ticket.cause in _RETRYABLE else 500
                self._json(code, {"error": ticket.error,
                                  "cause": ticket.cause})
                return
            # The batch loop stamped the EXACT weights identity the
            # forward used; ctx.weights() is only the startup fallback.
            step, crc = ctx.weights()
            if ticket.weights_crc is not None:
                step, crc = ticket.model_step, ticket.weights_crc
            self._json(200, {
                "id": rid,
                "y": [float(v) for v in ticket.response],
                "model_step": step,
                "weights_crc": crc,
                "replica": ctx.worker_id,
            })

        def _best_effort_error(self, e):
            try:
                self._json(500, {"error": "internal: %s" % e,
                                 "cause": "internal"})
            except Exception:
                pass  # client already gone; the replica serves on

        def _json(self, code, doc):
            self._reply(code, json.dumps(doc), "application/json")

        def _reply(self, code, body, ctype):
            data = body.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, fmt, *args):
            pass  # request logs ride the metrics plane, not stderr

    return Handler


def start_front_door(port, ctx):
    """Binds the replica's HTTP server; returns (httpd, actual_port).
    Port 0 binds ephemeral (tests)."""
    from http.server import ThreadingHTTPServer

    httpd = ThreadingHTTPServer(("0.0.0.0", port), _make_handler(ctx))
    httpd.daemon_threads = True
    thread = threading.Thread(target=httpd.serve_forever,
                              name="hvd-serve-http", daemon=True)
    thread.start()
    return httpd, httpd.server_address[1]
