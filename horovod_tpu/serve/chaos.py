"""Seeded chaos for the serving plane (docs/CHAOS.md, docs/SERVE.md).

``HVD_TPU_SERVE_CHAOS_SPEC`` grammar, mirroring the fleet schedule's
(semicolon-separated clauses, deterministic under ``seed=``)::

    seed=7;corrupt_batch=3            # flip a byte in the 3rd batch frame
    seed=7;corrupt_batch=3,5          # ...and the 5th
    seed=23;kill_after=2.0            # supervisor-side: SIGKILL a random
                                      # replica 2s into the run

``corrupt_batch`` acts INSIDE the replica, between frame assembly and
the per-row CRC verification — the injected bitflip must surface as a
cause-named per-request failure (`frame-corrupt`), never as a corrupt
answer. ``kill_after`` is consumed by the supervisor/test harness (the
replica cannot SIGKILL itself mid-request from outside the request
path); the elastic driver's respawn + the client's re-queue then have
to deliver the invariant end to end.
"""

import os
import random


class ServeChaos:
    def __init__(self, seed=0, corrupt_batches=(), kill_after=None):
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self.corrupt_batches = set(int(b) for b in corrupt_batches)
        self.kill_after = kill_after
        self._batches_seen = 0
        self.corrupted = 0

    @classmethod
    def from_env(cls, env=None):
        spec = (env or os.environ).get("HVD_TPU_SERVE_CHAOS_SPEC", "")
        if not spec.strip():
            return None
        return cls.parse(spec)

    @classmethod
    def parse(cls, spec):
        seed, corrupt, kill_after = 0, (), None
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            key, _, value = clause.partition("=")
            key = key.strip()
            value = value.strip()
            if key == "seed":
                seed = int(value)
            elif key == "corrupt_batch":
                corrupt = [int(v) for v in value.split(",") if v]
            elif key == "kill_after":
                kill_after = float(value)
            else:
                raise ValueError(
                    "unknown serve chaos clause %r (grammar: seed=N;"
                    "corrupt_batch=N[,M];kill_after=SECONDS)" % key)
        return cls(seed=seed, corrupt_batches=corrupt,
                   kill_after=kill_after)

    def maybe_corrupt_frame(self, frame, rows=None):
        """Called by the batcher on every assembled frame (1-indexed
        count); flips one byte of a scheduled frame in place. ``rows``
        bounds the flip to the occupied rows — flipping pad bytes would
        be chaos nobody can observe."""
        self._batches_seen += 1
        if self._batches_seen not in self.corrupt_batches:
            return False
        occupied = frame[:rows] if rows else frame
        flat = occupied.reshape(-1).view("uint8")
        pos = self.rng.randrange(len(flat))
        flat[pos] ^= 0xFF
        self.corrupted += 1
        return True
