"""hvd-serve: the inference serving plane on the trained chip pool
(docs/SERVE.md; ROADMAP "new traffic shapes" item 3).

Everything this package moves is a REQUEST, not a gradient — but every
structural part is a training part reused:

* a **replica** (``replica.py``) is one worker process spawned by the
  elastic driver (standalone via ``bin/hvd-serve``, or co-tenant under
  the fleet controller as a ``JobSpec`` with ``kind: "serve"``). It
  loads weights from a durable checkpoint lineage
  (``elastic/durable.py``), runs a jitted forward pass, and fronts it
  with a stdlib-only HTTP/JSON server (the same ThreadingHTTPServer
  pattern as ``_metrics.py``). Replicas are INDEPENDENT — no collective
  ever runs on the request path (``hvd-lint`` rule
  ``collective-in-serve-handler`` makes that an ERROR);
* **continuous micro-batching** (``batcher.py``): a bounded admission
  queue feeds a size/deadline-bounded batcher that pads each batch up
  to a power-of-two bucket (bounded XLA recompiles), then splits the
  outputs back to their requests;
* **rolling weight swap** (``swap.py``): a background watcher on the
  checkpoint lineage loads a newer VALID manifest into a shadow buffer
  and flips the serving weights between batches — never mid-batch, so
  a swap drops zero requests; torn/CRC-invalid manifests are rejected
  (``serve_swap_rejects_total``) and the replica keeps serving the
  current weights;
* **drain-native**: replicas poll the driver's drain record
  (``elastic/run.py::drain_requested``), stop admitting (clients are
  told the cause and re-queue to a surviving replica via
  ``client.py``), finish the queue, and exit ``EXIT_DRAINED`` — the
  same protocol training preemption uses, so fleet co-tenancy
  composes unchanged.

The metrics registry (``metrics.py``) mirrors the fleet plane's;
``hvd-top --serve`` renders the supervisor's aggregated ``/serve``
view.
"""

from .batcher import MicroBatcher, QueueFull, Ticket  # noqa: F401
from .client import ServeClient, ServeError  # noqa: F401
from .metrics import ServeMetrics, histogram_quantile  # noqa: F401
from .model import (fingerprint, forward, init_leaves,  # noqa: F401
                    make_forward)
from .swap import SwapWatcher  # noqa: F401
