"""Rolling weight swap: the durable-lineage watcher (docs/SERVE.md).

A background thread polls the checkpoint directory for a manifest NEWER
than the serving step. Candidates are walked newest-first and validated
DEEPLY (manifest parse + every shard's byte size and CRC32C) before a
single byte reaches the serving path: a torn manifest or a flipped bit
counts one ``serve_swap_rejects_total`` (once per offending directory,
not once per poll) and the scan falls through to the next-older
candidate — the replica keeps serving its current weights, never a
half-loaded set.

A valid candidate is loaded into a SHADOW buffer (fresh leaves + a
fresh jitted forward closure) off the request path, then flipped in by
one reference swap between batches — in-flight batches finish on the
old closure, so a swap drops zero requests by construction. Replicas
stagger their flips (``stagger * worker_id`` seconds) so a fleet of
replicas rolls one at a time and a poisoned-but-valid checkpoint never
takes the whole pool down in the same instant.

A drain beats a swap: once the replica is draining, a pending shadow is
abandoned (``serve_swap_aborts_total``) — the remaining queue finishes
on the weights it was admitted under, and the next incarnation of the
replica loads the new lineage at startup anyway.
"""

import threading
import time

from horovod_tpu.elastic import durable

from . import model as _model


def publish_leaves(directory, step, leaves, generation=0):
    """Synchronously writes one complete single-shard checkpoint of
    ``leaves`` at ``step`` — the writer side the swap tests, the load
    bench, and ``hvd-serve --init-ckpt`` use to grow a lineage without
    running a training job."""
    ck = durable.DurableCheckpointer(directory, every_n_commits=1,
                                     rank=0, world_size=1)
    ck._generation = lambda: generation
    if not ck.maybe_enqueue(dict(leaves), step):
        raise RuntimeError("checkpoint at step %d was not due (lineage "
                           "already past it?)" % step)
    if not ck.flush(timeout=60):
        raise RuntimeError("checkpoint publish at step %d timed out"
                           % step)
    return durable.last_durable_step(directory)[0]


class SwapWatcher(threading.Thread):
    """Watches ``ckpt_dir``; calls ``flip_fn(step, leaves, crc)`` with
    a validated newer weight set. ``current_step_fn`` reports the
    serving step; ``draining_fn`` gates the flip (and the load)."""

    def __init__(self, ckpt_dir, template, current_step_fn, flip_fn,
                 metrics=None, draining_fn=None, interval=0.5,
                 stagger=0.0, verbose=False):
        super().__init__(name="hvd-serve-swap", daemon=True)
        self.ckpt_dir = ckpt_dir
        self.template = template
        self.current_step_fn = current_step_fn
        self.flip_fn = flip_fn
        self.metrics = metrics
        self.draining_fn = draining_fn or (lambda: False)
        self.interval = float(interval)
        self.stagger = float(stagger)
        self._stop = threading.Event()
        self._rejected = set()  # ckpt dirs already counted invalid
        self._verbose = verbose
        self.swaps = 0
        self.rejects = 0
        self.aborts = 0

    def stop(self):
        self._stop.set()

    def _log(self, msg):
        if self._verbose:
            import sys
            sys.stderr.write("[serve-swap] %s\n" % msg)
            sys.stderr.flush()

    def poll_once(self):
        """One watcher step (directly callable from tests): scan, deep-
        validate, shadow-load, flip. Returns the step flipped to, or
        None."""
        if self.draining_fn():
            return None
        current = self.current_step_fn()
        candidate = None
        for step, gen, path in durable.list_checkpoints(self.ckpt_dir):
            if step <= current:
                break  # newest-first: everything below is old news
            manifest = durable.validate_manifest(path, deep=True)
            if manifest is None:
                if path not in self._rejected:
                    self._rejected.add(path)
                    self.rejects += 1
                    if self.metrics is not None:
                        self.metrics.inc("serve_swap_rejects_total")
                    self._log("rejecting torn/CRC-invalid checkpoint %s "
                              "(step %d); serving current weights"
                              % (path, step))
                continue  # fall back to the next-older candidate
            candidate = (step, path, manifest)
            break
        if candidate is None:
            return None
        step, path, manifest = candidate
        try:
            raw = durable.load_leaves(manifest, path, verify=True)
        except (OSError, ValueError) as e:
            # The shard changed between validate and load (a racing
            # retention pass, or a fault injector): same contract as an
            # invalid manifest.
            if path not in self._rejected:
                self._rejected.add(path)
                self.rejects += 1
                if self.metrics is not None:
                    self.metrics.inc("serve_swap_rejects_total")
                self._log("rejecting checkpoint %s at load time: %s"
                          % (path, e))
            return None
        leaves = _model.extract_leaves(raw, self.template)
        if leaves is None:
            if path not in self._rejected:
                self._rejected.add(path)
                self.rejects += 1
                if self.metrics is not None:
                    self.metrics.inc("serve_swap_rejects_total")
                self._log("checkpoint %s (step %d) has no usable model "
                          "leaves; serving current weights"
                          % (path, step))
            return None
        # Shadow is ready. Staggered flip: replicas roll one at a time.
        if self.stagger > 0 and self._stop.wait(self.stagger):
            return None
        if self.draining_fn():
            # Drain won the race: the queue finishes on the weights it
            # was admitted under; the shadow is dropped on the floor.
            self.aborts += 1
            if self.metrics is not None:
                self.metrics.inc("serve_swap_aborts_total")
            self._log("abandoning loaded swap to step %d: replica is "
                      "draining" % step)
            return None
        crc = _model.fingerprint(leaves)
        self.flip_fn(step, leaves, crc)
        self.swaps += 1
        if self.metrics is not None:
            self.metrics.inc("serve_swaps_total")
            self.metrics.set_gauge("serve_model_step", step)
        self._log("swapped to step %d (weights %s)" % (step, crc))
        return step

    def run(self):
        while not self._stop.wait(self.interval):
            try:
                self.poll_once()
            except Exception as e:  # the watcher must never kill serving
                self._log("watcher error (serving continues): %s" % e)
