"""The serve-pool supervisor: ``bin/hvd-serve`` (docs/SERVE.md).

Reuses the ELASTIC DRIVER as the replica process manager — a serve
pool is "an elastic job whose workers never rendezvous": the driver
spawns ``python -m horovod_tpu.serve.replica`` per slot, respawns
SIGKILLed replicas (with the host-blacklist cooldown), and runs the
same graceful-drain protocol (drain record in the rendezvous KV,
``EXIT_DRAINED`` keeps a host off the blacklist). Replica count is
steered entirely through :meth:`ElasticDriver.resize` — the driver
auto-grows toward the ceiling whenever discovery shows capacity, so
autoscaling is "move the ceiling" and nothing else.

The supervisor adds the pool-level view: an aggregated ``/serve``
status endpoint (what ``hvd-top --serve`` renders), a queue-pressure
autoscaler, and endpoint discovery (each replica listens on
``port_base + worker_id`` on the host the driver placed it on, read
from the driver's worker records — multi-host ``-H`` inventories
resolve to reachable endpoints).
"""

import argparse
import json
import os
import signal
import sys
import threading
import time
import urllib.request

from horovod_tpu.elastic.discovery import FixedHosts
from horovod_tpu.elastic.driver import ElasticDriver
from horovod_tpu.run import util


def _fetch_json(url, timeout=1.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


class ServeSupervisor:
    def __init__(self, command, hosts, min_replicas=1, max_replicas=1,
                 np_initial=None, port_base=9500, env=None,
                 start_timeout=2.0, drain_grace=None,
                 scale_up_queue=4.0, scale_down_idle=10.0,
                 autoscale_interval=0.5, verbose=False):
        self.port_base = int(port_base)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_queue = float(scale_up_queue)
        self.scale_down_idle = float(scale_down_idle)
        self.autoscale_interval = float(autoscale_interval)
        self.verbose = verbose
        self.started = time.monotonic()
        self.scale_events = []   # [{"t", "from", "to", "reason"}]
        self._idle_since = None
        self._stop = threading.Event()
        np0 = int(np_initial if np_initial is not None
                  else self.min_replicas)
        # start_timeout is SHORT by design: serve replicas never
        # rendezvous, so a size>1 generation only "resolves" by
        # stalling — a long timeout would freeze the growth gate.
        self.driver = ElasticDriver(
            command, FixedHosts(hosts),
            min_np=1, max_np=np0, np_initial=np0,
            start_timeout=start_timeout, verbose=verbose, env=env,
            drain_grace=drain_grace, placement="spread")

    def _log(self, msg):
        if self.verbose:
            sys.stderr.write("[hvd-serve] %s\n" % msg)
            sys.stderr.flush()

    # -- pool introspection -------------------------------------------
    def _replica_addrs(self):
        """[(worker id, "host:port")] from the driver's worker records
        — the HOST each replica actually landed on (-H accepts
        multi-host inventories), with local spellings normalized to
        the loopback the replica's listener is certainly reachable
        on."""
        addrs = []
        for wid, host in sorted(self.driver.worker_hosts().items()):
            if util.is_local_host(host):
                host = "127.0.0.1"
            addrs.append((wid, "%s:%d" % (host, self.port_base + wid)))
        return addrs

    def endpoints(self):
        return [addr for _, addr in self._replica_addrs()]

    def replica_views(self, timeout=1.0):
        """Per-replica /serve documents for every reachable replica."""
        views = []
        for _, addr in self._replica_addrs():
            try:
                views.append(_fetch_json("http://%s/serve" % addr,
                                         timeout=timeout))
            except Exception:
                continue  # booting or dying; the pool view skips it
        return views

    def view(self):
        """The aggregated /serve document (the ``hvd-top --serve``
        wire). Counters SUM across replicas; latency quantiles take the
        pool-pessimal (max) replica; every field is add-only under the
        mixed-version tolerance contract."""
        views = self.replica_views()
        agg = {
            "kind": "serve-pool",
            "uptime_seconds": time.monotonic() - self.started,
            "replicas": len(self.driver.live_workers()),
            "replicas_reporting": len(views),
            "replicas_min": self.min_replicas,
            "replicas_max": self.max_replicas,
            "scale_events": len(self.scale_events),
            "endpoints": self.endpoints(),
        }
        for field in ("requests_total", "responses_total",
                      "batches_total", "rejects_total", "errors_total",
                      "cancelled_total",
                      "frame_corrupt_total", "swaps_total",
                      "swap_rejects_total", "swap_aborts_total",
                      "queue_depth", "inflight"):
            agg[field] = sum(int(v.get(field) or 0) for v in views)
        for field in ("p50_ms", "p99_ms"):
            vals = [v[field] for v in views
                    if v.get(field) is not None]
            agg[field] = max(vals) if vals else None
        steps = [v.get("model_step") for v in views
                 if v.get("model_step") is not None]
        agg["model_step"] = max(steps) if steps else None
        agg["model_steps"] = sorted(set(steps))
        agg["draining"] = sum(1 for v in views
                              if v.get("state") == "draining")
        agg["per_replica"] = views
        return agg

    # -- autoscaling --------------------------------------------------
    def _record_scale(self, old, new, reason):
        self.scale_events.append({
            "t": round(time.monotonic() - self.started, 3),
            "from": old, "to": new, "reason": reason})
        self._log("autoscale %d -> %d (%s)" % (old, new, reason))

    def autoscale_once(self):
        """One autoscaler tick: queue pressure raises the replica
        ceiling one step; a sustained-idle pool lowers it by draining
        the highest replica (the driver does not regrow past the
        lowered ceiling). Returns the ceiling delta (-1/0/+1)."""
        views = self.replica_views(timeout=0.5)
        live = len(self.driver.live_workers())
        if not views or live == 0:
            return 0
        depth = sum(int(v.get("queue_depth") or 0) for v in views)
        pressure = depth / max(1, len(views))
        if pressure >= self.scale_up_queue and live < self.max_replicas:
            self._idle_since = None
            self.driver.resize(live + 1)
            self._record_scale(live, live + 1,
                               "queue pressure %.1f/replica" % pressure)
            return 1
        if depth == 0 and live > self.min_replicas:
            now = time.monotonic()
            if self._idle_since is None:
                self._idle_since = now
            elif now - self._idle_since >= self.scale_down_idle:
                self._idle_since = None
                victim = max(self.driver.live_workers())
                self.driver.resize(live - 1)
                self.driver.request_drain([victim])
                self._record_scale(live, live - 1,
                                   "idle %.0fs" % self.scale_down_idle)
                return -1
        else:
            self._idle_since = None
        return 0

    def _autoscale_loop(self):
        while not self._stop.wait(self.autoscale_interval):
            try:
                self.autoscale_once()
            except Exception as e:
                self._log("autoscale tick failed (pool serves on): %s"
                          % e)

    # -- status front door --------------------------------------------
    def start_status_server(self, port):
        """Aggregated /serve + /healthz on ``port`` (0 = ephemeral).
        Same ThreadingHTTPServer discipline as the replicas'."""
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)
        sup = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_GET(self):
                path = self.path.split("?")[0].rstrip("/") or "/"
                try:
                    if path in ("/", "/serve"):
                        doc = sup.view()
                    elif path == "/healthz":
                        doc = {"ok": True,
                               "replicas": len(
                                   sup.driver.live_workers())}
                    else:
                        self._json(404, {"error": "not found"})
                        return
                    self._json(200, doc)
                except Exception as e:
                    try:
                        self._json(500, {"error": str(e)})
                    except Exception:
                        pass

            def _json(self, code, doc):
                data = json.dumps(doc).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, fmt, *args):
                pass

        httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        httpd.daemon_threads = True
        threading.Thread(target=httpd.serve_forever,
                         name="hvd-serve-status", daemon=True).start()
        return httpd, httpd.server_address[1]

    # -- lifecycle ----------------------------------------------------
    def run(self, status_port=None, autoscale=True):
        """Blocks serving the pool; returns the driver's exit code.
        SIGTERM/SIGINT drain the whole pool gracefully."""
        if status_port is not None:
            _, actual = self.start_status_server(status_port)
            self._log("status endpoint on :%d" % actual)
        if autoscale:
            threading.Thread(target=self._autoscale_loop,
                             name="hvd-serve-autoscale",
                             daemon=True).start()
        try:
            rc = self.driver.run(install_signal_handlers=True)
        finally:
            self._stop.set()
        return rc

    def shutdown(self, grace=None):
        self._stop.set()
        self.driver.request_drain("all", grace=grace)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="hvd-serve",
        description="Serve a model from a durable checkpoint lineage "
                    "on a pool of replicas (docs/SERVE.md).")
    ap.add_argument("-np", "--np", type=int, default=1,
                    help="initial replica count")
    ap.add_argument("--min-np", type=int, default=None)
    ap.add_argument("--max-np", type=int, default=None,
                    help="autoscale ceiling (default: -np)")
    ap.add_argument("-H", "--hosts", default=None,
                    help="host:slots[,host:slots...] "
                         "(default: localhost:<max-np>)")
    ap.add_argument("--model", default="affine")
    ap.add_argument("--dim", type=int, default=8)
    ap.add_argument("--ckpt-dir", default=os.environ.get(
        "HVD_TPU_CKPT_DIR"))
    ap.add_argument("--port-base", type=int, default=9500)
    ap.add_argument("--status-port", type=int, default=9499,
                    help="aggregated /serve endpoint (hvd-top --serve)")
    ap.add_argument("--no-autoscale", action="store_true")
    ap.add_argument("--scale-up-queue", type=float, default=4.0,
                    help="mean queue depth per replica that adds one")
    ap.add_argument("--scale-down-idle", type=float, default=10.0,
                    help="seconds of empty queues before dropping one")
    ap.add_argument("--drain-grace", type=float, default=None)
    ap.add_argument("--exit-after", type=float, default=0,
                    help="forwarded to replicas (test/bench knob)")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)

    max_np = args.max_np if args.max_np is not None else args.np
    min_np = args.min_np if args.min_np is not None else min(
        args.np, max_np)
    hosts = args.hosts or ("localhost:%d" % max_np)
    env = dict(os.environ)
    env["HVD_TPU_SERVE_MODEL"] = args.model
    env["HVD_TPU_SERVE_DIM"] = str(args.dim)
    env["HVD_TPU_SERVE_PORT"] = str(args.port_base)
    if args.ckpt_dir:
        env["HVD_TPU_CKPT_DIR"] = args.ckpt_dir
    if args.exit_after:
        env["HVD_TPU_SERVE_EXIT_AFTER"] = str(args.exit_after)
    command = [sys.executable, "-m", "horovod_tpu.serve.replica"]
    sup = ServeSupervisor(
        command, hosts, min_replicas=min_np, max_replicas=max_np,
        np_initial=args.np, port_base=args.port_base, env=env,
        drain_grace=args.drain_grace,
        scale_up_queue=args.scale_up_queue,
        scale_down_idle=args.scale_down_idle, verbose=args.verbose)
    return sup.run(status_port=args.status_port,
                   autoscale=not args.no_autoscale)


if __name__ == "__main__":
    sys.exit(main())
