"""One serve replica: ``python -m horovod_tpu.serve.replica``
(docs/SERVE.md).

Spawned by the elastic driver (``bin/hvd-serve`` standalone, or a
fleet ``JobSpec`` with ``kind: "serve"``) exactly like a training
worker: ``HVD_TPU_WORKER_ID`` names it, ``HVD_TPU_RENDEZVOUS_ADDR``
reaches the driver's KV (drain records), ``HVD_TPU_CKPT_DIR`` points
at the durable lineage. Its HTTP port is ``port_base + worker_id`` —
deterministic, so the supervisor and clients compute endpoints instead
of needing a registry.

Thread model (docs/DESIGN.md diagram):

* HTTP handler threads admit requests into the bounded queue and park
  on ticket events;
* the MAIN thread runs the batch loop: take a size/deadline-bounded
  batch, run the jitted forward, split responses — and, between
  batches, poll the drain record (rate-limited local KV read, NO
  collective: replicas are independent by design);
* the swap watcher thread shadow-loads newer valid lineage manifests
  and flips the forward closure under ``_flip_lock``, between batches.

Drain (preemption, shutdown, SIGTERM): stop admitting — every new
request gets a prompt, cause-named 503 the client re-queues elsewhere —
finish the queue, exit ``EXIT_DRAINED``. In-flight work is never
silently dropped.
"""

import argparse
import os
import signal
import sys
import threading
import time

from horovod_tpu.elastic import durable
from horovod_tpu.elastic.run import drain_requested
from horovod_tpu.elastic.state import EXIT_DRAINED

from horovod_tpu.trace import emit as trace_emit

from . import model as _model
from .batcher import MicroBatcher
from .chaos import ServeChaos
from .metrics import ServeMetrics
from .server import ReplicaContext, start_front_door
from .swap import SwapWatcher


def make_parser():
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.serve.replica",
        description="One hvd-serve replica (normally spawned by "
                    "bin/hvd-serve or a fleet kind:serve job).")
    ap.add_argument("--model", default=os.environ.get(
        "HVD_TPU_SERVE_MODEL", "affine"))
    ap.add_argument("--dim", type=int, default=int(os.environ.get(
        "HVD_TPU_SERVE_DIM", "8")))
    ap.add_argument("--port-base", type=int, default=int(os.environ.get(
        "HVD_TPU_SERVE_PORT", "9500")))
    ap.add_argument("--ckpt-dir", default=os.environ.get(
        "HVD_TPU_CKPT_DIR"))
    ap.add_argument("--max-batch", type=int, default=int(os.environ.get(
        "HVD_TPU_SERVE_MAX_BATCH", "16")))
    ap.add_argument("--max-delay-ms", type=float,
                    default=float(os.environ.get(
                        "HVD_TPU_SERVE_MAX_DELAY_MS", "5")))
    ap.add_argument("--queue-max", type=int, default=int(os.environ.get(
        "HVD_TPU_SERVE_QUEUE_MAX", "256")))
    ap.add_argument("--request-deadline", type=float,
                    default=float(os.environ.get(
                        "HVD_TPU_SERVE_REQUEST_DEADLINE", "10")))
    ap.add_argument("--swap-interval", type=float,
                    default=float(os.environ.get(
                        "HVD_TPU_SERVE_SWAP_INTERVAL", "0.5")))
    ap.add_argument("--swap-stagger", type=float,
                    default=float(os.environ.get(
                        "HVD_TPU_SERVE_SWAP_STAGGER", "0.25")))
    ap.add_argument("--exit-after", type=float, default=float(
        os.environ.get("HVD_TPU_SERVE_EXIT_AFTER", "0")),
        help="test/bench knob: exit 0 after N seconds of serving "
             "(0 = serve forever)")
    ap.add_argument("--verbose", action="store_true", default=bool(
        os.environ.get("HVD_TPU_SERVE_VERBOSE")))
    return ap


class Replica:
    def __init__(self, args):
        self.args = args
        self.wid = int(os.environ.get("HVD_TPU_WORKER_ID", "0"))
        self.metrics = ServeMetrics()
        self.chaos = ServeChaos.from_env()
        self.batcher = MicroBatcher(
            max_batch=args.max_batch,
            max_delay=args.max_delay_ms / 1e3,
            queue_max=args.queue_max,
            metrics=self.metrics, chaos=self.chaos)
        self.ctx = ReplicaContext(self.batcher, self.metrics,
                                  worker_id=self.wid,
                                  request_deadline=args.request_deadline)
        self._flip_lock = threading.Lock()
        self._drain_seen = False
        self._last_drain_poll = 0.0
        self.template = _model.init_leaves(args.model, args.dim)
        self.step = -1
        self.leaves = None
        self.crc = None
        self.forward = None
        self.httpd = None
        self.port = None
        self.watcher = None
        self._trace = trace_emit.shard_for("serve_r%d" % self.wid,
                                           rank=self.wid)

    def _log(self, msg):
        sys.stderr.write("[serve %d] %s\n" % (self.wid, msg))
        sys.stderr.flush()

    # -- weights ------------------------------------------------------
    def _flip(self, step, leaves, crc):
        """Installs a weight set (initial load and every swap). One
        reference swap under the lock; in-flight batches finish on the
        closure they snapshotted."""
        fwd = _model.make_forward(self.args.model, leaves)
        with self._flip_lock:
            self.step, self.leaves, self.crc = step, leaves, crc
            self.forward = fwd
        self.ctx.set_weights(step, crc)
        self.metrics.set_gauge("serve_model_step", step)

    def _snapshot_forward(self):
        with self._flip_lock:
            return self.forward, (self.step, self.crc)

    def _load_initial(self):
        ckpt_dir = self.args.ckpt_dir
        if ckpt_dir and os.path.isdir(ckpt_dir):
            manifest, path = durable.latest_valid_manifest(ckpt_dir,
                                                           deep=True)
            if manifest is not None:
                try:
                    raw = durable.load_leaves(manifest, path,
                                              verify=True)
                    leaves = _model.extract_leaves(raw, self.template)
                    if leaves is not None:
                        step = int(manifest.get("step", 0))
                        self._flip(step, leaves,
                                   _model.fingerprint(leaves))
                        self._log("serving lineage step %d (weights %s)"
                                  % (step, self.crc))
                        return
                except (OSError, ValueError) as e:
                    self._log("lineage load failed (%s); serving "
                              "initial weights" % e)
        leaves = _model.init_leaves(self.args.model, self.args.dim)
        self._flip(0, leaves, _model.fingerprint(leaves))
        self._log("no usable lineage; serving initial weights (%s)"
                  % self.crc)

    # -- drain --------------------------------------------------------
    def _begin_drain(self, why):
        if self._drain_seen:
            return
        self._drain_seen = True
        self.ctx.begin_drain()
        self.batcher.close()
        self.metrics.inc("serve_drains_total")
        self.metrics.set_gauge("serve_draining", 1)
        self._log("draining (%s): admission closed, finishing %d "
                  "queued request(s)" % (why, self.batcher.depth()))

    def _poll_drain(self):
        now = time.monotonic()
        if now - self._last_drain_poll < 0.2:
            return
        self._last_drain_poll = now
        if drain_requested():
            self._begin_drain("drain record published")

    # -- main loop ----------------------------------------------------
    def serve(self):
        self._load_initial()
        self.httpd, self.port = start_front_door(
            self.args.port_base + self.wid, self.ctx)
        self._log("front door on :%d (model %s dim %d, max_batch %d, "
                  "max_delay %.1fms)"
                  % (self.port, self.args.model, self.args.dim,
                     self.args.max_batch, self.args.max_delay_ms))
        if self.args.ckpt_dir:
            self.watcher = SwapWatcher(
                self.args.ckpt_dir, self.template,
                current_step_fn=lambda: self.step,
                flip_fn=self._flip, metrics=self.metrics,
                draining_fn=lambda: self._drain_seen,
                interval=self.args.swap_interval,
                stagger=self.args.swap_stagger * self.wid,
                verbose=self.args.verbose)
            self.watcher.start()

        signal.signal(signal.SIGTERM,
                      lambda s, f: self._begin_drain("SIGTERM"))
        deadline = (time.monotonic() + self.args.exit_after
                    if self.args.exit_after > 0 else None)
        while True:
            self._poll_drain()
            tickets = self.batcher.next_batch(timeout=0.05)
            if tickets:
                fwd, stamp = self._snapshot_forward()
                # Per-request span (docs/TRACING.md): one "serve.batch"
                # span per forward into this replica's own trace shard,
                # so hvd-trace merges serve latency next to the training
                # plane's spans. No-op unless HVD_TPU_TRACE_DIR is set.
                span_start = trace_emit.now_ns()
                self.batcher.run_batch(fwd, tickets, stamp=stamp)
                self._trace.span("serve.batch", span_start,
                                 trace_emit.now_ns(),
                                 nbytes=len(tickets), cycle=self.step)
                continue
            if self._drain_seen:
                # Queue flushed (next_batch returned empty after
                # close()): the drain contract is met.
                break
            if deadline is not None and time.monotonic() > deadline:
                self._log("exit-after deadline reached; serving done")
                return 0
        if self.watcher is not None:
            self.watcher.stop()
        self._log("drained cleanly; exiting EXIT_DRAINED")
        return EXIT_DRAINED


def main(argv=None):
    args = make_parser().parse_args(argv)
    return Replica(args).serve()


if __name__ == "__main__":
    sys.exit(main())
