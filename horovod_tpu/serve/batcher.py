"""Continuous micro-batching: admission queue -> size/deadline-bounded
batches -> responses split back to their requests (docs/SERVE.md).

The policy, in one paragraph: a request is admitted into a BOUNDED
queue (full queue = immediate cause-named reject — backpressure must
reach the client, not grow an invisible latency tail). The batch loop
takes up to ``max_batch`` requests, but never waits longer than
``max_delay`` after the oldest admitted request — latency is bounded by
policy, not by traffic. The assembled frame is padded up to the next
power-of-two bucket (one XLA compile per bucket, ever, instead of one
per distinct batch size), the forward runs, and each row of the output
lands in its request's ticket.

Integrity: every ticket carries the CRC32C of its input row taken at
ADMISSION; assembly re-verifies each row after the (chaos-injectable)
frame copy. A corrupt row fails exactly that request with a prompt,
cause-named error — the framework invariant "a correct answer or a
named failure, never silent corruption" applied to the serving plane.
"""

import threading
import time

import numpy as np

from horovod_tpu.elastic import durable

BUCKETS = (1, 2, 4, 8, 16, 32, 64)


class QueueFull(Exception):
    """Admission refused: the queue is at its bound."""


def bucket_for(n, max_batch):
    """Smallest power-of-two bucket >= n (capped at max_batch)."""
    for b in BUCKETS:
        if b >= n:
            return min(b, max_batch)
    return max_batch


class Ticket:
    """One admitted request: the handler thread parks on ``event``
    until the batch loop fills ``response`` or ``error``."""

    __slots__ = ("rid", "x", "crc", "admitted", "event", "response",
                 "error", "cause", "model_step", "weights_crc",
                 "cancelled")

    def __init__(self, rid, x):
        self.rid = rid
        self.x = np.ascontiguousarray(x, dtype=np.float32)
        if self.x.ndim != 1:
            # Reject at ADMISSION: a non-flat row would only blow up
            # later inside the batch loop's frame assembly, where an
            # exception kills the whole replica, not one request.
            raise ValueError(
                "request x must be a flat vector, got shape %r"
                % (tuple(self.x.shape),))
        self.crc = durable.crc32c(self.x.tobytes())
        self.admitted = time.monotonic()
        self.event = threading.Event()
        self.response = None
        self.error = None
        self.cause = None
        self.model_step = None
        self.weights_crc = None
        self.cancelled = False

    def fail(self, cause, message):
        self.cause = cause
        self.error = message
        self.event.set()

    def cancel(self):
        """Marks the ticket abandoned (its handler already answered —
        deadline expiry). The batch loop drops cancelled tickets
        instead of spending a forward-pass row on them."""
        self.cancelled = True

    def finish(self, row, stamp=None):
        # The weights identity is stamped BEFORE the event fires: the
        # handler thread must never see an answer whose fingerprint a
        # concurrent swap already moved on from.
        if stamp is not None:
            self.model_step, self.weights_crc = stamp
        self.response = row
        self.event.set()


class MicroBatcher:
    def __init__(self, max_batch=16, max_delay=0.005, queue_max=256,
                 metrics=None, chaos=None):
        self.max_batch = int(max_batch)
        self.max_delay = float(max_delay)
        self.queue_max = int(queue_max)
        self.metrics = metrics
        self.chaos = chaos
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue = []
        self._closed = False

    # -- admission (HTTP handler threads) ------------------------------
    def submit(self, rid, x):
        """Admits one request; returns its Ticket. Raises QueueFull at
        the bound — the caller turns that into a prompt 503 so the
        client re-queues elsewhere instead of silently waiting."""
        ticket = Ticket(rid, x)
        with self._cond:
            if self._closed:
                raise QueueFull("replica draining")
            if len(self._queue) >= self.queue_max:
                if self.metrics is not None:
                    self.metrics.inc("serve_rejects_total")
                raise QueueFull(
                    "admission queue full (%d)" % self.queue_max)
            self._queue.append(ticket)
            if self.metrics is not None:
                self.metrics.inc("serve_requests_total")
                self.metrics.set_gauge("serve_queue_depth",
                                       len(self._queue))
            self._cond.notify()
        return ticket

    def depth(self):
        with self._lock:
            return len(self._queue)

    def close(self):
        """Stops admission (drain); queued tickets still get answered
        by the remaining batch-loop iterations."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- batch assembly (the replica's main loop) ----------------------
    def next_batch(self, timeout=0.1):
        """Blocks until a batch is ready: up to ``max_batch`` tickets,
        released early once ``max_delay`` has passed since the OLDEST
        ticket was admitted. Returns [] on timeout with an empty queue
        (the caller's chance to poll drain / shutdown)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while not self._queue:
                remain = deadline - time.monotonic()
                if remain <= 0 or self._closed:
                    if not self._queue:
                        return []
                    break
                self._cond.wait(remain)
            # Got at least one: wait out the batching window unless the
            # batch fills (or admission closed — drain flushes eagerly).
            release = self._queue[0].admitted + self.max_delay
            while (len(self._queue) < self.max_batch
                   and not self._closed):
                remain = release - time.monotonic()
                if remain <= 0:
                    break
                self._cond.wait(remain)
            # Purge deadline-abandoned tickets first: their handlers
            # already answered 504, so a forward row for them would
            # only amplify the overload that expired them.
            if any(t.cancelled for t in self._queue):
                kept = [t for t in self._queue if not t.cancelled]
                if self.metrics is not None:
                    self.metrics.inc("serve_cancelled_total",
                                     len(self._queue) - len(kept))
                self._queue = kept
            batch = self._queue[:self.max_batch]
            del self._queue[:len(batch)]
            if self.metrics is not None:
                self.metrics.observe("serve_queue_depth_sampled",
                                     len(self._queue))
                self.metrics.set_gauge("serve_queue_depth",
                                       len(self._queue))
            return batch

    def run_batch(self, forward_fn, tickets, stamp=None):
        """Assembles the padded frame, verifies per-row CRCs, runs the
        forward once, splits rows back to tickets (each stamped with
        ``stamp`` — the (step, weights_crc) identity of the weights the
        forward actually used). Never raises: every ticket ends
        answered, cause-named-failed, or dropped as cancelled (its
        handler already answered a deadline 504)."""
        live = [t for t in tickets if not t.cancelled]
        if self.metrics is not None and len(live) < len(tickets):
            self.metrics.inc("serve_cancelled_total",
                             len(tickets) - len(live))
        if not live:
            return
        dim = live[0].x.shape[-1] if live[0].x.ndim else 0
        bucket = bucket_for(len(live), self.max_batch)
        frame = np.zeros((bucket, dim), np.float32)
        ok = []
        for i, t in enumerate(live):
            if t.x.shape != (dim,):
                t.fail("shape",
                       "request shape %r does not match batch row "
                       "shape (%d,)" % (tuple(t.x.shape), dim))
                continue
            try:
                frame[i] = t.x
            except ValueError as e:
                t.fail("shape",
                       "request row does not fit the batch frame: %s"
                       % e)
                continue
            ok.append((i, t))
        if self.chaos is not None:
            self.chaos.maybe_corrupt_frame(frame, rows=len(live))
        # Integrity gate: the frame row must still be the bytes the
        # request was admitted with (catches the chaos bitflip and any
        # real copy bug between admission and the forward).
        verified = []
        for i, t in ok:
            row_crc = durable.crc32c(
                np.ascontiguousarray(frame[i]).tobytes())
            if row_crc != t.crc:
                if self.metrics is not None:
                    self.metrics.inc("serve_frame_corrupt_total")
                    self.metrics.inc("serve_errors_total")
                t.fail(
                    "frame-corrupt",
                    "batch frame corrupt (row crc %08x != admitted "
                    "%08x); request not computed" % (row_crc, t.crc))
            else:
                verified.append((i, t))
        if not verified:
            return
        if self.metrics is not None:
            self.metrics.add_gauge("serve_inflight", len(verified))
        try:
            out = forward_fn(frame)
        except Exception as e:
            for _, t in verified:
                if self.metrics is not None:
                    self.metrics.inc("serve_errors_total")
                t.fail("forward", "forward pass failed: %s" % e)
            return
        finally:
            if self.metrics is not None:
                self.metrics.add_gauge("serve_inflight", -len(verified))
        now = time.monotonic()
        for i, t in verified:
            t.finish(np.asarray(out[i]), stamp=stamp)
            if self.metrics is not None:
                self.metrics.inc("serve_responses_total")
                self.metrics.observe("serve_request_seconds",
                                     now - t.admitted)
        if self.metrics is not None:
            self.metrics.inc("serve_batches_total")
            self.metrics.observe("serve_batch_fill", len(live))
