"""The fleet controller: one chip pool, many jobs, preemption-native
(docs/FLEET.md; ROADMAP item 5).

One :class:`FleetController` owns the host inventory (through a
:class:`~horovod_tpu.fleet.placement.PlacementPool`) and supervises N
concurrent elastic jobs, each driven by its own
:class:`~horovod_tpu.elastic.driver.ElasticDriver` in a worker thread.
A job's driver sees ONLY the slots leased to it (a
:class:`_LeaseDiscovery` is its host-discovery source), so the existing
elastic machinery — shrink on failure, blacklist backoff, durable
checkpoints, ``--restart-from-ckpt`` recovery — composes unchanged into
multi-tenancy, and the pool's ledger is the single place that can
refuse oversubscription.

Scheduling, in priority order (higher number wins), each tick:

* **Gang admission** — a waiting job is admitted only when at least
  ``min_np`` slots can be leased at once (nothing is leased on a failed
  attempt); a job that cannot fit retries with capped exponential
  backoff.
* **Preemption by graceful drain** — when a waiting job outranks
  running work and free slots do not cover its ``min_np``, the
  controller reclaims slots from the lowest-priority victims: first by
  SHRINKING a victim toward its ``min_np`` (drain of its youngest
  workers), then by whole-job preemption (drain of everything). Either
  way the victims durable-commit the in-flight step and exit
  ``EXIT_DRAINED``; their hosts re-enter the pool immediately (voluntary
  exit never trips the failure blacklist).
* **Restore** — a preempted job re-queues for admission (its fresh
  driver auto-resumes from the durable lineage); a shrunk job is grown
  back (slots leased back, ceiling raised) once no higher-priority work
  is waiting.

The controller never calls ``hvd.init()``; fleet_* metrics live in the
Python mirror registry (``fleet/metrics.py``) served at ``/metrics`` +
``/fleet`` for ``hvd-top --fleet``.
"""

import os
import shlex
import signal
import sys
import threading
import time

from horovod_tpu.elastic import driver as _edriver
from horovod_tpu.elastic.discovery import HostDiscovery
from horovod_tpu.elastic.state import EXIT_DRAINED

from .metrics import FleetMetrics, start_server
from .placement import PlacementPool

# Job lifecycle. pending -> running -> done | failed, with the
# preemption loop running -> draining -> preempted -> running (restore).
PENDING = "pending"
RUNNING = "running"
DRAINING = "draining"
PREEMPTED = "preempted"
DONE = "done"
FAILED = "failed"
TERMINAL = (DONE, FAILED)


class JobSpec:
    """One tenant job. `command` is the worker argv (a string is
    shlex-split); `np` the desired world size, `min_np` the gang
    floor; bigger `priority` wins. `ckpt_dir` enables durable commits +
    preemption restore (the controller requires it — a preemptable job
    without a durable lineage would restart from step 0).

    `kind` is ``"train"`` (default) or ``"serve"`` — a serve job's
    workers are hvd-serve replicas (docs/SERVE.md): no rendezvous (so
    `start_timeout` defaults SHORT — the driver's growth gate only
    unsticks by stalling), and `placement` defaults to ``"spread"``
    (failure-domain diversity) where training defaults to ``"pack"``
    (locality). Both defaults are per-kind only; either field can be
    set explicitly."""

    def __init__(self, name, command, np, min_np=1, max_np=None,
                 priority=0, arrival=0.0, ckpt_dir=None, env=None,
                 max_restarts=2, start_timeout=None, kind="train",
                 placement=None):
        if isinstance(command, str):
            command = shlex.split(command)
        if min_np < 1 or np < min_np:
            raise ValueError(
                "job %r needs 1 <= min_np <= np (got %d..%d)"
                % (name, min_np, np))
        if kind not in ("train", "serve"):
            raise ValueError("job %r: unknown kind %r (train|serve)"
                             % (name, kind))
        if placement is None:
            placement = "spread" if kind == "serve" else "pack"
        if placement not in ("pack", "spread"):
            raise ValueError(
                "job %r: unknown placement %r (pack|spread)"
                % (name, placement))
        if start_timeout is None:
            start_timeout = 2 if kind == "serve" else 60
        self.name = str(name)
        self.command = list(command)
        self.np = int(np)
        self.min_np = int(min_np)
        self.max_np = int(max_np) if max_np else int(np)
        self.priority = int(priority)
        self.arrival = float(arrival)
        self.ckpt_dir = ckpt_dir
        self.env = dict(env or {})
        self.max_restarts = int(max_restarts)
        self.start_timeout = start_timeout
        self.kind = kind
        self.placement = placement

    @classmethod
    def from_dict(cls, d):
        known = ("name", "command", "np", "min_np", "max_np", "priority",
                 "arrival", "ckpt_dir", "env", "max_restarts",
                 "start_timeout", "kind", "placement")
        unknown = set(d) - set(known)
        if unknown:
            raise ValueError("unknown job field(s): %s" % sorted(unknown))
        return cls(**d)


class FleetJob:
    """Controller-side runtime of one JobSpec."""

    def __init__(self, spec):
        self.spec = spec
        self.state = PENDING
        self.driver = None
        self.thread = None
        self.rc = None
        self.next_try = 0.0
        self.backoff = float(os.environ.get(
            "HVD_TPU_FLEET_ADMIT_BACKOFF", "0.5"))
        self.restarts = 0
        self.admitted_at = None
        self.preempted_at = None
        self.drain_started = None
        self.shrink_target = None  # live-worker target of a partial drain
        self.drains = 0
        self.preemptions = 0
        self.restores = 0

    @property
    def name(self):
        return self.spec.name

    def live_per_host(self):
        if self.driver is None:
            return {}
        return self.driver.live_per_host()

    def live(self):
        return sum(self.live_per_host().values())


class _LeaseDiscovery(HostDiscovery):
    """A job's view of the pool: exactly its leased slots. The driver's
    own HostManager layers failure blacklisting on top, so a crashing
    host backs off within the job without leaving the fleet."""

    def __init__(self, pool, job_name):
        self._pool = pool
        self._job = job_name

    def find_available_hosts_and_slots(self):
        return self._pool.lease_of(self._job)


class FleetController:
    def __init__(self, discovery, jobs=(), port=None, drain_grace=None,
                 tick=0.2, chaos=None, verbose=False):
        cooldown = float(os.environ.get("HVD_TPU_ELASTIC_COOLDOWN", "10"))
        self.pool = PlacementPool(discovery, cooldown=cooldown)
        self.metrics = FleetMetrics()
        self.jobs = {}
        self.drain_grace = drain_grace or float(os.environ.get(
            "HVD_TPU_FLEET_DRAIN_GRACE", "30"))
        self._tick = tick
        self._chaos = chaos
        self._verbose = verbose
        self._start = None
        self._server = None
        self.port = None
        if port is not None:
            self._server, self.port = start_server(
                port, self.metrics, self.view)
        for spec in jobs:
            self.submit(spec)

    def _log(self, msg):
        sys.stderr.write("[fleet] %s\n" % msg)
        sys.stderr.flush()

    # -- job intake --------------------------------------------------------
    def submit(self, spec):
        if isinstance(spec, dict):
            spec = JobSpec.from_dict(spec)
        if spec.name in self.jobs:
            raise ValueError("duplicate job name %r" % spec.name)
        if self._chaos is not None:
            override = self._chaos.arrival_override(spec.name)
            if override is not None:
                spec.arrival = override
        job = FleetJob(spec)
        self.jobs[spec.name] = job
        return job

    # -- per-job driver lifecycle ------------------------------------------
    def _job_env(self, job):
        env = dict(os.environ)
        env.update(job.spec.env)
        if job.spec.ckpt_dir:
            env["HVD_TPU_CKPT_DIR"] = os.path.abspath(job.spec.ckpt_dir)
        return env

    def _start_driver(self, job, granted):
        np_now = sum(granted.values())
        driver = _edriver.ElasticDriver(
            job.spec.command, _LeaseDiscovery(self.pool, job.name),
            min_np=job.spec.min_np, max_np=job.spec.max_np,
            np_initial=np_now, start_timeout=job.spec.start_timeout,
            verbose=self._verbose, env=self._job_env(job),
            ckpt_dir=(os.path.abspath(job.spec.ckpt_dir)
                      if job.spec.ckpt_dir else None),
            restart_from_ckpt=bool(job.spec.ckpt_dir),
            drain_grace=self.drain_grace,
            placement=job.spec.placement,
            # One tenant's crashing host is everyone's problem: mirror
            # the job-local failure/health evidence into the pool so
            # the fleet-wide blacklist (fleet_hosts_blacklisted) is
            # actually fed, not just each job's private one.
            health_sink=self.pool)
        job.driver = driver

        def _run():
            try:
                job.rc = driver.run(install_signal_handlers=False)
            except Exception as e:
                self._log("job %s driver crashed: %s" % (job.name, e))
                job.rc = 1

        job.thread = threading.Thread(
            target=_run, name="hvd-fleet-%s" % job.name, daemon=True)
        job.thread.start()

    def _try_admit(self, job, now):
        """Gang admission (or restore): lease >= min_np or nothing."""
        granted = self.pool.lease(job.name, job.spec.np,
                                  min_slots=job.spec.min_np,
                                  placement=job.spec.placement)
        if not granted:
            self.metrics.inc("fleet_admission_retries_total")
            job.next_try = now + job.backoff
            job.backoff = min(job.backoff * 2, float(os.environ.get(
                "HVD_TPU_FLEET_ADMIT_BACKOFF_MAX", "10")))
            return False
        restore = job.state == PREEMPTED
        self._start_driver(job, granted)
        job.state = RUNNING
        job.admitted_at = now
        job.backoff = float(os.environ.get(
            "HVD_TPU_FLEET_ADMIT_BACKOFF", "0.5"))
        if restore:
            job.restores += 1
            self.metrics.inc("fleet_restores_total")
            self.metrics.observe("fleet_restore_seconds",
                                 now - (job.preempted_at or now))
            self._log("job %s restored on %s (preempted %.1fs)"
                      % (job.name, granted,
                         now - (job.preempted_at or now)))
        else:
            self.metrics.inc("fleet_admissions_total")
            if job.restarts:
                self.metrics.inc("fleet_job_restarts_total")
            self._log("job %s admitted on %s (priority %d)"
                      % (job.name, granted, job.spec.priority))
        return True

    def _capacity_event(self, now):
        """Slots just returned to the pool: every waiting job retries
        NOW, in priority order — without this, a backoff-delayed
        high-priority job would watch a retry-ready low-priority one
        (often the very job just preempted for it) take the freed
        slots back: priority inversion via the retry timer."""
        for job in self.jobs.values():
            if job.state in (PENDING, PREEMPTED):
                job.next_try = now

    def _reap_job(self, job, now):
        """Handles a driver thread that finished."""
        job.thread.join()
        job.thread = None
        rc = job.rc
        was_draining = job.state == DRAINING
        self.pool.release(job.name)
        job.driver = None
        # A death/full-drain mid-shrink must not leak the shrink into
        # the job's NEXT incarnation: a stale shrink_target would make
        # _finish_shrinks release slots freshly leased to the restarted
        # driver (and observe a garbage drain latency).
        job.shrink_target = None
        drain_started, job.drain_started = job.drain_started, None
        self._capacity_event(now)
        if rc == 0:
            job.state = DONE
            self.metrics.inc("fleet_job_completions_total")
            self._log("job %s completed" % job.name)
        elif rc == EXIT_DRAINED and was_draining:
            job.state = PREEMPTED
            job.preempted_at = now
            job.preemptions += 1
            self.metrics.inc("fleet_preemptions_total")
            drain_took = (now - drain_started
                          if drain_started is not None else 0.0)
            if drain_started is not None:
                self.metrics.observe("fleet_drain_seconds", drain_took)
            job.next_try = now
            self._log("job %s preempted (drained in %.1fs); hosts "
                      "reclaimed" % (job.name, drain_took))
        elif job.restarts < job.spec.max_restarts:
            job.restarts += 1
            job.state = PENDING
            job.next_try = now + job.backoff
            self._log("job %s died (rc=%s); controller restart %d/%d "
                      "from the durable lineage"
                      % (job.name, rc, job.restarts,
                         job.spec.max_restarts))
        else:
            job.state = FAILED
            self.metrics.inc("fleet_job_failures_total")
            self._log("job %s FAILED (rc=%s, restart budget spent)"
                      % (job.name, rc))

    # -- preemption planning -----------------------------------------------
    def _waiting(self, now):
        return [j for j in self.jobs.values()
                if j.state in (PENDING, PREEMPTED)
                and now - self._start >= j.spec.arrival]

    def _preempt_for(self, pending_job):
        """Reclaims slots for `pending_job` from strictly-lower-priority
        running jobs: shrink victims toward their min_np first, full
        preemption only when shrinking cannot cover the gang. Returns
        True when any drain was requested (admission then waits for the
        reclaimed slots to actually free)."""
        needed = pending_job.spec.min_np - self.pool.free_slots()
        if needed <= 0:
            return False
        victims = sorted(
            (j for j in self.jobs.values()
             if j.state == RUNNING
             and j.spec.priority < pending_job.spec.priority
             and j.driver is not None and not j.driver.draining()),
            key=lambda j: (j.spec.priority, -(j.admitted_at or 0)))
        if not victims:
            return False
        reclaimable = sum(
            self.pool.leased_slots_of(j.name) for j in victims)
        if self.pool.free_slots() + reclaimable < pending_job.spec.min_np:
            return False  # even preempting everything would not fit
        acted = False
        for victim in victims:
            if needed <= 0:
                break
            leased = self.pool.leased_slots_of(victim.name)
            shrinkable = leased - victim.spec.min_np
            if shrinkable >= needed:
                self._shrink(victim, leased - needed, pending_job)
                needed = 0
            else:
                self._preempt(victim, pending_job)
                needed -= leased
            acted = True
        return acted

    def _shrink(self, victim, target, for_job):
        """Partial drain: victim keeps running at `target` workers."""
        wids = victim.driver.live_workers()
        if len(wids) <= target:
            return
        drain_wids = wids[target:]  # youngest workers; rank 0 survives
        victim.driver.resize(target)
        victim.driver.request_drain(drain_wids, grace=self.drain_grace)
        victim.shrink_target = target
        victim.drain_started = time.monotonic()
        victim.drains += 1
        self.metrics.inc("fleet_drains_requested_total")
        self._log("shrinking job %s to %d worker(s) (drain of %s) to "
                  "fit job %s (priority %d > %d)"
                  % (victim.name, target, drain_wids, for_job.name,
                     for_job.spec.priority, victim.spec.priority))

    def _preempt(self, victim, for_job):
        """Whole-job drain: victim durable-commits and hands back every
        host; restored when capacity returns."""
        victim.driver.request_drain("all", grace=self.drain_grace)
        victim.state = DRAINING
        victim.drain_started = time.monotonic()
        victim.drains += 1
        self.metrics.inc("fleet_drains_requested_total")
        self._log("preempting job %s (priority %d) for job %s "
                  "(priority %d)"
                  % (victim.name, victim.spec.priority, for_job.name,
                     for_job.spec.priority))

    def _finish_shrinks(self, now):
        """Releases the slots a completed partial drain freed (leased
        minus live, bounded so a concurrent crash cannot strangle the
        victim's respawn headroom)."""
        for job in self.jobs.values():
            if job.shrink_target is None or job.driver is None:
                continue
            if job.driver.draining():
                continue
            live = job.driver.live_per_host()
            target = max(job.shrink_target, job.spec.min_np)
            excess = self.pool.leased_slots_of(job.name) - max(
                sum(live.values()), target)
            for host, leased in sorted(
                    self.pool.lease_of(job.name).items()):
                if excess <= 0:
                    break
                releasable = min(excess, leased - live.get(host, 0))
                if releasable > 0:
                    self.pool.release(job.name, host, releasable)
                    excess -= releasable
            job.shrink_target = None
            if job.drain_started is not None:
                self.metrics.observe("fleet_drain_seconds",
                                     now - job.drain_started)
                job.drain_started = None
            self.metrics.inc("fleet_shrinks_total")
            self._capacity_event(now)
            self._log("job %s shrink complete; slots reclaimed"
                      % job.name)

    def _grow_running(self, now):
        """Leases free slots back to running jobs below their max_np —
        the grow half of restore — but never while higher-or-equal
        priority work is waiting for those slots."""
        waiting = self._waiting(now)
        for job in sorted(self.jobs.values(),
                          key=lambda j: -j.spec.priority):
            if job.state != RUNNING or job.driver is None:
                continue
            if job.shrink_target is not None or job.driver.draining():
                continue
            if any(w.spec.priority >= job.spec.priority for w in waiting):
                continue
            leased = self.pool.leased_slots_of(job.name)
            room = job.spec.max_np - leased
            free = self.pool.free_slots()
            if room <= 0 or free <= 0:
                continue
            extra = self.pool.lease(job.name, min(room, free),
                                    min_slots=1,
                                    placement=job.spec.placement)
            if extra:
                grown = sum(extra.values())
                job.driver.resize(leased + grown)
                self.metrics.inc("fleet_grows_total", grown)
                self._log("job %s grown by %d slot(s) (%s)"
                          % (job.name, grown, extra))

    # -- chaos -------------------------------------------------------------
    def _defer_chaos(self, ev):
        """Re-arms an event whose target is not currently running
        (mid-restart, still pending, being drained): the schedule says
        the job EATS this fault, so it fires at the next tick the
        target is back — only a terminal target consumes it unfired."""
        target = self.jobs.get(ev.job)
        if ev.job != "*" and target is not None and \
                target.state in TERMINAL:
            self._log("chaos: dropping %s for job %s (already %s)"
                      % (ev.action, ev.job, target.state))
            return
        if ev.job == "*" and all(j.state in TERMINAL
                                 for j in self.jobs.values()):
            return
        ev.fired -= 1

    def _apply_chaos(self, now):
        if self._chaos is None:
            return
        for ev in self._chaos.due(now - self._start):
            running = [j for j in self.jobs.values()
                       if j.state == RUNNING and j.driver is not None]
            if ev.action == "kill":
                pool = ([j for j in running if j.name == ev.job]
                        if ev.job != "*" else running)
                name = self._chaos.pick([j.name for j in pool])
                if name is None:
                    self._defer_chaos(ev)
                    continue
                job = self.jobs[name]
                wid = self._chaos.pick(job.driver.live_workers())
                pid = (job.driver.worker_pid(wid)
                       if wid is not None else None)
                if pid is None:
                    # RUNNING but momentarily workerless (mid-respawn)
                    # or the pick raced the worker's exit: same
                    # contract as a non-running target — the event
                    # re-arms rather than being silently eaten, so the
                    # seeded schedule stays deterministic.
                    self._defer_chaos(ev)
                    continue
                self._log("chaos: SIGKILL job %s worker %d (pid %d)"
                          % (job.name, wid, pid))
                try:
                    os.killpg(os.getpgid(pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                self.metrics.inc("fleet_kills_injected_total")
            elif ev.action == "preempt":
                pool = ([j for j in running if j.name == ev.job]
                        if ev.job != "*" else running)
                name = self._chaos.pick([j.name for j in pool])
                if name is None:
                    self._defer_chaos(ev)
                    continue
                job = self.jobs[name]
                self._log("chaos: forced preemption of job %s"
                          % job.name)
                self._preempt(job, job)
                self.metrics.inc("fleet_preempts_injected_total")

    # -- gauges / views ----------------------------------------------------
    def _update_gauges(self):
        by_state = {s: 0 for s in (PENDING, RUNNING, DRAINING,
                                   PREEMPTED, DONE, FAILED)}
        for job in self.jobs.values():
            by_state[job.state] += 1
        for state, n in by_state.items():
            self.metrics.set_gauge("fleet_jobs_%s" % state, n)
        hosts = self.pool.host_states()
        for state in ("free", "leased", "blacklisted"):
            self.metrics.set_gauge(
                "fleet_hosts_%s" % state,
                sum(1 for h in hosts.values() if h["state"] == state))
        self.metrics.set_gauge("fleet_slots_free", self.pool.free_slots())
        self.metrics.set_gauge(
            "fleet_slots_leased",
            sum(h["leased"] for h in hosts.values()))

    def _check_occupancy(self):
        live_by_job = {name: job.live_per_host()
                       for name, job in self.jobs.items()
                       if job.driver is not None}
        violated = self.pool.check_occupancy(live_by_job)
        if violated:
            self.metrics.inc("fleet_occupancy_violations_total")
            self._log("OCCUPANCY VIOLATION on host(s) %s — this is a "
                      "fleet bug" % violated)
        return violated

    def view(self):
        """The /fleet JSON document (hvd-top --fleet renders it)."""
        now = time.monotonic()
        jobs = {}
        for name, job in sorted(self.jobs.items()):
            last_durable = None
            if job.spec.ckpt_dir and os.path.isdir(job.spec.ckpt_dir):
                try:
                    from horovod_tpu.elastic.durable import \
                        last_durable_step
                    step, _ = last_durable_step(job.spec.ckpt_dir)
                    last_durable = step
                except Exception:
                    last_durable = None
            jobs[name] = {
                "state": job.state,
                "kind": job.spec.kind,
                "placement": job.spec.placement,
                "priority": job.spec.priority,
                "np": job.spec.np,
                "min_np": job.spec.min_np,
                "live": job.live(),
                "leased": self.pool.leased_slots_of(name),
                "drains": job.drains,
                "preemptions": job.preemptions,
                "restores": job.restores,
                "restarts": job.restarts,
                "rc": job.rc,
                "last_durable_step": last_durable,
                "age_seconds": (now - job.admitted_at
                                if job.admitted_at else None),
            }
        return {
            "t": (now - self._start) if self._start else 0.0,
            "jobs": jobs,
            "hosts": self.pool.host_states(),
            "free_slots": self.pool.free_slots(),
            "counters": self.metrics.snapshot()["counters"],
        }

    # -- main loop ---------------------------------------------------------
    def _tick_once(self, now):
        self.pool.refresh()
        self._apply_chaos(now)
        # Reap finished driver threads.
        for job in self.jobs.values():
            if job.thread is not None and not job.thread.is_alive():
                self._reap_job(job, now)
        self._finish_shrinks(now)
        # Admission in priority order; a job that cannot fit may earn
        # its slots by preemption, in which case admission waits for
        # the drains to land (no lease is held meanwhile).
        draining = any(j.state == DRAINING or (
            j.driver is not None and j.driver.draining())
            for j in self.jobs.values())
        for job in sorted(self._waiting(now),
                          key=lambda j: (-j.spec.priority,
                                         j.spec.arrival, j.name)):
            if now < job.next_try:
                continue
            if self._try_admit(job, now):
                continue
            if not draining and self._preempt_for(job):
                draining = True
        self._grow_running(now)
        self._sync_pool_counters()
        self._update_gauges()
        self._check_occupancy()

    def _sync_pool_counters(self):
        refusals = self.pool.oversubscription_refusals
        have = self.metrics.get("fleet_oversubscription_refusals_total")
        if refusals > have:
            self.metrics.inc("fleet_oversubscription_refusals_total",
                             refusals - have)

    def run(self, timeout=None):
        """Supervises until every job is terminal. Returns 0 when all
        completed, 1 when any failed (or the timeout expired)."""
        self._start = time.monotonic()
        deadline = (self._start + timeout) if timeout else None
        try:
            while True:
                now = time.monotonic()
                self._tick_once(now)
                states = [j.state for j in self.jobs.values()]
                if states and all(s in TERMINAL for s in states):
                    break
                if deadline and now > deadline:
                    self._log("fleet timeout after %.0fs; tearing down"
                              % timeout)
                    self.shutdown()
                    return 1
                time.sleep(self._tick)
        except KeyboardInterrupt:
            self._log("interrupted; tearing down")
            self.shutdown()
            return 1
        self._update_gauges()
        failed = [j.name for j in self.jobs.values()
                  if j.state == FAILED]
        if failed:
            self._log("fleet finished with FAILED job(s): %s"
                      % ", ".join(sorted(failed)))
            return 1
        self._log("fleet finished: all %d job(s) completed"
                  % len(self.jobs))
        return 0

    def shutdown(self):
        for job in self.jobs.values():
            if job.driver is not None:
                job.driver.terminate()
        for job in self.jobs.values():
            if job.thread is not None:
                job.thread.join(timeout=30)
                job.thread = None
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
