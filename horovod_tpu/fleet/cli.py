"""``hvd-fleet`` — run a jobfile of concurrent elastic jobs on one
host pool (docs/FLEET.md).

Jobfile (JSON)::

    {
      "hosts": "localhost:8",          // or --hosts / --host-discovery-script
      "drain_grace": 30,               // optional, seconds
      "jobs": [
        {"name": "prod", "command": "python train.py", "np": 4,
         "min_np": 2, "priority": 10, "ckpt_dir": "ckpt/prod"},
        {"name": "batch", "command": "python sweep.py", "np": 4,
         "min_np": 1, "priority": 0, "arrival": 5.0,
         "ckpt_dir": "ckpt/batch", "env": {"SWEEP_ID": "7"}}
      ]
    }

Exit code 0 when every job completed; 1 when any job failed or the
``--timeout`` expired. ``--port`` serves the controller's metrics plane
(``/metrics`` Prometheus, ``/fleet`` JSON) — point ``hvd-top --fleet``
at it for the live cross-job view.
"""

import argparse
import json
import sys


def make_parser():
    parser = argparse.ArgumentParser(
        prog="hvd-fleet",
        description="Run N concurrent elastic jobs with priorities and "
                    "preemption-by-graceful-drain on one host pool.")
    parser.add_argument("jobfile", help="JSON jobfile (see docs/FLEET.md)")
    parser.add_argument("-H", "--hosts", default=None,
                        help='host pool, e.g. "localhost:8,host2:4" '
                             "(overrides the jobfile's hosts)")
    parser.add_argument("--host-discovery-script", default=None,
                        help="executable printing one 'host[:slots]' "
                             "line per available host; polled so the "
                             "pool tracks preemption/churn")
    parser.add_argument("--port", type=int, default=None,
                        help="controller metrics/view port (serves "
                             "/metrics and /fleet; hvd-top --fleet "
                             "polls it). 0 picks a free port")
    parser.add_argument("--drain-grace", type=float, default=None,
                        help="seconds a drain victim gets to durable-"
                             "commit before SIGKILL escalation "
                             "(default 30, or the jobfile's)")
    parser.add_argument("--timeout", type=float, default=None,
                        help="give up (exit 1) after this many seconds")
    parser.add_argument("--verbose", action="store_true")
    return parser


def main(argv=None):
    args = make_parser().parse_args(argv)
    try:
        with open(args.jobfile) as f:
            jobfile = json.load(f)
    except (OSError, ValueError) as e:
        sys.stderr.write("hvd-fleet: cannot read jobfile %s: %s\n"
                         % (args.jobfile, e))
        return 2
    specs = jobfile.get("jobs") or []
    if not specs:
        sys.stderr.write("hvd-fleet: jobfile has no jobs\n")
        return 2

    from horovod_tpu.elastic.discovery import (FixedHosts,
                                               HostDiscoveryScript)
    from horovod_tpu.fleet.chaos import FleetChaos
    from horovod_tpu.fleet.controller import FleetController

    if args.host_discovery_script:
        discovery = HostDiscoveryScript(args.host_discovery_script)
    else:
        hosts = args.hosts or jobfile.get("hosts")
        if not hosts:
            sys.stderr.write(
                "hvd-fleet: no host pool (give -H/--hosts, "
                "--host-discovery-script, or a jobfile 'hosts' key)\n")
            return 2
        discovery = FixedHosts(hosts)

    chaos = FleetChaos.from_env()
    if chaos is not None:
        sys.stderr.write(
            "[fleet] ! chaos schedule active (HVD_TPU_FLEET_CHAOS_SPEC, "
            "seed %d, %d event(s)) — test mode\n"
            % (chaos.seed, len(chaos.events)))

    controller = FleetController(
        discovery,
        port=args.port,
        drain_grace=args.drain_grace or jobfile.get("drain_grace"),
        chaos=chaos,
        verbose=args.verbose)
    for spec in specs:
        controller.submit(spec)
    if controller.port is not None:
        sys.stderr.write(
            "[fleet] metrics at http://localhost:%d/metrics, job view "
            "at /fleet (try: bin/hvd-top --fleet localhost:%d)\n"
            % (controller.port, controller.port))
    try:
        return controller.run(timeout=args.timeout)
    finally:
        controller.shutdown()


if __name__ == "__main__":
    sys.exit(main())
