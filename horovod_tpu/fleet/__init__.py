"""hvd-fleet: one chip pool, many jobs, preemption-native.

The production-scale composition of the reliability stack (ROADMAP item
5): a :class:`FleetController` owns the host inventory and runs N
concurrent elastic jobs with priorities. A job that cannot fit yet is
gang-admitted later with capped backoff; a higher-priority arrival
preempts lower-priority work by **graceful drain** — the victim's
workers finish the in-flight step, force a durable commit of exactly
that step, and exit with ``EXIT_DRAINED`` so the controller reclaims
their hosts immediately (voluntary exit never trips the failure
blacklist) — and the victim is restored (grow or full durable resume)
when capacity returns.

Pieces:

* ``placement.py`` — the reusable placement library: ``plan_spawns``
  (shared with the single-job elastic driver) and :class:`PlacementPool`
  (slot-granular leases over the host inventory, oversubscription
  refused and counted).
* ``controller.py`` — the controller: admission, priority preemption,
  drain/restore orchestration, one elastic driver thread per job.
* ``chaos.py`` — the seeded fleet chaos schedule
  (``HVD_TPU_FLEET_CHAOS_SPEC``: arrival / kill / preempt events).
* ``metrics.py`` — fleet_* counters/gauges/histograms + the HTTP
  endpoint serving Prometheus ``/metrics`` and the ``/fleet`` JSON view
  ``hvd-top --fleet`` polls.
* ``cli.py`` — the ``hvd-fleet`` launcher (jobfile in, exit 0 when
  every job completed).

See docs/FLEET.md for the controller model, the drain protocol, and the
chaos grammar.
"""

from .chaos import FleetChaos  # noqa: F401
from .controller import FleetController, FleetJob, JobSpec  # noqa: F401
from .metrics import FleetMetrics  # noqa: F401
from .placement import PlacementPool, plan_spawns  # noqa: F401
