"""Seeded fleet chaos schedule (docs/FLEET.md).

Extends the ``HVD_TPU_FAULT_SPEC`` grammar family (native/fault.cc,
``HVD_TPU_CKPT_FAULT_SPEC``) from frames and storage ops up to WHOLE
JOBS: a deterministic, seeded schedule of job arrivals, random worker
SIGKILLs, and forced priority preemptions, applied by the fleet
controller's tick loop. **Test-only — never set it on a real fleet.**

Grammar (``HVD_TPU_FLEET_CHAOS_SPEC``)::

    spec   := clause (';' clause)*
    clause := 'seed=N' | event
    event  := field (',' field)*
    field  := job=NAME|*          target job ('*' = seeded-random pick
                                  among currently-running jobs)
            | at=T                seconds after controller start (default 0)
            | action=arrive|kill|preempt
            | count=K             repeat K times (default 1)
            | every=S             seconds between repeats (default 1)

Actions:

* ``arrive``  — override the target job's arrival time to ``at`` (the
  jobfile's own ``arrival`` is the un-chaosed schedule).
* ``kill``    — SIGKILL one seeded-random live worker of the target job:
  the crash path (blacklist backoff, elastic shrink, or full
  ``--restart-from-ckpt`` recovery), NOT the drain path.
* ``preempt`` — force a graceful-drain preemption of the target job as
  if a higher-priority arrival needed its hosts; the controller
  restores it when capacity returns.

Example — job b arrives at t=3, a random worker of job a is SIGKILLed
at t=5 and again at t=7, and job c is force-preempted at t=8::

    HVD_TPU_FLEET_CHAOS_SPEC='seed=11;job=b,at=3,action=arrive;job=a,at=5,action=kill,count=2,every=2;job=c,at=8,action=preempt'

Determinism: same spec + same seed -> same schedule and same random
picks (victim workers, '*' jobs), independent of wall-clock jitter in
the controller loop (events fire on the controller's relative clock).
"""

import os
import random

ACTIONS = ("arrive", "kill", "preempt")


class FleetChaosError(ValueError):
    pass


class _Event:
    __slots__ = ("job", "at", "action", "count", "every", "fired")

    def __init__(self, job, at, action, count, every):
        self.job = job
        self.at = at
        self.action = action
        self.count = count
        self.every = every
        self.fired = 0

    def __repr__(self):
        return ("chaos(%s job=%s at=%.3g count=%d every=%.3g)"
                % (self.action, self.job, self.at, self.count,
                   self.every))


class FleetChaos:
    """Parsed schedule + the seeded PRNG the controller draws victim
    picks from. ``due(now_rel)`` returns the events to apply this tick
    (each at most ``count`` times, ``every`` seconds apart)."""

    def __init__(self, spec, seed=0):
        self.seed = seed
        self.events = []
        self._parse(spec)
        self.rng = random.Random(self.seed)

    @classmethod
    def from_env(cls):
        spec = os.environ.get("HVD_TPU_FLEET_CHAOS_SPEC", "")
        return cls(spec) if spec.strip() else None

    def _parse(self, spec):
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            if clause.startswith("seed="):
                try:
                    self.seed = int(clause[5:])
                except ValueError:
                    raise FleetChaosError(
                        "bad seed clause %r" % clause) from None
                continue
            fields = {}
            for field in clause.split(","):
                field = field.strip()
                if "=" not in field:
                    raise FleetChaosError(
                        "bad field %r in clause %r (expected key=value)"
                        % (field, clause))
                key, _, val = field.partition("=")
                fields[key.strip()] = val.strip()
            unknown = set(fields) - {"job", "at", "action", "count",
                                     "every"}
            if unknown:
                raise FleetChaosError(
                    "unknown field(s) %s in clause %r"
                    % (sorted(unknown), clause))
            action = fields.get("action")
            if action not in ACTIONS:
                raise FleetChaosError(
                    "clause %r needs action=%s (got %r)"
                    % (clause, "|".join(ACTIONS), action))
            job = fields.get("job", "*")
            if action == "arrive" and job == "*":
                raise FleetChaosError(
                    "arrive events need an explicit job= (clause %r)"
                    % clause)
            try:
                at = float(fields.get("at", "0"))
                count = int(fields.get("count", "1"))
                every = float(fields.get("every", "1"))
            except ValueError as e:
                raise FleetChaosError(
                    "bad numeric field in clause %r (%s)"
                    % (clause, e)) from None
            if count < 1 or at < 0 or every <= 0:
                raise FleetChaosError(
                    "clause %r needs at>=0, count>=1, every>0" % clause)
            self.events.append(_Event(job, at, action, count, every))

    def arrival_override(self, job_name):
        """The chaos-scheduled arrival time for `job_name`, or None."""
        for ev in self.events:
            if ev.action == "arrive" and ev.job == job_name:
                return ev.at
        return None

    def due(self, now_rel):
        """Kill/preempt events due at `now_rel` seconds since start —
        each event fires at ``at``, ``at + every``, ... up to ``count``
        total firings. Arrive events never fire here (they are
        consumed up front as arrival overrides)."""
        out = []
        for ev in self.events:
            if ev.action == "arrive":
                continue
            while (ev.fired < ev.count
                   and now_rel >= ev.at + ev.fired * ev.every):
                ev.fired += 1
                out.append(ev)
        return out

    def pick(self, candidates):
        """Seeded-deterministic pick among `candidates` (sorted first,
        so set iteration order can't leak into the schedule)."""
        candidates = sorted(candidates)
        return self.rng.choice(candidates) if candidates else None
