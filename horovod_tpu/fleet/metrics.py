"""fleet_* metrics + the controller's HTTP plane (docs/FLEET.md).

The fleet controller is a supervisor process — it never calls
``hvd.init()`` — so its registry is a small Python mirror of the native
one (``native/metrics.h``): monotonic counters, gauges, and fixed-bucket
histograms, rendered with the SAME Prometheus renderer the worker
endpoints use (``horovod_tpu/_metrics.py``), so one scrape config covers
workers and controller alike (families are ``hvdtpu_fleet_*``).

The HTTP endpoint serves:

* ``/metrics`` — Prometheus text exposition of the fleet registry,
* ``/fleet``   — the cross-job JSON view ``hvd-top --fleet`` polls
  (jobs with their states/sizes/lineage, hosts by state, counters).

Thread model: counters/gauges are plain numbers mutated under one lock
(the controller tick is the only writer; scrapes are read-only
snapshots) — no atomics needed at controller request rates.
"""

import json
import threading

# One histogram ladder serves both drain and restore latencies: sub-
# second (an idle commit loop notices the request immediately) up to
# minutes (restore waits for capacity to return).
_LATENCY_BOUNDS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                   120.0, 300.0, 600.0)

COUNTERS = (
    "fleet_admissions_total",         # jobs granted their initial gang
    "fleet_admission_retries_total",  # gang attempts that could not fit
    "fleet_drains_requested_total",   # drain requests the controller sent
    "fleet_preemptions_total",        # whole-job drains completed
    "fleet_shrinks_total",            # partial (subset-victim) drains
    "fleet_grows_total",              # slots leased back to a shrunk job
    "fleet_restores_total",           # preempted jobs re-admitted
    "fleet_job_completions_total",
    "fleet_job_failures_total",       # permanent (restart budget spent)
    "fleet_job_restarts_total",       # controller-level re-admissions
    "fleet_kills_injected_total",     # chaos schedule SIGKILLs
    "fleet_preempts_injected_total",  # chaos schedule forced preemptions
    "fleet_oversubscription_refusals_total",
    "fleet_occupancy_violations_total",  # should stay 0 forever
)

GAUGES = (
    "fleet_jobs_pending", "fleet_jobs_running", "fleet_jobs_draining",
    "fleet_jobs_preempted", "fleet_jobs_done", "fleet_jobs_failed",
    "fleet_hosts_free", "fleet_hosts_leased", "fleet_hosts_blacklisted",
    "fleet_slots_free", "fleet_slots_leased",
)

HISTOGRAMS = ("fleet_drain_seconds", "fleet_restore_seconds")


class _Histogram:
    """Fixed-bucket histogram, snapshot-compatible with the native
    registry's JSON shape (bounds / counts / sum / count)."""

    def __init__(self, bounds=_LATENCY_BOUNDS):
        self.bounds = list(bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v):
        i = 0
        while i < len(self.bounds) and v > self.bounds[i]:
            i += 1
        self.counts[i] += 1
        self.sum += v
        self.count += 1

    def snapshot(self):
        return {"bounds": list(self.bounds), "counts": list(self.counts),
                "sum": self.sum, "count": self.count}


class FleetMetrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {name: 0 for name in COUNTERS}
        self._gauges = {name: 0 for name in GAUGES}
        self._histograms = {name: _Histogram() for name in HISTOGRAMS}

    def inc(self, name, n=1):
        with self._lock:
            self._counters[name] += n

    def get(self, name):
        with self._lock:
            return self._counters.get(name, self._gauges.get(name, 0))

    def set_gauge(self, name, v):
        with self._lock:
            self._gauges[name] = v

    def observe(self, name, v):
        with self._lock:
            self._histograms[name].observe(v)

    def snapshot(self):
        """Native-registry-shaped dict, accepted verbatim by
        ``_metrics.render_prometheus``."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {n: h.snapshot()
                               for n, h in self._histograms.items()},
            }


def render_prometheus(metrics):
    from horovod_tpu._metrics import render_prometheus as _render
    return _render(metrics.snapshot())


def _make_handler(metrics, view_fn):
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            path = self.path.split("?")[0].rstrip("/") or "/"
            try:
                if path in ("/", "/metrics"):
                    self._reply(200, render_prometheus(metrics),
                                "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/fleet":
                    self._reply(200, json.dumps(view_fn()),
                                "application/json")
                else:
                    self._reply(404, "not found\n", "text/plain")
            except Exception as e:  # a scrape must never kill the fleet
                self._reply(500, "error: %s\n" % e, "text/plain")

        def _reply(self, code, body, ctype):
            data = body.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, fmt, *args):
            pass  # scrapes must not spam controller stderr

    return Handler


def start_server(port, metrics, view_fn):
    """Starts the controller's HTTP endpoint; returns (server, port).
    ``port`` 0 binds an ephemeral port (tests)."""
    from http.server import ThreadingHTTPServer

    httpd = ThreadingHTTPServer(("0.0.0.0", port),
                                _make_handler(metrics, view_fn))
    httpd.daemon_threads = True
    thread = threading.Thread(target=httpd.serve_forever,
                              name="hvd-fleet-http", daemon=True)
    thread.start()
    return httpd, httpd.server_address[1]
