"""Placement library shared by the fleet controller and the single-job
elastic driver.

Two layers:

* :func:`plan_spawns` — the pure spawn-planning rule the elastic
  driver's growth path has always used (refactored out of
  ``elastic/driver.py`` into ``elastic/discovery.py`` so one
  implementation serves both consumers; re-exported here): given the
  available inventory, the live per-host occupancy, and the remaining
  room, list the hosts to spawn on (one entry per worker).

* :class:`PlacementPool` — the fleet controller's ledger over the host
  inventory: slot-granular leases per job, gang grants (all-or-nothing
  at ``min_slots``), voluntary release vs. failure blacklisting (via the
  shared :class:`~horovod_tpu.elastic.discovery.HostManager`), and the
  oversubscription invariant: the pool REFUSES any lease that would put
  a host's leased slot total above its capacity, and counts every
  refusal-worthy request in ``oversubscription_refusals`` — the fleet
  chaos e2e asserts the observed occupancy never exceeds capacity.
"""

import threading

# plan_spawns LIVES in the elastic layer (the base layer both consumers
# sit on) and is re-exported here as part of the placement library's
# public face — fleet importing elastic keeps the dependency pointing
# one way (fleet -> elastic, never the reverse).
from horovod_tpu.elastic.discovery import HostManager, plan_spawns  # noqa: F401


class PlacementPool:
    """Slot-granular host leases for N concurrent jobs.

    The pool wraps a :class:`HostManager` (discovery + per-host failure
    blacklist with exponential backoff) and tracks, per host, how many
    slots each job holds. Lease-ledger mutations are controller-thread
    only (the lock exists for the metrics/view readers);
    ``record_failure``/``record_success`` additionally arrive from the
    per-job driver threads (their health evidence is mirrored here so
    one tenant's crashing host blacklists fleet-wide) — single-dict-op
    updates on the HostManager, safe under the GIL."""

    def __init__(self, discovery, cooldown=10.0, max_backoff=600.0,
                 clock=None):
        kwargs = {"cooldown": cooldown, "max_backoff": max_backoff}
        if clock is not None:
            kwargs["clock"] = clock
        self._hosts = HostManager(discovery, **kwargs)
        self._lock = threading.Lock()
        self._leases = {}  # host -> {job_name: slots}
        self.oversubscription_refusals = 0

    # -- inventory ---------------------------------------------------------
    def refresh(self):
        return self._hosts.refresh()

    def record_failure(self, host):
        """Failure evidence (a worker on `host` crashed): backoff
        blacklist, shared across every job in the fleet."""
        self._hosts.record_failure(host)

    def record_success(self, host, started_at=None):
        self._hosts.record_success(host, started_at=started_at)

    def inventory(self):
        """{host: slots} — discovered minus blacklisted."""
        return self._hosts.available_hosts_and_slots()

    def is_blacklisted(self, host):
        return self._hosts.is_blacklisted(host)

    # -- lease ledger ------------------------------------------------------
    def _leased_slots(self, host):
        return sum(self._leases.get(host, {}).values())

    def free_by_host(self):
        """{host: free slots} over the non-blacklisted inventory."""
        out = {}
        with self._lock:
            for host, slots in self.inventory().items():
                free = slots - self._leased_slots(host)
                if free > 0:
                    out[host] = free
        return out

    def free_slots(self):
        return sum(self.free_by_host().values())

    def lease(self, job, want_slots, min_slots=None, placement="pack"):
        """Gang grant: lease up to `want_slots` (but at least
        `min_slots`, default = want) across hosts; returns {host:
        slots} or {} when the minimum cannot be met — nothing is leased
        on failure, so a job never holds a useless partial gang.

        `placement` shapes the grant the same way
        :func:`plan_spawns` shapes a spawn plan: ``"pack"`` fills
        hosts densely in sorted order (training locality); ``"spread"``
        takes one slot per host round-robin (serve-replica
        failure-domain diversity)."""
        if min_slots is None:
            min_slots = want_slots
        if placement not in ("pack", "spread"):
            raise ValueError("unknown placement %r (pack|spread)"
                             % (placement,))
        grant = {}
        got = 0
        if placement == "spread":
            free = sorted(self.free_by_host().items())
            while got < want_slots:
                progressed = False
                for host, cap in free:
                    if got >= want_slots:
                        break
                    if grant.get(host, 0) < cap:
                        grant[host] = grant.get(host, 0) + 1
                        got += 1
                        progressed = True
                if not progressed:
                    break
        else:
            for host, free in sorted(self.free_by_host().items()):
                if got >= want_slots:
                    break
                take = min(free, want_slots - got)
                if take > 0:
                    grant[host] = take
                    got += take
        if got < max(1, min_slots):
            return {}
        with self._lock:
            for host, take in grant.items():
                inv = self.inventory().get(host, 0)
                if self._leased_slots(host) + take > inv:
                    # Raced against another grant (single-controller
                    # fleets never hit this) — refuse rather than
                    # oversubscribe, and make the near-miss visible.
                    self.oversubscription_refusals += 1
                    return {}
            for host, take in grant.items():
                self._leases.setdefault(host, {})[job] = \
                    self._leases.get(host, {}).get(job, 0) + take
        return dict(grant)

    def release(self, job, host=None, slots=None):
        """Voluntary hand-back (drain, completion, controller shrink):
        the slots re-enter the free pool IMMEDIATELY — no blacklist
        cooldown (that is failure evidence only; see
        ``HostManager.record_release``)."""
        with self._lock:
            hosts = [host] if host is not None else list(self._leases)
            for h in hosts:
                by_job = self._leases.get(h)
                if not by_job or job not in by_job:
                    continue
                self._hosts.record_release(h)
                if slots is None or slots >= by_job[job]:
                    del by_job[job]
                else:
                    by_job[job] -= slots
                if not by_job:
                    self._leases.pop(h, None)

    def lease_of(self, job):
        """{host: slots} currently leased to `job`."""
        with self._lock:
            return {h: by_job[job] for h, by_job in self._leases.items()
                    if job in by_job}

    def leased_slots_of(self, job):
        return sum(self.lease_of(job).values())

    # -- invariants / views ------------------------------------------------
    def check_occupancy(self, live_by_job):
        """Verifies no host runs more workers than it has slots.
        ``live_by_job``: {job: {host: live workers}}. Returns the list
        of violated hosts (empty = invariant holds). The RAW discovered
        inventory is the capacity reference — blacklisting a host must
        not turn its still-draining workers into a false violation."""
        raw = self._hosts._current
        occupancy = {}
        for per_host in live_by_job.values():
            for host, n in per_host.items():
                occupancy[host] = occupancy.get(host, 0) + n
        return [h for h, n in occupancy.items() if n > raw.get(h, 0)]

    def host_states(self):
        """{host: {"slots", "leased", "by_job", "state"}} over the raw
        discovered inventory; state is free | leased | blacklisted."""
        out = {}
        with self._lock:
            for host, slots in sorted(self._hosts._current.items()):
                by_job = dict(self._leases.get(host, {}))
                leased = sum(by_job.values())
                if self._hosts.is_blacklisted(host):
                    state = "blacklisted"
                elif leased:
                    state = "leased"
                else:
                    state = "free"
                out[host] = {"slots": slots, "leased": leased,
                             "by_job": by_job, "state": state}
        return out
