"""MXNet binding.

Capability parity with the reference MXNet API
(`horovod/mxnet/__init__.py:40-131`, `horovod/mxnet/mpi_ops.py:52-224`):
``allreduce``/``allreduce_``/``allgather``/``broadcast``/``broadcast_``,
``broadcast_parameters``, ``DistributedOptimizer`` (wraps an
``mx.optimizer.Optimizer`` so every update sees averaged gradients) and
``DistributedTrainer`` (gluon ``Trainer`` whose ``_allreduce_grads``
rides this framework). Fresh implementation: NDArrays bridge to the
native host core through numpy (``.asnumpy()`` / in-place ``[:]``
copy-back), the same host-tensor path every other binding uses — there
is no MXNet C++ kernel because the core's C API is framework-agnostic.

MXNet is EOL upstream and not installed in this environment; the import
is lazy and raises an actionable error at first use, mirroring how the
reference gates unbuilt extensions (`horovod/common/util.py
check_extension`).
"""

import horovod_tpu as _hvd
from horovod_tpu import (  # noqa: F401
    init, shutdown, is_initialized, rank, local_rank, cross_rank, size,
    local_size, cross_size, is_homogeneous,
    mpi_threads_supported, mpi_enabled, mpi_built, gloo_enabled,
    gloo_built, nccl_built, ddl_built, mlsl_built,
)
from horovod_tpu.common import ops as _ops
from horovod_tpu.common.ops import HorovodInternalError  # noqa: F401

_name_counter = [0]


def _auto_name(prefix):
    _name_counter[0] += 1
    return "%s.mx%d" % (prefix, _name_counter[0])


def _mx():
    try:
        import mxnet
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.mxnet requires MXNet, which is not installed "
            "(MXNet is EOL upstream). Use horovod_tpu.jax (TPU-native), "
            "horovod_tpu.torch, horovod_tpu.tensorflow, or "
            "horovod_tpu.keras.") from e
    return mxnet


def allreduce(tensor, average=True, name=None, priority=0):
    """Averaged (or summed) allreduce of an NDArray; returns a new
    NDArray on the same context (reference: mpi_ops.py:52-93).
    `priority` is accepted for API parity; the core's cycle scheduler
    orders work itself."""
    mx = _mx()
    out = _ops.allreduce(tensor.asnumpy(), name or _auto_name("allreduce"),
                         average=average)
    return mx.nd.array(out, ctx=tensor.context, dtype=out.dtype)


def allreduce_(tensor, average=True, name=None, priority=0):
    """In-place allreduce (reference: mpi_ops.py:94-128)."""
    out = _ops.allreduce(tensor.asnumpy(), name or _auto_name("allreduce"),
                         average=average)
    tensor[:] = out
    return tensor


def allgather(tensor, name=None, priority=0):
    """Concatenates every rank's NDArray along dim 0 (unequal first dims
    allowed; reference: mpi_ops.py:129-167)."""
    mx = _mx()
    out = _ops.allgather(tensor.asnumpy(), name or _auto_name("allgather"))
    return mx.nd.array(out, ctx=tensor.context, dtype=out.dtype)


def broadcast(tensor, root_rank, name=None, priority=0):
    """Broadcast from root_rank; returns a new NDArray (reference:
    mpi_ops.py:168-207)."""
    mx = _mx()
    out = _ops.broadcast(tensor.asnumpy(), root_rank,
                         name or _auto_name("broadcast"))
    return mx.nd.array(out, ctx=tensor.context, dtype=out.dtype)


def broadcast_(tensor, root_rank, name=None, priority=0):
    """In-place broadcast (reference: mpi_ops.py:208-224)."""
    out = _ops.broadcast(tensor.asnumpy(), root_rank,
                         name or _auto_name("broadcast"))
    tensor[:] = out
    return tensor


def broadcast_parameters(params, root_rank=0):
    """Broadcasts a gluon ``ParameterDict`` (or a plain dict of
    NDArrays) from root so all ranks start identical (reference:
    mxnet/__init__.py:109-131)."""
    if not hasattr(params, "items"):
        raise ValueError("invalid params of type %r" % type(params))
    tensors = []
    for key in sorted(params.keys()):
        p = params[key]
        # gluon Parameter -> its data NDArray(s); plain NDArray passes
        # through.
        if hasattr(p, "list_data"):
            tensors.extend(("%s.%d" % (key, i), d)
                           for i, d in enumerate(p.list_data()))
        elif hasattr(p, "data") and callable(p.data):
            tensors.append((key, p.data()))
        else:
            tensors.append((key, p))
    for key, tensor in tensors:
        broadcast_(tensor, root_rank, name="param.%s" % key)


class DistributedOptimizer(object):
    """Wraps an ``mx.optimizer.Optimizer`` so each ``update`` first
    allreduce-averages the gradient (reference: mxnet/__init__.py:40-84,
    which proxies the wrapped optimizer the same way)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def _do_allreduce(self, index, grad):
        if _hvd.size() == 1:
            return
        if isinstance(index, (tuple, list)):
            for i in range(len(index)):
                allreduce_(grad[i], average=True,
                           name="grad.%s" % index[i])
        else:
            allreduce_(grad, average=True, name="grad.%s" % index)

    def update(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update_multi_precision(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self._optimizer.set_lr_mult(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self._optimizer.set_wd_mult(args_wd_mult)


def DistributedTrainer(params, optimizer, optimizer_params=None):
    """gluon ``Trainer`` whose gradient reduction rides this framework
    (reference: mxnet/__init__.py:85-108). The base Trainer's KVStore is
    disabled; ``_allreduce_grads`` averages through the host core."""
    mx = _mx()

    class _DistributedTrainer(mx.gluon.Trainer):
        def __init__(self, params, optimizer, optimizer_params=None):
            if isinstance(optimizer, DistributedOptimizer):
                optimizer = optimizer._optimizer
            super(_DistributedTrainer, self).__init__(
                params, optimizer, optimizer_params, kvstore=None)

        def _allreduce_grads(self):
            if _hvd.size() == 1:
                return
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    for grad in param.list_grad():
                        allreduce_(grad, average=True,
                                   name="grad.%d.%s" % (i, param.name))

    return _DistributedTrainer(params, optimizer, optimizer_params)
