"""MXNet binding slot (reference: ``horovod/mxnet/__init__.py``).

MXNet reached end-of-life and is not shipped in this environment; the
module exists to keep the binding registry complete (`--check-build`
reports it absent). Importing raises with a clear message, mirroring how
the reference gates unbuilt extensions
(`horovod/common/util.py check_extension`)."""

raise ImportError(
    "horovod_tpu.mxnet requires MXNet, which is not installed in this "
    "environment (MXNet is EOL upstream). Use horovod_tpu.jax (TPU-native), "
    "horovod_tpu.torch, horovod_tpu.tensorflow, or horovod_tpu.keras.")
