"""Breadth-first explicit-state explorer.

BFS (rather than DFS) is deliberate: the first time a violating state is
dequeued, the path to it is a shortest path, so every counterexample
trace is minimal by construction — no separate trace-minimization pass.
Canonical hashing with symmetry reduction (``Model.canon``) collapses
states that differ only by a permutation of interchangeable ranks, which
is what keeps 3–4-rank models in the low thousands of states.

Three property classes are checked:

- **invariants** — every dequeued state is run through every
  ``Invariant``; a failure is reported with the minimal trace.
- **deadlock** — a state with no enabled action where ``done`` is false.
- **livelock** — after exploration, a reachable cycle whose edges are all
  non-``progress`` actions through states where ``done`` is false: the
  system can run forever without anything real happening (e.g. the
  coordinator ticking fast cycles while a tensor never clears
  negotiation).
"""

import collections
import time

from .dsl import freeze


class Violation(object):
    """One property failure with its minimal counterexample.

    ``trace`` is a list of action names from the initial state; ``state``
    is the offending state (for livelock, a state on the cycle and
    ``cycle`` holds the repeating action suffix).
    """

    __slots__ = ("kind", "message", "invariant", "trace", "state", "cycle")

    def __init__(self, kind, message, trace, state,
                 invariant=None, cycle=None):
        self.kind = kind
        self.message = message
        self.invariant = invariant
        self.trace = list(trace)
        self.state = state
        self.cycle = list(cycle) if cycle else []

    def __repr__(self):
        return "Violation(%s, %r, %d steps)" % (
            self.kind, self.message, len(self.trace))


class BudgetExceeded(Exception):
    """Raised when exploration exceeds ``max_states``.

    A shipped model hitting this is itself a bug: the models are designed
    to close in well under the CI budget (see tests/test_model.py).
    """


ExploreResult = collections.namedtuple(
    "ExploreResult",
    [
        "model",        # the Model explored
        "num_states",   # canonical (symmetry-reduced) reachable states
        "num_edges",    # explored transitions
        "violations",   # list of Violation, minimal-trace-first
        "complete",     # False if stopped early at a violation
        "elapsed",      # wall seconds
    ],
)


def explore(model, max_states=200000, stop_at_first=True,
            check_liveness=True):
    """Exhaustively explore ``model``; return an :class:`ExploreResult`.

    With ``stop_at_first`` (the default) exploration stops at the first
    safety violation — BFS order guarantees its trace is minimal.  Pass
    ``False`` to keep going and collect every distinct violating state.
    """
    start = time.monotonic()
    init = model.init
    init_key = model.canon(init)

    states = {init_key: init}             # canonical key -> representative
    parent = {init_key: None}             # key -> (parent_key, action name)
    edges = collections.defaultdict(list)  # key -> [(name, succ, progress)]
    queue = collections.deque([init_key])
    violations = []
    num_edges = 0

    def trace_to(key):
        names = []
        cur = key
        while parent[cur] is not None:
            prev, name = parent[cur]
            names.append(name)
            cur = prev
        names.reverse()
        return names

    while queue:
        key = queue.popleft()
        state = states[key]

        for inv in model.invariants:
            if not inv.pred(state):
                violations.append(Violation(
                    "invariant",
                    "invariant %r violated%s" % (
                        inv.name,
                        " (%s)" % inv.detail if inv.detail else ""),
                    trace_to(key), state, invariant=inv))
                if stop_at_first:
                    return ExploreResult(
                        model, len(states), num_edges, violations,
                        False, time.monotonic() - start)

        enabled = model.enabled(state)
        if not enabled:
            if not model.done(state):
                violations.append(Violation(
                    "deadlock",
                    "no action enabled and the protocol is not done",
                    trace_to(key), state))
                if stop_at_first:
                    return ExploreResult(
                        model, len(states), num_edges, violations,
                        False, time.monotonic() - start)
            continue

        for action in enabled:
            succ = model.step(state, action)
            succ_key = model.canon(succ)
            num_edges += 1
            edges[key].append((action.name, succ_key, action.progress))
            if succ_key not in states:
                if len(states) >= max_states:
                    raise BudgetExceeded(
                        "model %r exceeded %d states" % (
                            model.name, max_states))
                states[succ_key] = succ
                parent[succ_key] = (key, action.name)
                queue.append(succ_key)

    if check_liveness and not violations:
        lv = _find_livelock(model, states, edges, trace_to)
        if lv is not None:
            violations.append(lv)

    return ExploreResult(model, len(states), num_edges, violations,
                         True, time.monotonic() - start)


def _find_livelock(model, states, edges, trace_to):
    """Find a reachable no-progress cycle through not-``done`` states.

    Iterative three-color DFS over the subgraph restricted to
    non-progress edges between states where ``done`` is false.  The first
    back edge closes a cycle the system can traverse forever without a
    single progress action firing.
    """
    sub = {}
    for key, outs in edges.items():
        if model.done(states[key]):
            continue
        nexts = [(name, succ) for (name, succ, progress) in outs
                 if not progress and succ in states
                 and not model.done(states[succ])]
        if nexts:
            sub[key] = nexts

    WHITE, GREY, BLACK = 0, 1, 2
    color = collections.defaultdict(int)
    on_path = []          # stack of (key, action-name-into-key)
    on_path_pos = {}

    for root in sub:
        if color[root] != WHITE:
            continue
        stack = [(root, None, iter(sub.get(root, ())))]
        on_path = [(root, None)]
        on_path_pos = {root: 0}
        color[root] = GREY
        while stack:
            key, _, it = stack[-1]
            advanced = False
            for name, succ in it:
                if color[succ] == GREY:
                    # Cycle: from succ's position on the path back to key,
                    # then the closing edge `name`.
                    pos = on_path_pos[succ]
                    cycle_names = [n for (_, n) in on_path[pos + 1:]]
                    cycle_names.append(name)
                    return Violation(
                        "livelock",
                        "no-progress cycle: the system can run forever "
                        "without completing (actions repeat: %s)"
                        % ", ".join(cycle_names),
                        trace_to(succ), states[succ], cycle=cycle_names)
                if color[succ] == WHITE:
                    color[succ] = GREY
                    stack.append((succ, name, iter(sub.get(succ, ()))))
                    on_path.append((succ, name))
                    on_path_pos[succ] = len(on_path) - 1
                    advanced = True
                    break
            if not advanced:
                done_key, _, _ = stack.pop()
                color[done_key] = BLACK
                popped = on_path.pop()
                on_path_pos.pop(popped[0], None)
    return None


def format_state(state, indent="    "):
    """Pretty-print a state dict for human trace output."""
    lines = []
    for k in sorted(state):
        lines.append("%s%s = %r" % (indent, k, state[k]))
    return "\n".join(lines)


def replay(model, trace):
    """Re-execute a trace (list of action names) from init; return states.

    Used by the human reporter to show the state after every step of a
    counterexample, and by tests to assert traces stay executable.
    """
    by_name = {a.name: a for a in model.actions}
    state = model.init
    out = [state]
    for name in trace:
        action = by_name[name]
        if not action.guard(state):
            raise ValueError("trace step %r not enabled" % (name,))
        state = model.step(state, action)
        out.append(state)
    return out


def assert_frozen_equal(a, b):
    """Helper for tests: compare two states modulo freezing."""
    return freeze(a) == freeze(b)
