"""bin/hvd-model — explore the shipped protocol models and re-find the
seeded historical bugs.

Two duties, both CI-gated via ``make check-model``:

1. every shipped (fixed) protocol model explores CLEAN — no invariant
   violation, no deadlock, no livelock — across its whole supported rank
   range;
2. every seeded "revert the fix in-model" bug variant produces a
   violation of the REQUIRED kind: a checker that stops re-finding the
   late-registration hang (or any other historical bug) is itself
   broken, and that is a CI failure even though the shipped models are
   clean.

Problems are emitted as hvd-lint ``Finding`` records anchored into the
model source files, so the human/JSON/SARIF reporters — including the
stable fingerprints SARIF consumers diff across runs — are reused
verbatim from ``horovod_tpu/lint/report.py``.
"""

import argparse
import sys

from ..report import format_human, format_json, format_sarif
from ..rules import ERROR, Finding, register_meta
from .explore import BudgetExceeded, explore, format_state, replay
from .protocols import MODELS

register_meta("model-invariant", ERROR,
              "a protocol model reached a state violating a safety "
              "invariant cross-referenced to the real implementation")
register_meta("model-deadlock", ERROR,
              "a protocol model reached a state with no enabled action "
              "before the protocol completed")
register_meta("model-livelock", ERROR,
              "a protocol model can cycle forever without progress")
register_meta("model-regression-missed", ERROR,
              "a seeded historical-bug variant no longer produces its "
              "violation — the checker lost a regression")
register_meta("model-budget", ERROR,
              "a protocol model exceeded the state budget — it no "
              "longer closes under the CI cap")


def _anchor(spec, needle):
    """Line in the model's source where ``needle`` appears (for finding
    anchors: invariants anchor at their definition, everything else at
    the model's NAME line)."""
    path = sys.modules[spec.build.__module__].__file__
    if path.endswith(".pyc"):
        path = path[:-1]
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for i, text in enumerate(fh, 1):
                if needle in text:
                    return path, i
    except OSError:
        pass
    return path, 1


def _violation_finding(spec, model, violation):
    kind = violation.kind
    if kind == "invariant" and violation.invariant is not None:
        path, line = _anchor(spec, '"%s"' % violation.invariant.name)
        ref = violation.invariant.code_ref
    else:
        path, line = _anchor(spec, "NAME = ")
        ref = ""
    msg = ("model %s: %s after %d step(s): %s"
           % (model.name, kind, len(violation.trace),
              " -> ".join(violation.trace) or "<initial state>"))
    if ref:
        msg += " [see %s]" % ref
    return Finding(path=path, line=line, col=1, rule="model-%s" % kind,
                   severity="error", message=msg, end_line=line)


def _print_trace(model, violation, out):
    out.write("\n  counterexample (%s, %d steps, minimal):\n"
              % (violation.kind, len(violation.trace)))
    try:
        states = replay(model, violation.trace)
    except (ValueError, KeyError):
        states = None
    for i, name in enumerate(violation.trace, 1):
        out.write("    %2d. %s\n" % (i, name))
    if violation.cycle:
        out.write("    ... then forever: %s\n"
                  % " -> ".join(violation.cycle))
    out.write("  final state:\n")
    final = states[-1] if states else violation.state
    out.write(format_state(final) + "\n")
    if violation.invariant is not None and violation.invariant.code_ref:
        out.write("  real code: %s\n" % violation.invariant.code_ref)


def _rank_list(spec, ranks_arg):
    if ranks_arg is not None:
        return [ranks_arg]
    lo, hi = spec.rank_range
    return list(range(lo, hi + 1))


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="hvd-model",
        description="explicit-state model checker for the coordination "
                    "protocols (see docs/MODEL.md)")
    ap.add_argument("--model", action="append", default=None,
                    metavar="NAME", help="check only this model "
                    "(repeatable; default: all)")
    ap.add_argument("--ranks", type=int, default=None,
                    help="rank count (default: each model's full "
                    "supported range)")
    ap.add_argument("--bug", default=None, metavar="NAME",
                    help="explore ONE seeded bug variant (requires "
                    "--model) and print its counterexample")
    ap.add_argument("--no-bugs", action="store_true",
                    help="skip the seeded-bug regressions")
    ap.add_argument("--format", default="human",
                    choices=("human", "json", "sarif"))
    ap.add_argument("--max-states", type=int, default=200000)
    ap.add_argument("--list", action="store_true",
                    help="list models and their seeded bugs")
    args = ap.parse_args(argv)
    out = sys.stdout

    if args.list:
        for spec in MODELS.values():
            out.write("%-12s ranks %d-%d  %s\n"
                      % (spec.name, spec.rank_range[0],
                         spec.rank_range[1], spec.description))
            for bug, bs in spec.bugs.items():
                out.write("  bug %-22s -> %-9s %s\n"
                          % (bug, bs.kind, bs.description))
        return 0

    names = args.model or list(MODELS)
    for name in names:
        if name not in MODELS:
            ap.error("unknown model %r (have: %s)"
                     % (name, ", ".join(MODELS)))
    if args.bug is not None and len(names) != 1:
        ap.error("--bug requires exactly one --model")

    findings = []
    human = args.format == "human"
    models_clean = bugs_refound = 0
    total_states = total_edges = 0

    # single-bug mode: show the counterexample and exit 0 if found
    if args.bug is not None:
        spec = MODELS[names[0]]
        if args.bug not in spec.bugs:
            ap.error("model %s has no bug %r (have: %s)"
                     % (spec.name, args.bug, ", ".join(spec.bugs)))
        model = spec.build(ranks=args.ranks, bug=args.bug)
        result = explore(model, max_states=args.max_states)
        expected = spec.bugs[args.bug].kind
        hit = [v for v in result.violations if v.kind == expected]
        if hit:
            out.write("%s: re-found %s (%d canonical states)\n"
                      % (model.name, expected, result.num_states))
            _print_trace(model, hit[0], out)
            return 0
        out.write("%s: expected a %s violation, found %s\n"
                  % (model.name, expected,
                     [v.kind for v in result.violations] or "nothing"))
        return 1

    for name in names:
        spec = MODELS[name]
        for ranks in _rank_list(spec, args.ranks):
            for model in spec.clean_builds(ranks):
                try:
                    result = explore(model, max_states=args.max_states)
                except BudgetExceeded as exc:
                    path, line = _anchor(spec, "NAME = ")
                    findings.append(Finding(
                        path=path, line=line, col=1, rule="model-budget",
                        severity="error", message=str(exc),
                        end_line=line))
                    continue
                total_states += result.num_states
                total_edges += result.num_edges
                if result.violations:
                    for v in result.violations:
                        findings.append(
                            _violation_finding(spec, model, v))
                        if human:
                            out.write("FAIL %s @ %d ranks\n"
                                      % (model.name, ranks))
                            _print_trace(model, v, out)
                else:
                    models_clean += 1
                    if human:
                        out.write("ok   %-28s @ %d ranks: %6d states, "
                                  "%7d transitions, clean (%.2fs)\n"
                                  % (model.name, ranks,
                                     result.num_states,
                                     result.num_edges, result.elapsed))

        if args.no_bugs:
            continue
        for bug, bs in spec.bugs.items():
            model = spec.build(ranks=None, bug=bug)
            try:
                result = explore(model, max_states=args.max_states)
            except BudgetExceeded as exc:
                path, line = _anchor(spec, '"%s"' % bug)
                findings.append(Finding(
                    path=path, line=line, col=1, rule="model-budget",
                    severity="error", message=str(exc), end_line=line))
                continue
            hit = [v for v in result.violations if v.kind == bs.kind]
            if hit:
                bugs_refound += 1
                if human:
                    out.write("ok   %-28s seeded bug re-found: %s in "
                              "%d step(s)\n"
                              % (model.name, bs.kind,
                                 len(hit[0].trace)))
            else:
                path, line = _anchor(spec, '"%s"' % bug)
                got = ([v.kind for v in result.violations]
                       if result.violations else "a clean exploration")
                findings.append(Finding(
                    path=path, line=line, col=1,
                    rule="model-regression-missed", severity="error",
                    message="model %s: seeded bug %r must produce a %s "
                            "violation but produced %s"
                            % (spec.name, bug, bs.kind, got),
                    end_line=line))
                if human:
                    out.write("FAIL %s: seeded bug %r NOT re-found "
                              "(%s)\n" % (model.name, bug, got))

    if args.format == "json":
        format_json(findings, len(names), out)
    elif args.format == "sarif":
        format_sarif(findings, len(names), out, tool_name="hvd-model",
                     information_uri="docs/MODEL.md")
    else:
        if findings:
            format_human(findings, out)
        out.write("hvd-model: %d model explorations clean (%d canonical "
                  "states, %d transitions), %d seeded bugs re-found, "
                  "%d problem(s)\n"
                  % (models_clean, total_states, total_edges,
                     bugs_refound, len(findings)))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
