"""State-machine DSL for the protocol models.

A model is a set of *guarded atomic actions* over a single shared state
dict.  There is no separate process object: a "process" is a naming
convention (actions named ``"w1.publish"`` belong to process ``w1``) plus
an optional symmetry declaration saying which processes are
interchangeable.  This keeps the DSL honest about what explicit-state
checking actually explores — one flat transition relation — while still
letting models read like per-process pseudocode.

State values must be hashable after :func:`freeze` (ints, bools, strings,
tuples, frozensets, or nested dicts thereof).  Effects receive a deep
copy and mutate it in place; the explorer freezes the result for hashing,
so models never worry about aliasing.
"""

import copy
import itertools


def freeze(value):
    """Recursively convert a state value into a hashable canonical form.

    Dicts become sorted (key, value) tuples, lists/tuples become tuples,
    sets become frozensets of frozen elements.  Used both for the visited
    set and for symmetry canonicalization (min over permuted freezings).
    """
    if isinstance(value, dict):
        return tuple(sorted((k, freeze(v)) for k, v in value.items()))
    if isinstance(value, (list, tuple)):
        return tuple(freeze(v) for v in value)
    if isinstance(value, (set, frozenset)):
        return frozenset(freeze(v) for v in value)
    return value


class Action(object):
    """One guarded atomic step.

    ``guard(state) -> bool`` decides enabledness; ``effect(state)``
    mutates a private copy.  ``progress=True`` marks actions that
    represent real forward progress for liveness purposes: a reachable
    cycle that uses only non-progress actions while the model is not
    ``done`` is reported as a livelock (e.g. the coordinator spinning
    fast cycles forever while a tensor never clears negotiation).
    """

    __slots__ = ("name", "guard", "effect", "progress")

    def __init__(self, name, guard, effect, progress=False):
        self.name = name
        self.guard = guard
        self.effect = effect
        self.progress = progress

    def __repr__(self):
        return "Action(%r)" % (self.name,)


class Invariant(object):
    """A safety predicate checked in every reachable state.

    ``code_ref`` anchors the property to the real implementation
    (``"horovod_tpu/native/controller.cc:449"``) so a violation report
    points at the code whose behavior the invariant abstracts.
    """

    __slots__ = ("name", "pred", "detail", "code_ref")

    def __init__(self, name, pred, detail="", code_ref=""):
        self.name = name
        self.pred = pred
        self.detail = detail
        self.code_ref = code_ref

    def __repr__(self):
        return "Invariant(%r)" % (self.name,)


class Model(object):
    """A closed system: initial state, actions, properties.

    Parameters
    ----------
    name: model identifier (``"cache_bits"``).
    init: initial state dict.
    actions: list of :class:`Action`.
    invariants: list of :class:`Invariant` checked in every state.
    done: predicate marking acceptable terminal states.  A state with no
        enabled action where ``done`` is false is a deadlock; a
        no-progress cycle through states where ``done`` is false is a
        livelock.
    symmetry: list of process-id lists that are interchangeable
        (e.g. ``[[1, 2, 3]]`` for worker ranks).  The explorer
        canonicalizes each state as the minimum freezing over all
        permutations within each class, collapsing symmetric
        interleavings.
    permute: ``permute(state, mapping) -> state`` applying a pid
        renaming.  The default handles the common layout where
        per-process values live in dicts keyed by pid; models that store
        pid *values* inside globals must supply their own.
    source: path of the module defining the model (for report anchors).
    """

    def __init__(self, name, init, actions, invariants=(), done=None,
                 symmetry=(), permute=None, source=""):
        self.name = name
        self.init = init
        self.actions = list(actions)
        self.invariants = list(invariants)
        self.done = done if done is not None else (lambda s: True)
        self.symmetry = [list(cls) for cls in symmetry]
        self._permute = permute
        self.source = source

    # -- symmetry ---------------------------------------------------------

    def permutations(self):
        """Yield pid->pid mappings for the full symmetry group (incl. id)."""
        if not self.symmetry:
            yield {}
            return
        per_class = []
        for cls in self.symmetry:
            per_class.append([dict(zip(cls, perm))
                              for perm in itertools.permutations(cls)])
        for combo in itertools.product(*per_class):
            mapping = {}
            for m in combo:
                mapping.update(m)
            yield mapping

    def permute(self, state, mapping):
        if not mapping or all(k == v for k, v in mapping.items()):
            return state
        if self._permute is not None:
            return self._permute(state, mapping)
        return default_permute(state, mapping)

    def canon(self, state):
        """Canonical hashable form: min freezing over the symmetry group."""
        if not self.symmetry:
            return freeze(state)
        return min(freeze(self.permute(state, m))
                   for m in self.permutations())

    # -- execution --------------------------------------------------------

    def enabled(self, state):
        return [a for a in self.actions if a.guard(state)]

    def step(self, state, action):
        nxt = copy.deepcopy(state)
        action.effect(nxt)
        return nxt


def default_permute(state, mapping):
    """Permute a state whose per-process values live in pid-keyed dicts.

    Any dict (at any nesting level) whose keys are all ints is treated as
    pid-indexed and re-keyed through ``mapping``; everything else is
    copied through.  Pid values stored elsewhere (e.g. a global holding
    "the rank that won") need a model-specific permute.
    """
    def walk(v):
        if isinstance(v, dict):
            if v and all(isinstance(k, int) for k in v):
                return {mapping.get(k, k): walk(val) for k, val in v.items()}
            return {k: walk(val) for k, val in v.items()}
        if isinstance(v, list):
            return [walk(x) for x in v]
        if isinstance(v, (set, frozenset)):
            return type(v)(walk(x) for x in v)
        return v

    return walk(state)
