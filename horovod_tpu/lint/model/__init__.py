"""hvd-model: explicit-state model checking for the coordination protocols.

hvd-verify (``horovod_tpu/lint/``) proves that per-rank collective
*schedules* agree.  This package covers the orthogonal failure class:
cross-process *interleavings*.  Every coordination protocol the runtime
ships — response-cache bit sync, elastic drain agreement, the SPSC shm
ring's futex wake protocol, group-ring connection establishment — is
modeled as a set of processes taking guarded atomic actions over shared
state, and the explorer enumerates every reachable interleaving, checking
invariants, deadlocks, and livelock (no-progress cycles).

Layout:

- ``dsl.py``      — Action/Invariant/Model: the state-machine DSL.
- ``explore.py``  — BFS explorer with canonical hashing, symmetry
                    reduction over rank permutations, and minimal
                    counterexample traces.
- ``protocols/``  — the shipped models, each cross-referenced
                    ``file:line`` to the real implementation and each
                    carrying "revert the fix" bug variants that the
                    checker must re-find (regressions for the historical
                    bugs in CHANGES.md).
- ``cli.py``      — ``bin/hvd-model``: human/JSON/SARIF reporters
                    reusing hvd-lint's fingerprinting.

See docs/MODEL.md for the DSL reference and how to read a trace.
"""

from .dsl import Action, Invariant, Model, freeze  # noqa: F401
from .explore import ExploreResult, Violation, explore  # noqa: F401
