"""Group-ring connection establishment: connect-before-accept with the
stash/reconnect window (PR 10, round-2 fix).

What is modeled
---------------
Three ranks, two overlapping 2-member groups: ``g1 = {0, 1}`` and
``g2 = {0, 2}``.  Rank 0 builds its rings in op order (g1 then g2 — one
controller thread).  ``GroupPairConnect`` connects to the ring successor
FIRST — the TCP backlog completes the connect without the peer accepting,
so connect never blocks and the connect/accept cycle cannot deadlock
(horovod_tpu/native/tcp_context.cc:634-660) — then accepts from the
predecessor.  The accept loop pops whatever connection arrives next:
group connects are ONE-SHOT on the connector side, so an accepted
connect belonging to a *different* group (rank 2 racing ahead into
rank 0's g1 build) must be stashed under ``GroupFdKey(gid, chan, rank)``
for that group's own build to find (tcp_context.cc:666-671 consume,
:704-714 stash) — dropping it wedges the later build forever.  The
round-2 fix extends the same stash to group connects that land inside a
control-RECONNECT accept window (tcp_context.cc:1081-1085).

Seeded bugs (revert the fix in-model):

- ``no_stash`` — a mismatched group connect accepted during another
  group's build is dropped.  Rank 2's g2 connect races into rank 0's g1
  build and is destroyed; rank 2 will never reconnect (one-shot), so
  rank 0's g2 accept waits forever → **deadlock** (the PR 10 round-1
  hang).
- ``reconnect_drop`` — a group connect landing inside rank 0's control
  reconnect window is closed instead of stashed → same wedge →
  **deadlock** (the round-2 race).
"""

import collections

from ..dsl import Action, Invariant, Model
from ._bugspec import BugSpec

NAME = "group_ring"
DESCRIPTION = ("group-ring connect-before-accept with the stash for "
               "cross-group and reconnect-window races")
DEFAULT_RANKS = 3
RANK_RANGE = (3, 3)

BUGS = collections.OrderedDict([
    ("no_stash", BugSpec(
        "deadlock",
        "mismatched group connect dropped during another group's "
        "build: the one-shot connector never retries, the group's own "
        "build waits forever")),
    ("reconnect_drop", BugSpec(
        "deadlock",
        "group connect landing inside the control reconnect window is "
        "closed instead of stashed — same wedge, round-2 race")),
])

G1, G2 = "g1", "g2"
GROUPS = {G1: (0, 1), G2: (0, 2)}
# builds: (rank, group) pairs; rank 0 builds g1 before g2 (op order)
BUILDS = ((0, G1), (1, G1), (0, G2), (2, G2))


def _peer(rank, group):
    a, b = GROUPS[group]
    return b if rank == a else a


def build(ranks=None, bug=None):
    if ranks is not None and int(ranks) != DEFAULT_RANKS:
        raise ValueError("group_ring models exactly 3 ranks "
                         "(two overlapping 2-member groups)")
    if bug is not None and bug not in BUGS:
        raise ValueError("unknown bug %r" % (bug,))

    # In no_stash the reconnect window is irrelevant (the round-1 race
    # already wedges); keep it shut so the counterexample is minimal.
    recon_active = bug != "no_stash"

    init = {
        "phase": {b: "todo" for b in BUILDS},
        "backlog": {r: frozenset() for r in range(3)},
        "stash": {r: frozenset() for r in range(3)},
        "recon": "idle" if recon_active else "closed",
    }

    def match_token(b):
        rank, group = b
        return (_peer(rank, group), group)

    def gated(s, b):
        # rank 0's second build waits for the first (op order)
        return b == (0, G2) and s["phase"][(0, G1)] != "done"

    def mk_connect(b):
        rank, group = b

        def guard(s):
            if s["phase"][b] != "todo" or gated(s, b):
                return False
            if b == (0, G1) and s["recon"] == "open":
                return False        # controller busy in the window
            return True

        def effect(s):
            s["phase"][b] = "conn"
            peer = _peer(rank, group)
            s["backlog"][peer] = s["backlog"][peer] | {(rank, group)}
            if b == (0, G1) and s["recon"] == "idle":
                s["recon"] = "closed"   # window never opened
        return Action("r%d.connect_%s" % (rank, group), guard, effect)

    def mk_accept_match(b):
        rank, group = b
        tok = match_token(b)

        def guard(s):
            return (s["phase"][b] == "conn"
                    and (tok in s["backlog"][rank]
                         or tok in s["stash"][rank]))

        def effect(s):
            # tcp_context.cc:666-671 — the stash is consulted first
            if tok in s["stash"][rank]:
                s["stash"][rank] = s["stash"][rank] - {tok}
            else:
                s["backlog"][rank] = s["backlog"][rank] - {tok}
            s["phase"][b] = "done"
        return Action("r%d.accept_%s" % (rank, group), guard, effect,
                      progress=True)

    def mk_accept_other(b, tok):
        rank, _ = b

        def guard(s):
            return (s["phase"][b] == "conn"
                    and tok != match_token(b)
                    and tok in s["backlog"][rank])

        def effect(s):
            s["backlog"][rank] = s["backlog"][rank] - {tok}
            if bug != "no_stash":
                # tcp_context.cc:704-714 — stash by (group, chan, rank)
                s["stash"][rank] = s["stash"][rank] | {tok}
            # else: dropped — the connector is one-shot and never retries
        label = "drop" if bug == "no_stash" else "stash"
        return Action("r%d.accept_%s_foreign_r%d_%s"
                      % (rank, label, tok[0], tok[1]), guard, effect)

    actions = [mk_connect(b) for b in BUILDS]
    actions += [mk_accept_match(b) for b in BUILDS]
    # the only cross-group race lands on rank 0: rank 2's g2 connect
    # arriving during the g1 build
    actions.append(mk_accept_other((0, G1), (2, G2)))

    if recon_active:
        def recon_pop_effect(s):
            tok = (2, G2)
            s["backlog"][0] = s["backlog"][0] - {tok}
            if bug != "reconnect_drop":
                # tcp_context.cc:1081-1085 — round-2 fix: stash group
                # connects landing inside the reconnect window too
                s["stash"][0] = s["stash"][0] | {tok}

        actions.append(Action(
            "r0.reconnect_window_open",
            lambda s: s["recon"] == "idle"
            and s["phase"][(0, G1)] == "todo",
            lambda s: s.update(recon="open")))
        actions.append(Action(
            "r0.reconnect_pop_group_connect",
            lambda s: s["recon"] == "open"
            and (2, G2) in s["backlog"][0],
            recon_pop_effect))
        actions.append(Action(
            "r0.reconnect_window_close",
            lambda s: s["recon"] == "open",
            lambda s: s.update(recon="closed")))

    invariants = [
        Invariant(
            "no-connection-invented",
            lambda s: all(s["phase"][b] != "done"
                          or match_token(b) not in s["backlog"][b[0]]
                          for b in BUILDS),
            "a completed build consumed its peer's one-shot connect — "
            "it cannot still be pending",
            "horovod_tpu/native/tcp_context.cc:634"),
    ]

    def done(s):
        return all(s["phase"][b] == "done" for b in BUILDS)

    return Model(NAME if bug is None else "%s[%s]" % (NAME, bug),
                 init, actions, invariants, done, symmetry=(),
                 source=__file__)
