"""The shipped protocol models and their seeded historical-bug variants.

Each module exports ``build(ranks=None, bug=None)`` returning a
:class:`~horovod_tpu.lint.model.dsl.Model`.  ``bug`` selects a
"revert the fix in-model" variant; the registry records, per bug, the
violation kind the checker is required to re-find (these are the CI
regressions for the historical bugs logged in CHANGES.md).
"""

import collections

from . import cache_bits, drain, group_ring, rendezvous, shm_ring
from ._bugspec import BugSpec  # noqa: F401  (re-exported)

ModelSpec = collections.namedtuple(
    "ModelSpec",
    ["name", "build", "clean_builds", "bugs", "default_ranks",
     "rank_range", "description"])


def _spec(mod):
    # ``clean_builds(ranks)`` returns every fixed model a module ships
    # (some protocols carry a sub-protocol, e.g. drain's sticky slots).
    clean = getattr(mod, "clean_builds",
                    lambda ranks=None, _m=mod: [_m.build(ranks)])
    return ModelSpec(mod.NAME, mod.build, clean, mod.BUGS,
                     mod.DEFAULT_RANKS, mod.RANK_RANGE, mod.DESCRIPTION)


MODELS = collections.OrderedDict(
    (mod.NAME, _spec(mod))
    for mod in (cache_bits, drain, rendezvous, shm_ring, group_ring))
