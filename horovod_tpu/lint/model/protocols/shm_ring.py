"""SPSC shm ring: the spin-then-futex waiter-gated wake protocol,
including the closed-word hangup (PR 15).

What is modeled
---------------
One writer, one reader, a ring of capacity 1 carrying 2 items — small
enough to close exhaustively, large enough that the writer exercises the
write-side wait path too.  The wake protocol's individual memory
accesses are separate atomic actions, so the explorer interleaves them
exactly as two CPUs would under sequential consistency:

- writer publish: occupy a slot → ``data_seq.fetch_add`` →
  load ``read_waiters`` → conditional ``FutexWake``
  (horovod_tpu/native/shm_context.cc:302-305; the space-side mirror is
  :328-330 and is modeled atomically in ``r.consume`` — same protocol,
  same proof).
- waiter park: set the waiters flag → load the seq word → recheck
  emptiness/closed → ``FutexWait(expected=seq)`` where the kernel
  re-compares the word and refuses to sleep on a stale value
  (shm_context.cc:369-376 read side, :386-399 write side).
- close: set ``closed`` → bump BOTH seq words → unconditional wakes
  (shm_context.cc:250-257); EOF only after the ring drains (:315-317).

The fixed model is the PR 15 hand-proof, mechanized: under SC, either
the publisher sees the waiter flag (and wakes) or the parking side sees
the bumped seq (and refuses to sleep).  Spin iterations are not modeled
— scheduling nondeterminism covers every spin-count outcome.  Futex
timeouts are also omitted deliberately: the production timeout would
re-poll and mask a missed wake as latency; the model checks the wake
protocol proper, where a missed wake is a hang.

Seeded bugs (revert the fix in-model):

- ``missed_wake`` — the writer's ``read_waiters`` load is hoisted above
  publish+bump (what a relaxed load/store pair permits the hardware to
  do).  The reader parks in the window, the writer publishes without
  waking, fills the ring, parks on the space side → both sides asleep →
  **deadlock**.  This is the exact hazard the seq_cst pairing at
  shm_context.cc:302-303 forbids (and the lockorder atomics-pairing
  rule now checks statically).
- ``no_close_wake`` — ``Close()`` sets the closed word but neither bumps
  the seqs nor wakes.  A reader that parked just before the hangup never
  observes EOF → **deadlock** (the closed-word hangup).
"""

import collections

from ..dsl import Action, Invariant, Model
from ._bugspec import BugSpec

NAME = "shm_ring"
DESCRIPTION = ("SPSC shm ring spin-then-futex wake protocol "
               "(waiter-gated wake, closed-word hangup)")
DEFAULT_RANKS = 2          # one writer, one reader — SPSC by contract
RANK_RANGE = (2, 2)
ITEMS = 2                  # frames the writer ships
CAP = 1                    # ring capacity: forces the write-side wait

BUGS = collections.OrderedDict([
    ("missed_wake", BugSpec(
        "deadlock",
        "waiters load hoisted above publish+seq-bump: reader parks in "
        "the window, ring fills, writer parks too — both asleep")),
    ("no_close_wake", BugSpec(
        "deadlock",
        "Close() without seq bumps + unconditional wakes: a reader "
        "parked just before hangup never sees EOF")),
])


def build(ranks=None, bug=None):
    if ranks is not None and int(ranks) != 2:
        raise ValueError("shm_ring is SPSC: exactly 2 processes")
    if bug is not None and bug not in BUGS:
        raise ValueError("unknown bug %r" % (bug,))

    init = {
        "occ": 0, "written": 0, "read": 0,
        "dseq": 0, "sseq": 0,          # data_seq / space_seq
        "rw": 0, "ww": 0,              # read_waiters / write_waiters
        "closed": False,
        "wpc": "idle", "rpc": "idle",  # program counters
        "wsaw": 0,                     # bug only: stale waiters load
        "rexp": 0, "wexp": 0,          # FutexWait expected values
    }

    def unpark_reader(s):
        if s["rpc"] == "r_parked":
            # FutexWake unblocks; the waiter clears its own flag on the
            # way out (shm_context.cc:376) — collapsed into the unpark.
            s["rpc"] = "idle"
            s["rw"] = 0

    def unpark_writer(s):
        if s["wpc"] == "w_parked":
            s["wpc"] = "idle"          # shm_context.cc:399
            s["ww"] = 0

    actions = []
    add = actions.append

    # -- writer: publish path --------------------------------------------

    def can_start_write(s):
        return (s["wpc"] == "idle" and s["written"] < ITEMS
                and not s["closed"])

    if bug == "missed_wake":
        add(Action(
            "w.stale_waiter_load",
            lambda s: can_start_write(s) and s["occ"] < CAP,
            lambda s: (s.update(wsaw=s["rw"], wpc="w_pub"))))
        add(Action(
            "w.publish",
            lambda s: s["wpc"] == "w_pub",
            lambda s: s.update(occ=s["occ"] + 1,
                               written=s["written"] + 1, wpc="w_bump"),
            progress=True))
        add(Action(
            "w.bump_data_seq",
            lambda s: s["wpc"] == "w_bump",
            lambda s: s.update(dseq=s["dseq"] + 1, wpc="w_wake")))

        def wake_effect(s):
            if s["wsaw"]:
                unpark_reader(s)
            s["wpc"] = "idle"
        add(Action("w.wake_if_stale_saw_waiter",
                   lambda s: s["wpc"] == "w_wake", wake_effect))
    else:
        add(Action(
            "w.publish",
            lambda s: can_start_write(s) and s["occ"] < CAP,
            lambda s: s.update(occ=s["occ"] + 1,
                               written=s["written"] + 1, wpc="w_bump"),
            progress=True))
        add(Action(
            "w.bump_data_seq",          # shm_context.cc:302
            lambda s: s["wpc"] == "w_bump",
            lambda s: s.update(dseq=s["dseq"] + 1, wpc="w_wake")))

        def wake_effect(s):
            if s["rw"]:                  # shm_context.cc:303-305
                unpark_reader(s)
            s["wpc"] = "idle"
        add(Action("w.wake_if_read_waiters",
                   lambda s: s["wpc"] == "w_wake", wake_effect))

    # -- writer: wait-for-space path (shm_context.cc:386-399) ------------

    add(Action(
        "w.set_write_waiters",
        lambda s: (s["wpc"] == "idle" and s["written"] < ITEMS
                   and s["occ"] >= CAP and not s["closed"]),
        lambda s: s.update(ww=1, wpc="w_ldseq")))
    add(Action(
        "w.load_space_seq",
        lambda s: s["wpc"] == "w_ldseq",
        lambda s: s.update(wexp=s["sseq"], wpc="w_recheck")))

    def w_recheck_effect(s):
        if s["occ"] < CAP or s["closed"]:
            s["ww"] = 0
            s["wpc"] = "idle"
        else:
            s["wpc"] = "w_park"
    add(Action("w.recheck_space",
               lambda s: s["wpc"] == "w_recheck", w_recheck_effect))

    def w_park_effect(s):
        if s["sseq"] == s["wexp"]:
            s["wpc"] = "w_parked"        # kernel compare passed
        else:
            s["ww"] = 0                  # stale expected: EAGAIN, retry
            s["wpc"] = "idle"
    add(Action("w.futex_wait_space",
               lambda s: s["wpc"] == "w_park", w_park_effect))

    # -- writer: close (shm_context.cc:250-257) --------------------------

    def close_effect(s):
        s["closed"] = True
        if bug != "no_close_wake":
            s["dseq"] += 1
            s["sseq"] += 1
            unpark_reader(s)             # unconditional wakes
            unpark_writer(s)
    add(Action(
        "w.close",
        lambda s: (s["wpc"] == "idle" and s["written"] == ITEMS
                   and not s["closed"]),
        close_effect, progress=True))

    # -- reader ----------------------------------------------------------

    def consume_effect(s):
        # ReadSome: drain a frame, bump space_seq, gated wake of the
        # writer (shm_context.cc:328-330).  Modeled atomically in the
        # CORRECT order (bump before waiter load); the write side above
        # is where the seeded ordering bug lives.
        s["occ"] -= 1
        s["read"] += 1
        s["sseq"] += 1
        if s["ww"]:
            unpark_writer(s)
    add(Action("r.consume",
               lambda s: s["rpc"] == "idle" and s["occ"] > 0,
               consume_effect, progress=True))
    add(Action(
        "r.eof",                         # shm_context.cc:315-317
        lambda s: (s["rpc"] == "idle" and s["occ"] == 0 and s["closed"]),
        lambda s: s.update(rpc="r_done"), progress=True))
    add(Action(
        "r.set_read_waiters",            # shm_context.cc:369
        lambda s: (s["rpc"] == "idle" and s["occ"] == 0
                   and not s["closed"]),
        lambda s: s.update(rw=1, rpc="r_ldseq")))
    add(Action(
        "r.load_data_seq",
        lambda s: s["rpc"] == "r_ldseq",
        lambda s: s.update(rexp=s["dseq"], rpc="r_recheck")))

    def r_recheck_effect(s):
        if s["occ"] > 0 or s["closed"]:  # shm_context.cc:370-373
            s["rw"] = 0
            s["rpc"] = "idle"
        else:
            s["rpc"] = "r_park"
    add(Action("r.recheck_empty",
               lambda s: s["rpc"] == "r_recheck", r_recheck_effect))

    def r_park_effect(s):
        if s["dseq"] == s["rexp"]:       # shm_context.cc:374
            s["rpc"] = "r_parked"
        else:
            s["rw"] = 0
            s["rpc"] = "idle"
    add(Action("r.futex_wait_data",
               lambda s: s["rpc"] == "r_park", r_park_effect))

    invariants = [
        Invariant(
            "ring-accounting",
            lambda s: (s["occ"] == s["written"] - s["read"]
                       and 0 <= s["occ"] <= CAP),
            "occupancy is exactly written-minus-read and bounded by "
            "capacity — no frame is lost or duplicated",
            "horovod_tpu/native/shm_context.cc:281"),
        Invariant(
            "eof-only-after-drain",
            lambda s: (s["rpc"] != "r_done"
                       or (s["read"] == s["written"] and s["closed"])),
            "EOF is reported only once the ring drained AND the peer "
            "hung up — closed with bytes in flight keeps reading",
            "horovod_tpu/native/shm_context.cc:315"),
    ]

    def done(s):
        return (s["closed"] and s["rpc"] == "r_done"
                and s["read"] == ITEMS and s["wpc"] == "idle")

    return Model(NAME if bug is None else "%s[%s]" % (NAME, bug),
                 init, actions, invariants, done, symmetry=(),
                 source=__file__)
