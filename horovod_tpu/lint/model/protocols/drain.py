"""Elastic drain agreement: KV publish → indicator-allreduce → sticky
force-write (PR 7, hardened in PR 13's satellite fix).

Two models share this module:

``drain`` (this file's ``build``) — the agreement protocol itself.
A drain record is published to the KV at an arbitrary moment; each rank's
local KV poll is rate-limited, so ranks see it at different commits.  The
fix under test: the drain *decision* is never taken from the local poll
alone — an indicator allreduce rides EVERY commit (commits are the
elastic contract's rank-uniform points), all ranks act on the OR'd
result at the same commit, and a rank whose own poll missed the record
re-reads the KV synchronously (a peer proved it exists).

Real-code anchors:

- horovod_tpu/elastic/run.py:140-191 — ``poll_drain_agreement``:
  rate-limited local read (:156-158), ``_hvd_drain_poll`` indicator
  allreduce at every commit (:162-166), ``agreed < 0.5`` (:167),
  bounded synchronous re-read when a peer agreed (:170-178).
- horovod_tpu/elastic/run.py:417 — the agreed drain force-enqueues the
  sticky snapshot at that same commit.

Seeded bug ``local_poll`` — revert to acting on the local poll alone.
The rank that saw the record drains immediately; a peer that has not
seen it yet enters the next training allreduce and waits for the drained
rank forever → **deadlock** (the pre-PR-7 behavior the agreement was
built to kill).  The ``drain-step-uniform`` invariant additionally pins
the contract the fix restores.

``build_sticky`` — the sticky snapshot slots in the durable writer.
The drain's force-enqueued snapshot must survive newer stickies: the
writer thread drains slots at its own pace with a bounded write budget,
so "latest wins" on a single slot lets a newer sticky displace the
first one before it was ever written — two ranks then write disjoint
sticky steps, no step is written by ALL ranks, and no manifest can
anchor (ranks anti-align).  The fix pins the OLDEST unwritten sticky
(``_sticky_head``, capture decided at enqueue = rank-uniform) and keeps
latest-wins only among newer stickies (``_sticky_next``).

Real-code anchors: horovod_tpu/elastic/durable.py:544-559 (slot
contract), :635-659 (``maybe_enqueue``), :670-690 (``force_enqueue``),
:707-710 (writer dequeue: head, then promote next).

Seeded bug ``sticky_displacement`` — collapse head/next back to a single
latest-wins slot → terminal states where the ranks' written sticky sets
have an empty intersection → **invariant** ``common-written-sticky``.
"""

import collections

from ..dsl import Action, Invariant, Model
from ._bugspec import BugSpec

NAME = "drain"
DESCRIPTION = ("elastic drain agreement: rate-limited KV poll + "
               "indicator allreduce at every commit")
DEFAULT_RANKS = 3
RANK_RANGE = (2, 4)
COMMITS = 2  # bounded horizon: enough for every see/miss split

BUGS = collections.OrderedDict([
    ("local_poll", BugSpec(
        "deadlock",
        "acting on the local KV poll alone: the seeing rank drains "
        "while a peer waits in the next allreduce forever")),
    ("sticky_displacement", BugSpec(
        "invariant",
        "single latest-wins sticky slot: a newer sticky displaces the "
        "unwritten first one; ranks write disjoint sticky steps and no "
        "manifest can anchor")),
])

RUN, DRAINED, FINISHED = "run", "drained", "finished"


def build(ranks=None, bug=None):
    if bug == "sticky_displacement":
        return build_sticky(ranks)
    n = DEFAULT_RANKS if ranks is None else int(ranks)
    if not (RANK_RANGE[0] <= n <= RANK_RANGE[1]):
        raise ValueError("drain supports %d-%d ranks" % RANK_RANGE)
    if bug is not None and bug not in BUGS:
        raise ValueError("unknown bug %r" % (bug,))
    all_ranks = list(range(n))

    init = {
        "published": False,
        "seen": {r: False for r in all_ranks},
        "status": {r: RUN for r in all_ranks},
        "step": {r: 1 for r in all_ranks},        # commit being entered
        "contributed": {r: False for r in all_ranks},
        "indicator": {r: 0 for r in all_ranks},
        "drain_step": {r: 0 for r in all_ranks},  # 0 = not drained
    }

    def publish_effect(s):
        s["published"] = True

    def mk_poll(r):
        # The rate-limited local KV read (run.py:156-158).  Whether it
        # lands before a given commit is scheduling nondeterminism —
        # exactly what the rate limit makes true in production.
        def guard(s):
            return (s["published"] and not s["seen"][r]
                    and s["status"][r] == RUN and not s["contributed"][r])

        def effect(s):
            s["seen"][r] = True
        return Action("w%d.poll_kv" % r, guard, effect)

    if bug == "local_poll":
        def mk_decide(r):
            # BUG: the commit-time decision uses only the local poll.
            def guard(s):
                return s["status"][r] == RUN and not s["contributed"][r]

            def effect(s):
                if s["seen"][r]:
                    s["status"][r] = DRAINED
                    s["drain_step"][r] = s["step"][r]
                else:
                    s["contributed"][r] = True
                    s["indicator"][r] = 0
            return Action("w%d.commit" % r, guard, effect)
        arrive_actions = [mk_decide(r) for r in all_ranks]
    else:
        def mk_arrive(r):
            # Fixed: every running rank contributes its indicator to the
            # commit's allreduce unconditionally (run.py:162-166).
            def guard(s):
                return s["status"][r] == RUN and not s["contributed"][r]

            def effect(s):
                s["contributed"][r] = True
                s["indicator"][r] = 1 if s["seen"][r] else 0
            return Action("w%d.commit" % r, guard, effect)
        arrive_actions = [mk_arrive(r) for r in all_ranks]

    def resolve_guard(s):
        running = [r for r in all_ranks if s["status"][r] == RUN]
        # The ring's membership is fixed until re-bootstrap: the
        # allreduce completes only when EVERY rank arrived — a drained
        # rank never will, which is precisely the hang the agreement
        # prevents.
        return (bool(running)
                and all(s["status"][r] == RUN for r in all_ranks)
                and all(s["contributed"][r] for r in running))

    def resolve_effect(s):
        agreed = any(s["indicator"][r] for r in all_ranks)
        for r in all_ranks:
            s["contributed"][r] = False
            s["indicator"][r] = 0
            if bug != "local_poll" and agreed:
                # run.py:170-178 — a rank that agreed without seeing the
                # record re-reads the KV synchronously (bounded): the
                # record is committed before any peer can report it.
                s["seen"][r] = True
                s["status"][r] = DRAINED
                s["drain_step"][r] = s["step"][r]
            elif s["step"][r] == COMMITS:
                s["status"][r] = FINISHED
            else:
                s["step"][r] += 1

    actions = [Action("driver.publish_record",
                      lambda s: not s["published"], publish_effect)]
    actions.extend(mk_poll(r) for r in all_ranks)
    actions.extend(arrive_actions)
    actions.append(Action("ring.allreduce", resolve_guard, resolve_effect,
                          progress=True))

    invariants = [
        Invariant(
            "drain-step-uniform",
            lambda s: len({s["drain_step"][r] for r in all_ranks
                           if s["status"][r] == DRAINED}) <= 1,
            "every rank drains at the same commit — the agreement is "
            "taken from the allreduced indicator, not the local poll",
            "horovod_tpu/elastic/run.py:162"),
        Invariant(
            "drain-implies-record",
            lambda s: all(s["seen"][r] for r in all_ranks
                          if s["status"][r] == DRAINED),
            "a draining rank has read the drain record (post-agreement "
            "bounded re-read closes the gap)",
            "horovod_tpu/elastic/run.py:170"),
    ]

    def done(s):
        st = {s["status"][r] for r in all_ranks}
        if st == {DRAINED}:
            return len({s["drain_step"][r] for r in all_ranks}) == 1
        return st == {FINISHED}

    return Model(NAME if bug is None else "%s[%s]" % (NAME, bug),
                 init, actions, invariants, done,
                 symmetry=[all_ranks], source=__file__)


def clean_builds(ranks=None):
    """Both fixed models this module ships: the agreement protocol and
    the durable writer's sticky slots."""
    return [build(ranks), build_sticky(ranks, bug=None)]


# -- sticky snapshot slots (durable writer) ------------------------------

STICKIES = 2     # two sticky snapshots per rank, steps 1 then 2
BUDGET = 1       # writer budget before terminal: slow storage


def build_sticky(ranks=None, bug="sticky_displacement"):
    """The durable writer's sticky slots; ``bug=None`` for the fixed
    head/next protocol, ``"sticky_displacement"`` for the single
    latest-wins slot it replaced."""
    n = DEFAULT_RANKS if ranks is None else int(ranks)
    if not (RANK_RANGE[0] <= n <= RANK_RANGE[1]):
        raise ValueError("drain supports %d-%d ranks" % RANK_RANGE)
    all_ranks = list(range(n))
    single_slot = bug == "sticky_displacement"

    init = {
        "enq": {r: 0 for r in all_ranks},      # stickies enqueued so far
        "head": {r: 0 for r in all_ranks},     # 0 = empty
        "nxt": {r: 0 for r in all_ranks},
        "budget": {r: BUDGET for r in all_ranks},
        "written": {r: frozenset() for r in all_ranks},
    }

    def mk_enqueue(r):
        def guard(s):
            return s["enq"][r] < STICKIES

        def effect(s):
            step = s["enq"][r] + 1
            s["enq"][r] = step
            if single_slot:
                # BUG: latest wins outright — may displace an unwritten
                # earlier sticky.
                s["head"][r] = step
            elif s["head"][r] == 0:
                # durable.py:654-655 — the oldest unwritten sticky is
                # pinned; its capture is decided at enqueue, which is the
                # rank-uniform point.
                s["head"][r] = step
            else:
                # durable.py:659 — latest-wins only among NEWER stickies.
                s["nxt"][r] = step
        return Action("w%d.enqueue_sticky" % r, guard, effect)

    def mk_write(r):
        def guard(s):
            return s["budget"][r] > 0 and s["head"][r] != 0

        def effect(s):
            step = s["head"][r]
            s["written"][r] = s["written"][r] | {step}
            s["budget"][r] -= 1
            # durable.py:707-710 — dequeue head, promote next.
            s["head"][r] = s["nxt"][r]
            s["nxt"][r] = 0
        return Action("w%d.writer_flush" % r, guard, effect, progress=True)

    def terminal(s):
        return (all(s["enq"][r] == STICKIES for r in all_ranks)
                and all(s["budget"][r] == 0 or s["head"][r] == 0
                        for r in all_ranks))

    def common_written(s):
        sets = [s["written"][r] for r in all_ranks]
        inter = sets[0]
        for w in sets[1:]:
            inter = inter & w
        return inter

    actions = []
    for r in all_ranks:
        actions.append(mk_enqueue(r))
        actions.append(mk_write(r))

    invariants = [
        Invariant(
            "common-written-sticky",
            lambda s: not terminal(s) or bool(common_written(s)),
            "some sticky step is written by EVERY rank once the dust "
            "settles — the manifest anchor; a displaced unwritten head "
            "anti-aligns the ranks",
            "horovod_tpu/elastic/durable.py:544"),
    ]

    name = "drain[sticky]" if not single_slot else "drain[sticky_displacement]"
    return Model(name, init, actions, invariants, terminal,
                 symmetry=[all_ranks], source=__file__)
