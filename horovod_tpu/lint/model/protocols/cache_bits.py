"""Response-cache bit-sync protocol with group FOREIGN placeholders and
the rearm-epoch bootstrap (PR 9/10).

What is modeled
---------------
Rank 0 is the coordinator.  One process group ``g`` spans every rank but
the coordinator; every rank registers the group locally (``new_group`` is
called on all ranks, registration is per-process and unsynchronized).
Two tensors negotiate: ``e1`` (a group tensor of ``g``) and ``e2`` (a
world tensor).  After negotiation, each rank mirrors cache entries in
response-broadcast order and the steady-state bit protocol runs: each
cycle ANDs per-position hit bits across ranks and executes the agreed
positions.  The autotuner's rearm-epoch bootstrap rides the same loop.

Real-code anchors for the invariants and actions:

- late-registration sweep: horovod_tpu/native/controller.cc:451-460
  (pending group tensors re-checked once ``group_table_->Size`` resolves;
  ``ShouldForceFullCycle`` keeps full cycles coming).
- FOREIGN placeholders: horovod_tpu/native/response_cache.h:63 (mirror
  on non-members), :79 (``NonMemberBits`` vacuous-ready), :18-20 (the
  cross-rank AND must span exactly the members).
- rearm-epoch bootstrap: horovod_tpu/native/controller.cc:650-651
  (``RearmPending`` forces ``set_uncached_in_queue(true)`` so the
  (epoch, profile) wire word rides a full-cycle broadcast).

Seeded historical bugs (revert the fix in-model):

- ``late_registration`` — drop the re-check sweep.  Schedule: both
  members register + announce before the coordinator registers; the
  pending entry is only examined on announcement arrival, all
  announcements have already arrived → the op never goes ready →
  **deadlock** (the PR 10 hang).
- ``no_foreign`` — non-members do not mirror group entries.  Their cache
  table is shorter, so bit position 0 decodes to ``e2`` on the
  coordinator but ``e1`` on the members; the AND still agrees (each rank
  has a genuine hit at position 0) and the fast path executes different
  tensors on different ranks → **invariant** ``decode-agreement``.
- ``rearm_no_force`` — rearm does not break the all-cached fast path.
  Once every tensor is cached only fast cycles fire, the epoch word
  never rides a broadcast, and the tuner's re-arm spins forever →
  **livelock** (no-progress cycle of idle fast cycles).
"""

import collections

from ..dsl import Action, Invariant, Model

NAME = "cache_bits"
DESCRIPTION = ("response-cache bit sync: group registration race, FOREIGN "
               "placeholders, rearm-epoch bootstrap")
DEFAULT_RANKS = 3
RANK_RANGE = (2, 4)

from ._bugspec import BugSpec

BUGS = collections.OrderedDict([
    ("late_registration", BugSpec(
        "deadlock",
        "PR 10 hang: member announcements arrive before the coordinator "
        "registers the group and no sweep re-checks pending entries")),
    ("no_foreign", BugSpec(
        "invariant",
        "missing FOREIGN placeholders misalign bit positions; a fast "
        "cycle decodes the same agreed bit to different tensors")),
    ("rearm_no_force", BugSpec(
        "livelock",
        "rearm does not force a full cycle; the epoch word never ships "
        "while the all-cached fast path spins")),
])

E1, E2 = "e1", "e2"


def build(ranks=None, bug=None):
    n = DEFAULT_RANKS if ranks is None else int(ranks)
    if not (RANK_RANGE[0] <= n <= RANK_RANGE[1]):
        raise ValueError("cache_bits supports %d-%d ranks" % RANK_RANGE)
    if bug is not None and bug not in BUGS:
        raise ValueError("unknown bug %r" % (bug,))

    coord = 0
    members = list(range(1, n))          # e1's group: everyone but rank 0
    all_ranks = list(range(n))

    init = {
        "reg": {r: False for r in all_ranks},     # new_group called
        "ann": {r: False for r in members},       # e1 announced
        "arrived": 0,                             # announcements at coord
        "ready": False,                           # e1 fully counted
        "responded": False,                       # e1 response broadcast
        "deliv1": {r: False for r in all_ranks},  # e1 response received
        "deliv2": {r: False for r in all_ranks},  # e2 response received
        "table": {r: () for r in all_ranks},      # cache insertion order
        "want": {r: frozenset() for r in all_ranks},  # queued cached work
        "epoch": {r: 0 for r in all_ranks},       # applied tuning epoch
        "rearm_pending": False,
        "rearm_target": 0,
    }

    def is_member(r):
        return r != coord

    def all_delivered(s):
        return all(s["deliv2"][r] for r in all_ranks)

    # -- phase 1: registration + announcement race -----------------------

    def mk_register(r):
        def effect(s):
            s["reg"][r] = True
            # Registering the group on the coordinator does NOT by itself
            # re-examine pending entries — that is the sweep's job
            # (controller.cc:451-460), which is exactly what the
            # late_registration bug removes.
        return Action("reg%d.new_group" % r,
                      lambda s: not s["reg"][r], effect)

    def mk_announce(r):
        def guard(s):
            return s["reg"][r] and not s["ann"][r]

        def effect(s):
            s["ann"][r] = True
            s["arrived"] += 1
            # IncrementTensorCount at arrival: only resolves the member
            # set if the coordinator's own registry knows the group.
            if s["reg"][coord] and s["arrived"] == len(members):
                s["ready"] = True
        return Action("w%d.announce" % r, guard, effect)

    def sweep_guard(s):
        return (s["reg"][coord] and s["arrived"] == len(members)
                and not s["ready"])

    def sweep_effect(s):
        s["ready"] = True

    def respond_effect(s):
        s["responded"] = True

    def mk_deliver1(r):
        def guard(s):
            return s["responded"] and not s["deliv1"][r]

        def effect(s):
            s["deliv1"][r] = True
            if is_member(r):
                s["table"][r] = s["table"][r] + (E1,)
                # each member wants one cached re-execution of e1
                s["want"][r] = s["want"][r] | {E1}
            elif bug != "no_foreign":
                # response_cache.h:63 — non-members mirror a FOREIGN
                # placeholder so positions stay aligned.
                s["table"][r] = s["table"][r] + (E1,)
        return Action("r%d.deliver_e1" % r, guard, effect, progress=True)

    def mk_deliver2(r):
        def guard(s):
            return s["deliv1"][r] and not s["deliv2"][r]

        def effect(s):
            s["deliv2"][r] = True
            s["table"][r] = s["table"][r] + (E2,)
            s["want"][r] = s["want"][r] | {E2}
        return Action("r%d.deliver_e2" % r, guard, effect, progress=True)

    # -- phase 2: steady-state bit cycles --------------------------------

    def bit(s, r, p):
        """Rank r's reported hit bit for its table position p."""
        entry = s["table"][r][p]
        if is_member_of(entry, r):
            return 1 if entry in s["want"][r] else 0
        # FOREIGN placeholder: vacuously ready (response_cache.h:79).
        return 1

    def is_member_of(entry, r):
        return entry == E2 or (entry == E1 and r != coord)

    def agreed_positions(s):
        width = min(len(s["table"][r]) for r in all_ranks)
        out = []
        for p in range(width):
            if all(bit(s, r, p) for r in all_ranks):
                out.append(p)
        return out

    def fast_guard(s):
        if not all_delivered(s):
            return False
        if not any(s["want"][r] for r in all_ranks):
            return False
        if bug == "rearm_no_force":
            # fast path fires regardless of a pending rearm
            return bool(agreed_positions(s))
        if s["rearm_pending"]:
            # controller.cc:650-651 — a pending rearm forces the full
            # cycle; the fast path is broken until the epoch ships.
            return False
        return bool(agreed_positions(s))

    def fast_effect(s):
        decoded = {}
        for p in agreed_positions(s):
            for r in all_ranks:
                entry = s["table"][r][p]
                decoded.setdefault(r, []).append(entry)
                if is_member_of(entry, r):
                    s["want"][r] = s["want"][r] - {entry}
        s["last_decoded"] = {r: tuple(v) for r, v in decoded.items()}

    def rearm_guard(s):
        return (all_delivered(s) and not s["rearm_pending"]
                and s["rearm_target"] == 0)

    def rearm_effect(s):
        s["rearm_pending"] = True
        s["rearm_target"] = 1

    def full_guard(s):
        if bug == "rearm_no_force":
            return False
        return all_delivered(s) and s["rearm_pending"]

    def full_effect(s):
        # the (epoch, profile) word rides the full-cycle broadcast and is
        # applied in rank-lockstep (controller.cc:650-663)
        for r in all_ranks:
            s["epoch"][r] = s["rearm_target"]
        s["rearm_pending"] = False

    def idle_tick_guard(s):
        # rearm_no_force only: the all-cached steady state keeps ticking
        # fast cycles that carry nothing — the no-progress loop.
        return (bug == "rearm_no_force" and all_delivered(s)
                and s["rearm_pending"]
                and not any(s["want"][r] for r in all_ranks))

    def idle_tick_effect(s):
        pass

    actions = []
    for r in all_ranks:
        actions.append(mk_register(r))
    for r in members:
        actions.append(mk_announce(r))
    if bug != "late_registration":
        actions.append(Action("coord.sweep_pending", sweep_guard,
                              sweep_effect))
    actions.append(Action("coord.respond_e1",
                          lambda s: s["ready"] and not s["responded"],
                          respond_effect, progress=True))
    for r in all_ranks:
        actions.append(mk_deliver1(r))
        actions.append(mk_deliver2(r))
    actions.append(Action("cycle.fast", fast_guard, fast_effect,
                          progress=True))
    actions.append(Action("tuner.rearm", rearm_guard, rearm_effect))
    actions.append(Action("cycle.full_rearm", full_guard, full_effect,
                          progress=True))
    actions.append(Action("cycle.idle_tick", idle_tick_guard,
                          idle_tick_effect))

    invariants = [
        Invariant(
            "no-premature-response",
            lambda s: (not s["ready"]
                       or (s["reg"][coord]
                           and s["arrived"] == len(members))),
            "an op goes ready only after the coordinator's registry "
            "resolves the group and every member announced",
            "horovod_tpu/native/controller.cc:457"),
        Invariant(
            "decode-agreement",
            lambda s: len(set(s.get("last_decoded", {}).values()
                              or [()])) <= 1,
            "every rank must decode an agreed bit position to the same "
            "tensor — FOREIGN placeholders keep the tables aligned",
            "horovod_tpu/native/response_cache.h:18"),
        Invariant(
            "epoch-lockstep",
            lambda s: len(set(s["epoch"].values())) == 1,
            "tuning epochs apply in rank-lockstep via the full-cycle "
            "broadcast",
            "horovod_tpu/native/controller.cc:650"),
    ]

    def done(s):
        return (all_delivered(s)
                and not any(s["want"][r] for r in all_ranks)
                and not s["rearm_pending"])

    return Model(NAME if bug is None else "%s[%s]" % (NAME, bug),
                 init, actions, invariants, done,
                 symmetry=[members], source=__file__)
