"""Shared BugSpec record for the seeded historical-bug variants.

Kept in its own module so protocol modules can import it without going
through ``protocols/__init__`` (which imports them — a cycle otherwise).
``kind`` is the violation class the checker is REQUIRED to re-find when
the bug variant is explored: ``"deadlock"``, ``"invariant"``, or
``"livelock"``.
"""

import collections

BugSpec = collections.namedtuple("BugSpec", ["kind", "description"])
