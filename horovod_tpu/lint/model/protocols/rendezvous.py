"""Elastic rendezvous generation gating (the PR 4 one-survivor-per-
generation split).

What is modeled
---------------
After a shrink, the driver publishes generation ``N`` and the survivors
re-bootstrap — but seconds apart (connection-loss detection and
reconnect windows are not synchronized across ranks).  Each survivor
fetches its assignment, which names the CURRENT published generation,
and then waits in that generation's rendezvous.  Meanwhile a blacklist
cooldown can expire inside that gap: a respawned host asks for a grow
generation ``N+1``.

The fix under test: the driver's growth gate ``_generation_ready`` —
a grow generation is only published once the current generation's
rendezvous has resolved (or provably stalled, which bumps it anyway; the
stall path is outside this bounded model).  Without the gate, one
survivor fetches ``N`` and the other ``N+1``; each waits in a rendezvous
the other will never join, and both time out.

Real-code anchors:

- horovod_tpu/elastic/driver.py:213-227 — ``_generation_ready`` and the
  comment narrating exactly this failure.
- horovod_tpu/elastic/driver.py:616-618 — growth planned only when ready.
- horovod_tpu/elastic/run.py:204 — ``fetch_assignment`` (the fetch that
  binds a survivor to whatever generation is published at that instant).

Seeded bug ``ungated_growth`` — remove the gate: the respawn may bump
the published generation between the two survivors' fetches.  The
``no-generation-split`` invariant (both survivors, once waiting, wait in
the SAME generation) fires with a minimal trace; the same schedule also
deadlocks (neither rendezvous can ever resolve).
"""

import collections

from ..dsl import Action, Invariant, Model
from ._bugspec import BugSpec

NAME = "rendezvous"
DESCRIPTION = ("post-shrink re-bootstrap vs. grow-generation publish: "
               "the _generation_ready gate")
DEFAULT_RANKS = 2          # survivors of the shrink
RANK_RANGE = (2, 3)

BUGS = collections.OrderedDict([
    ("ungated_growth", BugSpec(
        "invariant",
        "grow generation published between the survivors' bootstraps: "
        "one waits in gen N, the other in gen N+1, both time out")),
])

WAITING = None


def build(ranks=None, bug=None):
    n = DEFAULT_RANKS if ranks is None else int(ranks)
    if not (RANK_RANGE[0] <= n <= RANK_RANGE[1]):
        raise ValueError("rendezvous supports %d-%d survivors" % RANK_RANGE)
    if bug is not None and bug not in BUGS:
        raise ValueError("unknown bug %r" % (bug,))
    survivors = list(range(n))

    init = {
        "pub_gen": 0,                 # generation currently published
        "fetched": {r: -1 for r in survivors},   # -1 = not yet fetched
        "arrived": {r: -1 for r in survivors},   # generation waited in
        "resolved": {0: False, 1: False},
        "respawn_pending": True,      # blacklist cooldown may expire
        "new_arrived": False,         # the respawned worker, gen 1 only
    }

    def mk_fetch(r):
        # run.py:204 fetch_assignment: binds to the instant's pub_gen.
        def guard(s):
            return s["fetched"][r] == -1

        def effect(s):
            s["fetched"][r] = s["pub_gen"]
        return Action("s%d.fetch_assignment" % r, guard, effect)

    def mk_arrive(r):
        def guard(s):
            return s["fetched"][r] != -1 and s["arrived"][r] == -1

        def effect(s):
            s["arrived"][r] = s["fetched"][r]
        return Action("s%d.join_rendezvous" % r, guard, effect)

    def grow_guard(s):
        if not s["respawn_pending"]:
            return False
        if bug == "ungated_growth":
            return True
        # driver.py:213-227 — growth gated on the current generation's
        # rendezvous having resolved.
        return s["resolved"][s["pub_gen"]]

    def grow_effect(s):
        s["respawn_pending"] = False
        s["pub_gen"] = 1

    def resolve0_guard(s):
        return (not s["resolved"][0]
                and all(s["arrived"][r] == 0 for r in survivors))

    def resolve1_guard(s):
        return (not s["resolved"][1] and s["new_arrived"]
                and all(s["arrived"][r] == 1 for r in survivors))

    def mk_resolve(g, guard):
        def effect(s):
            s["resolved"][g] = True
        return Action("rendezvous.resolve_gen%d" % g, guard, effect,
                      progress=True)

    def new_arrive_effect(s):
        s["new_arrived"] = True

    actions = [mk_fetch(r) for r in survivors]
    actions += [mk_arrive(r) for r in survivors]
    actions.append(Action("driver.publish_grow_gen", grow_guard,
                          grow_effect))
    actions.append(Action("respawn.join_rendezvous",
                          lambda s: s["pub_gen"] == 1
                          and not s["new_arrived"],
                          new_arrive_effect))
    actions.append(mk_resolve(0, resolve0_guard))
    actions.append(mk_resolve(1, resolve1_guard))

    invariants = [
        Invariant(
            "no-generation-split",
            lambda s: len({g for g in
                           (s["arrived"][r] for r in survivors)
                           if g != -1}) <= 1,
            "once waiting, all shrink survivors wait in the SAME "
            "generation's rendezvous — a split strands both sides until "
            "timeout",
            "horovod_tpu/elastic/driver.py:213"),
    ]

    def done(s):
        # training resumes once some generation's rendezvous resolved
        # with every survivor in it
        return s["resolved"][0] or s["resolved"][1]

    return Model(NAME if bug is None else "%s[%s]" % (NAME, bug),
                 init, actions, invariants, done,
                 symmetry=[survivors], source=__file__)
