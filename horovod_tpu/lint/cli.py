"""The ``hvd-lint`` command line.

Exit codes:
  0 — no findings at or above the --fail-on severity (clean);
  1 — findings at or above the --fail-on severity;
  2 — usage error (no such path, unknown rule).

``--format json`` emits a stable machine-readable report for CI; the
human format is ``path:line:col: severity [rule] message``.
"""

import argparse
import os
import sys

from . import RULES, lint_paths
from .report import (format_human, format_json, format_sarif,
                     summarize_human)
from .rules import severity_at_least


def make_parser():
    parser = argparse.ArgumentParser(
        prog="hvd-lint",
        description="Static collective-consistency analysis for "
                    "horovod_tpu training scripts (see docs/LINT.md).")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to lint")
    parser.add_argument("--format", choices=("human", "json", "sarif"),
                        default="human")
    parser.add_argument("--fail-on", choices=("warning", "error"),
                        default="warning",
                        help="lowest severity that causes exit code 1 "
                             "(default: warning — any finding fails)")
    parser.add_argument("--disable", default="",
                        help="comma-separated rule ids to skip globally")
    parser.add_argument("--verify", action="store_true",
                        help="additionally run the hvd-verify symbolic "
                             "collective-schedule verifier: each .py "
                             "file is executed for an abstract N-rank "
                             "world (local imports followed, helpers "
                             "inlined) and the per-rank collective "
                             "schedules are diffed (docs/LINT.md)")
    parser.add_argument("--verify-world", type=int, default=None,
                        metavar="N",
                        help="symbolic world size for --verify "
                             "(default: 4)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule registry and exit")
    return parser


def main(argv=None):
    parser = make_parser()
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in RULES.values():
            sys.stdout.write("%-28s %-8s %s\n" %
                             (rule.id, rule.default_severity, rule.summary))
        return 0

    if not args.paths:
        parser.error("no paths given (or use --list-rules)")

    disabled = {r.strip() for r in args.disable.split(",") if r.strip()}
    unknown = disabled - set(RULES)
    if unknown:
        parser.error("unknown rule id(s): %s" % ", ".join(sorted(unknown)))
    for path in args.paths:
        if not os.path.exists(path):
            parser.error("no such file or directory: %s" % path)

    enabled = set(RULES) - disabled
    findings, files_checked = lint_paths(args.paths, rules=enabled)

    if args.verify:
        from .schedule import DEFAULT_WORLD, verify_paths
        world = args.verify_world or DEFAULT_WORLD
        vfindings, _ = verify_paths(args.paths, world=world,
                                    rules=enabled)
        findings.extend(vfindings)
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    if args.format == "json":
        format_json(findings, files_checked, sys.stdout)
    elif args.format == "sarif":
        format_sarif(findings, files_checked, sys.stdout)
    else:
        format_human(findings, sys.stdout)
        summarize_human(findings, files_checked, sys.stderr)

    failing = [f for f in findings
               if severity_at_least(f.severity, args.fail_on)]
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main())
