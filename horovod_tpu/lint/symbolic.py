"""Abstract interpreter for hvd-verify: one symbolic rank's execution.

The Horovod coordinator's core correctness condition (arxiv 1802.05799)
is that every rank submits the SAME ordered sequence of collectives.
This module runs the user's program once per *symbolic rank* of an
abstract W-rank world — ``hvd.rank()`` evaluates to that rank's
concrete index, ``hvd.size()`` to W — and records the ordered
*collective schedule* the rank would submit: ``(kind, name, group,
compression, sharded)`` events, each with the full interprocedural call
chain that reached it. schedule.py then diffs the schedules across
ranks; any disagreement is a statically-proven divergence.

Abstraction choices (the "what it can/cannot prove" contract,
docs/LINT.md):

* values are CONST (a concrete Python value), structured handles
  (GROUP / OPT / STATE / CKPT / FUNC / MODULE), or UNKNOWN with a
  rank-dependence taint;
* conditions: decidable ones branch concretely per rank; uniform
  unknowns execute BOTH branches in order (every rank does the same,
  so no false divergence and no missed uniform collectives);
  rank-dependent unknowns split the world deterministically (low half
  true) — a divergence is then reported only if the branches actually
  disagree about collectives, which is strictly more precise than the
  lexical rank-conditional rule;
* loops unroll concretely up to MAX_UNROLL iterations, else run once
  with the target unknown; user functions (local imports included)
  inline to MAX_DEPTH with recursion cut off; everything is capped by
  a step budget so the verifier always terminates.

Exceptional control flow (``raise``, ``except`` bodies) is out of
scope: ``try`` bodies and ``finally`` run, handlers do not.
"""

import ast
import os

from .walker import (COLLECTIVES, INITIAL_BROADCASTS, _call_base_attr,
                     _is_hvd_base, collective_call_name)

MAX_DEPTH = 10        # user-function inline depth
MAX_UNROLL = 8        # concrete loop iterations explored
MAX_STEPS = 60000     # AST-node evaluation budget per symbolic rank
MAX_EVENTS = 2048     # schedule length cap per symbolic rank

# hvd informational calls the executor evaluates concretely for the
# symbolic world (single symbolic host: local == world, cross == 1).
_INFO_FUNCS = {"rank", "local_rank", "cross_rank", "size", "local_size",
               "cross_size", "is_initialized", "is_homogeneous"}

# Optimizer-ish methods that stand for "run the wrapped gradient
# allreduce now" on a DistributedOptimizer / DistributedGradientTape.
_OPT_STEP_METHODS = {"update", "apply_gradients", "step", "minimize",
                     "compute_gradients", "gradient"}


class SymVal(object):
    """One abstract value. kind in {"const", "group", "opt", "state",
    "ckpt", "func", "module", "unknown"}; `rank_dep` marks values
    derived from per-rank sources (meaningful for "unknown")."""

    __slots__ = ("kind", "value", "rank_dep")

    def __init__(self, kind, value=None, rank_dep=False):
        self.kind = kind
        self.value = value
        self.rank_dep = rank_dep

    def __repr__(self):  # pragma: no cover - debug aid
        return "SymVal(%s, %r%s)" % (
            self.kind, self.value, ", rank" if self.rank_dep else "")


def const(v, rank_dep=False):
    return SymVal("const", v, rank_dep)


def unknown(rank_dep=False):
    return SymVal("unknown", None, rank_dep)


class GroupVal(object):
    """A hvd.new_group() handle: `ranks` is the concrete member tuple
    when the registration's rank list evaluated concretely, else None
    (membership unknown — every check is vacuous). `label` names
    implicit groups (model_group/batch_group) whose membership the
    verifier cannot know but whose identity it can still compare."""

    __slots__ = ("gid", "ranks", "label", "chain")

    def __init__(self, gid, ranks, label, chain):
        self.gid = gid
        self.ranks = tuple(ranks) if ranks is not None else None
        self.label = label
        self.chain = chain  # call chain of the new_group() registration

    def key(self):
        """Identity for schedule comparison. The gid is part of it:
        two registrations with IDENTICAL member lists are still two
        distinct groups at runtime (ids come from the per-process
        counter), so a collective issued under gA by some ranks and gB
        by others is a mixed-group divergence, not a match. Counters
        align across symbolic ranks whenever the registration sequence
        is uniform — and a non-uniform sequence is itself reported via
        the new_group schedule events."""
        if self.ranks is not None:
            return ("g", self.gid, self.ranks)
        return ("g?", self.gid, self.label)

    def describe(self):
        if self.ranks is not None:
            return "group#%d[%s]" % (
                self.gid, ",".join(str(r) for r in self.ranks))
        return self.label


class OptVal(object):
    """DistributedOptimizer / DistributedGradientTape handle carrying
    the negotiation-relevant modes its gradient allreduce will use."""

    __slots__ = ("sharded", "compression", "group", "chain", "prefix")

    def __init__(self, sharded, compression, group, chain,
                 prefix=None):
        self.sharded = sharded          # True | False | None (unknown)
        self.compression = compression  # str | None | "<?>"
        self.group = group              # GroupVal | None
        self.chain = chain
        self.prefix = prefix            # explicit name_prefix= or None

    def grads_name(self):
        """Symbolic name for this optimizer's gradient negotiation.
        Two optimizers with DISTINCT explicit name_prefix= values
        negotiate disjoint tensor names at runtime, so they must not
        collide in the per-name analyses; default-prefix optimizers
        genuinely alias (both negotiate grad.<i>) and share the
        placeholder."""
        if self.prefix:
            return "<grads:%s>" % self.prefix
        return "<grads>"


class Event(object):
    """One schedule entry."""

    __slots__ = ("kind", "name", "group", "compression", "sharded",
                 "collective", "chain", "path", "line")

    def __init__(self, kind, name, group=None, compression=None,
                 sharded=False, collective=True, chain=(), path="",
                 line=0):
        self.kind = kind
        self.name = name
        self.group = group              # GroupVal | None
        self.compression = compression
        self.sharded = sharded
        self.collective = collective    # False: rank-local (restore)
        self.chain = chain              # tuple of (path, line, func)
        self.path = path
        self.line = line

    def group_key(self):
        return None if self.group is None else self.group.key()

    def identity(self):
        """What two ranks must agree on for this schedule slot."""
        return (self.kind, self.name, self.group_key())

    def describe(self):
        bits = [self.kind, "'%s'" % self.name]
        if self.group is not None:
            bits.append("in " + self.group.describe())
        if self.compression not in (None, "none"):
            bits.append("compression=%s" % self.compression)
        if self.sharded:
            bits.append("sharded")
        return " ".join(bits)


class ExecFinding(object):
    """A hazard proven during execution itself (not by diffing)."""

    __slots__ = ("rule", "message", "path", "line", "end_line")

    def __init__(self, rule, message, path, line, end_line=None):
        self.rule = rule
        self.message = message
        self.path = path
        self.line = line
        self.end_line = end_line or line


def format_chain(chain):
    return " -> ".join("%s:%d in %s" % (os.path.basename(p), ln, fn)
                       for p, ln, fn in chain)


class _Return(Exception):
    def __init__(self, value):
        self.value = value


class _Raise(Exception):
    """A `raise` statement: ends the enclosing function (or module)
    unless an enclosing `try` absorbs it — the closest sound-enough
    approximation while handler bodies stay out of scope."""


class _Break(Exception):
    pass


class _Continue(Exception):
    pass


class _Budget(Exception):
    """Step/event budget exhausted — stop quietly, keep what we have."""


class Executor(object):
    """Executes the program for ONE symbolic rank."""

    def __init__(self, graph, rank, world):
        self.graph = graph
        self.rank = rank
        self.world = world
        self.events = []
        self.findings = []
        self.steps = 0
        self.depth = 0
        self.stack = ()          # call chain: tuple of (path, line, func)
        self.inlining = ()       # (path, funcname) pairs, recursion cut
        self.group_counter = 0
        self.auto_counter = 0
        self.truncated = False
        self._module_envs = {}   # realpath -> env dict (top-level run once)

    # -- entry ------------------------------------------------------------

    def run(self):
        entry = self.graph.entry
        env = self._fresh_module_env(entry)
        self._module_envs[os.path.realpath(entry.path)] = env
        try:
            self._exec_body(entry.tree.body, env, entry, "<module>")
        except _Budget:
            self.truncated = True
        except (_Return, _Raise, _Break, _Continue):
            pass  # stray signals at top level
        return self.events, self.findings

    def _fresh_module_env(self, module):
        return {"__name__": const(module.run_name),
                "__file__": const(module.path)}

    def _module_env(self, module):
        """Top-level of a local import runs once per symbolic rank; the
        resulting globals are shared by later imports (Python
        semantics) and by calls into its functions."""
        real = os.path.realpath(module.path)
        env = self._module_envs.get(real)
        if env is None:
            env = self._fresh_module_env(module)
            self._module_envs[real] = env  # pre-bind: import cycles stop
            try:
                self._exec_body(module.tree.body, env, module, "<module>")
            except (_Return, _Raise, _Break, _Continue):
                pass
        return env

    def _tick(self):
        self.steps += 1
        if self.steps > MAX_STEPS or len(self.events) > MAX_EVENTS:
            raise _Budget()

    # -- statements -------------------------------------------------------

    def _exec_body(self, body, env, module, funcname):
        for stmt in body:
            self._exec_stmt(stmt, env, module, funcname)

    def _exec_stmt(self, node, env, module, funcname):
        self._tick()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            from .callgraph import FunctionInfo
            env[node.name] = SymVal(
                "func", FunctionInfo(node.name, node, module))
        elif isinstance(node, ast.ClassDef):
            env[node.name] = unknown()
        elif isinstance(node, ast.Import):
            self._exec_import(node, env, module, funcname)
        elif isinstance(node, ast.ImportFrom):
            self._exec_import_from(node, env, module, funcname)
        elif isinstance(node, ast.Assign):
            # Literal tuple unpack binds element-wise in one pass:
            # `r, n = hvd.rank(), hvd.size()` must taint r but NOT n
            # (a folded const tuple only knows a combined taint), and
            # the elements must be evaluated exactly once (they may
            # emit events).
            if len(node.targets) == 1 and \
                    isinstance(node.targets[0], (ast.Tuple, ast.List)) \
                    and isinstance(node.value, (ast.Tuple, ast.List)) \
                    and len(node.targets[0].elts) == \
                    len(node.value.elts):
                for tgt, val_node in zip(node.targets[0].elts,
                                         node.value.elts):
                    self._bind(tgt,
                               self._eval(val_node, env, module,
                                          funcname),
                               None, env, module, funcname)
            else:
                value = self._eval(node.value, env, module, funcname)
                for target in node.targets:
                    self._bind(target, value, node.value, env, module,
                               funcname)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:
                value = self._eval(node.value, env, module, funcname)
                self._bind(node.target, value, node.value, env, module,
                           funcname)
        elif isinstance(node, ast.AugAssign):
            value = self._eval(node.value, env, module, funcname)
            if isinstance(node.target, ast.Name):
                old = env.get(node.target.id, unknown())
                env[node.target.id] = self._binop_val(old, node.op, value)
        elif isinstance(node, ast.Expr):
            self._eval(node.value, env, module, funcname)
        elif isinstance(node, ast.If):
            self._exec_if(node, env, module, funcname)
        elif isinstance(node, ast.While):
            self._exec_while(node, env, module, funcname)
        elif isinstance(node, ast.For):
            self._exec_for(node, env, module, funcname)
        elif isinstance(node, ast.With):
            for item in node.items:
                val = self._eval(item.context_expr, env, module, funcname)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, val, item.context_expr,
                               env, module, funcname)
            self._exec_body(node.body, env, module, funcname)
        elif isinstance(node, ast.Try):
            try:
                self._exec_body(node.body, env, module, funcname)
                # `else:` runs on the normal path — the path the
                # executor models
                self._exec_body(node.orelse, env, module, funcname)
            except _Raise:
                pass  # assume some handler catches; handlers not run
            finally:
                self._exec_body(node.finalbody, env, module, funcname)
        elif isinstance(node, ast.Return):
            value = const(None)
            if node.value is not None:
                value = self._eval(node.value, env, module, funcname)
            raise _Return(value)
        elif isinstance(node, ast.Raise):
            if node.exc is not None:
                self._eval(node.exc, env, module, funcname)
            raise _Raise()
        elif isinstance(node, ast.Break):
            raise _Break()
        elif isinstance(node, ast.Continue):
            raise _Continue()
        elif isinstance(node, ast.Assert):
            self._eval(node.test, env, module, funcname)
        elif isinstance(node, (ast.Delete, ast.Global, ast.Nonlocal,
                               ast.Pass)):
            pass
        # anything else (Match, etc.): evaluated conservatively as no-op

    def _imported_env(self, local, node, module, funcname):
        """Runs (once) and returns a local import's module env with the
        IMPORT SITE on the chain, so collectives at the imported
        module's top level anchor at the entry file's import line
        (where a suppression can actually reach them)."""
        old = self.stack
        self.stack = self.stack + (self._site(node, module, funcname),)
        try:
            return self._module_env(local)
        finally:
            self.stack = old

    def _exec_import(self, node, env, module, funcname):
        for alias in node.names:
            local = self.graph.load_local(module.directory, alias.name)
            bound = alias.asname or alias.name.split(".")[0]
            if local is not None:
                # run its top level (events!)
                self._imported_env(local, node, module, funcname)
                env[bound] = SymVal("module", local)
            # hvd/3rd-party imports: the walker model already indexed
            # the aliases; names stay unbound (syntactic resolution).

    def _exec_import_from(self, node, env, module, funcname):
        if node.module is None or node.level:
            # relative import: resolve against this module's directory
            modname = node.module or ""
            local = self.graph.load_local(module.directory, modname) \
                if modname else None
        else:
            local = self.graph.load_local(module.directory, node.module)
        if local is None:
            return
        menv = self._imported_env(local, node, module, funcname)
        for alias in node.names:
            if alias.name == "*":
                for k, v in menv.items():
                    if not k.startswith("__"):
                        env[k] = v
                continue
            bound = alias.asname or alias.name
            if alias.name in menv:
                env[bound] = menv[alias.name]
            else:
                sub = self.graph.load_local(
                    os.path.join(local.directory), alias.name)
                if sub is not None:
                    self._imported_env(sub, node, module, funcname)
                    env[bound] = SymVal("module", sub)
                else:
                    env[bound] = unknown()

    def _bind(self, target, value, value_node, env, module, funcname):
        if isinstance(target, ast.Name):
            env[target.id] = value
        elif isinstance(target, (ast.Tuple, ast.List)):
            # (literal-tuple unpacks are handled element-wise by the
            # Assign statement itself; this path sees computed values)
            elts = None
            if value.kind == "const" and \
                    isinstance(value.value, (tuple, list)) and \
                    len(value.value) == len(target.elts):
                elts = [const(v, value.rank_dep) for v in value.value]
            for i, sub in enumerate(target.elts):
                self._bind(sub, elts[i] if elts is not None
                           else unknown(value.rank_dep),
                           None, env, module, funcname)
        # attribute/subscript targets: state mutation we do not model

    # -- control flow -----------------------------------------------------

    def _truth(self, val):
        """True/False when decidable, else None."""
        if val.kind == "const":
            try:
                return bool(val.value)
            except Exception:
                return None
        if val.kind in ("group", "opt", "optunion", "state", "ckpt",
                        "func", "module"):
            return True
        return None

    def _exec_if(self, node, env, module, funcname):
        test = self._eval(node.test, env, module, funcname)
        decision = self._truth(test)
        if decision is True:
            self._exec_body(node.body, env, module, funcname)
        elif decision is False:
            self._exec_body(node.orelse, env, module, funcname)
        elif test.rank_dep:
            # Undecidable but rank-derived: split the symbolic world
            # deterministically. If the two halves' schedules agree the
            # branch was harmless; if not, the diff names it.
            if self.rank < (self.world + 1) // 2:
                self._exec_body(node.body, env, module, funcname)
            else:
                self._exec_body(node.orelse, env, module, funcname)
        else:
            # Uniform unknown: every rank makes the SAME choice at run
            # time, whichever it is. Executing both arms in order keeps
            # the schedules rank-identical while still surfacing each
            # arm's collectives for the per-name mode/kind analyses.
            # Each arm runs on its own env copy and the results merge,
            # so `opt = DistributedOptimizer(..., sharded_update=True)`
            # in one arm vs a replicated one in the other survives as
            # an either-of value the later opt.step() can expand.
            env_a = dict(env)
            env_b = dict(env)
            self._exec_body(node.body, env_a, module, funcname)
            self._exec_body(node.orelse, env_b, module, funcname)
            self._merge_envs(env, env_a, env_b)

    @staticmethod
    def _vals_equal(a, b):
        if a is b:
            return True
        if a.kind != b.kind:
            return False
        if a.kind == "const":
            try:
                return a.value == b.value and a.rank_dep == b.rank_dep
            except Exception:
                return False
        if a.kind == "unknown":
            return a.rank_dep == b.rank_dep
        return a.value is b.value

    def _merge_envs(self, env, env_a, env_b):
        for key in set(env_a) | set(env_b):
            va, vb = env_a.get(key), env_b.get(key)
            if va is None or vb is None:
                env[key] = va or vb
            elif self._vals_equal(va, vb):
                env[key] = va
            elif va.kind == "opt" and vb.kind == "opt":
                env[key] = SymVal("optunion", (va.value, vb.value))
            else:
                env[key] = unknown(va.rank_dep or vb.rank_dep)

    def _exec_while(self, node, env, module, funcname):
        test = self._eval(node.test, env, module, funcname)
        if self._truth(test) is False:
            self._exec_body(node.orelse, env, module, funcname)
            return
        try:
            self._exec_body(node.body, env, module, funcname)  # one pass
        except _Break:
            return
        except _Continue:
            pass
        self._exec_body(node.orelse, env, module, funcname)

    def _iter_items(self, val):
        """Concrete iteration values, or None when unknown."""
        if val.kind != "const":
            return None
        v = val.value
        if isinstance(v, (list, tuple)):
            return list(v)
        if isinstance(v, range):
            return list(v)
        if isinstance(v, dict):
            return list(v.keys())
        if isinstance(v, str):
            return list(v)
        return None

    def _exec_for(self, node, env, module, funcname):
        it = self._eval(node.iter, env, module, funcname)
        items = self._iter_items(it)
        broke = False
        if items is None:
            self._bind(node.target, unknown(it.rank_dep), None, env,
                       module, funcname)
            try:
                self._exec_body(node.body, env, module, funcname)
            except _Break:
                broke = True
            except _Continue:
                pass
        else:
            for item in items[:MAX_UNROLL]:
                self._bind(node.target,
                           item if isinstance(item, SymVal)
                           else const(item, it.rank_dep),
                           None, env, module, funcname)
                try:
                    self._exec_body(node.body, env, module, funcname)
                except _Break:
                    broke = True
                    break
                except _Continue:
                    continue
        if not broke:
            self._exec_body(node.orelse, env, module, funcname)

    # -- expressions ------------------------------------------------------

    def _eval(self, node, env, module, funcname):
        self._tick()
        if node is None:
            return const(None)
        if isinstance(node, ast.Constant):
            return const(node.value)
        if isinstance(node, ast.Name):
            if node.id in env:
                return env[node.id]
            menv = self._module_envs.get(os.path.realpath(module.path))
            if menv is not None and menv is not env and node.id in menv:
                return menv[node.id]
            if node.id in module.functions:
                return SymVal("func", module.functions[node.id])
            return unknown()
        if isinstance(node, (ast.Tuple, ast.List)):
            vals = [self._eval(e, env, module, funcname)
                    for e in node.elts]
            if all(v.kind == "const" for v in vals):
                seq = [v.value for v in vals]
                return const(tuple(seq) if isinstance(node, ast.Tuple)
                             else seq,
                             any(v.rank_dep for v in vals))
            return unknown(any(v.rank_dep for v in vals))
        if isinstance(node, ast.Set):
            for e in node.elts:
                self._eval(e, env, module, funcname)
            return unknown()
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if k is not None:
                    self._eval(k, env, module, funcname)
            for v in node.values:
                self._eval(v, env, module, funcname)
            return unknown()
        if isinstance(node, ast.JoinedStr):
            return self._eval_fstring(node, env, module, funcname)
        if isinstance(node, ast.FormattedValue):
            return self._eval(node.value, env, module, funcname)
        if isinstance(node, ast.BinOp):
            left = self._eval(node.left, env, module, funcname)
            right = self._eval(node.right, env, module, funcname)
            return self._binop_val(left, node.op, right)
        if isinstance(node, ast.UnaryOp):
            val = self._eval(node.operand, env, module, funcname)
            if val.kind == "const":
                try:
                    if isinstance(node.op, ast.Not):
                        return const(not val.value, val.rank_dep)
                    if isinstance(node.op, ast.USub):
                        return const(-val.value, val.rank_dep)
                    if isinstance(node.op, ast.UAdd):
                        return const(+val.value, val.rank_dep)
                except Exception:
                    pass
            return unknown(val.rank_dep)
        if isinstance(node, ast.BoolOp):
            return self._eval_boolop(node, env, module, funcname)
        if isinstance(node, ast.Compare):
            return self._eval_compare(node, env, module, funcname)
        if isinstance(node, ast.Call):
            return self._eval_call(node, env, module, funcname)
        if isinstance(node, ast.Attribute):
            return self._eval_attribute(node, env, module, funcname)
        if isinstance(node, ast.Subscript):
            return self._eval_subscript(node, env, module, funcname)
        if isinstance(node, ast.IfExp):
            test = self._eval(node.test, env, module, funcname)
            decision = self._truth(test)
            if decision is True:
                return self._eval(node.body, env, module, funcname)
            if decision is False:
                return self._eval(node.orelse, env, module, funcname)
            if test.rank_dep:
                branch = node.body if self.rank < (self.world + 1) // 2 \
                    else node.orelse
                val = self._eval(branch, env, module, funcname)
                return SymVal(val.kind, val.value, True) \
                    if val.kind == "const" else unknown(True)
            a = self._eval(node.body, env, module, funcname)
            b = self._eval(node.orelse, env, module, funcname)
            if a.kind == "const" and b.kind == "const" and \
                    a.value == b.value:
                return a
            return unknown(a.rank_dep or b.rank_dep)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self._eval_comp(node, env, module, funcname)
        if isinstance(node, ast.DictComp):
            return unknown()
        if isinstance(node, ast.Lambda):
            return unknown()
        if isinstance(node, ast.Starred):
            return self._eval(node.value, env, module, funcname)
        if isinstance(node, ast.Slice):
            return unknown()
        if isinstance(node, ast.Await):
            return self._eval(node.value, env, module, funcname)
        return unknown()

    def _eval_fstring(self, node, env, module, funcname):
        parts = []
        rank_dep = False
        for piece in node.values:
            if isinstance(piece, ast.Constant):
                parts.append(str(piece.value))
                continue
            val = self._eval(piece.value, env, module, funcname)
            rank_dep = rank_dep or val.rank_dep
            if val.kind == "const":
                parts.append(str(val.value))
            elif val.rank_dep:
                # A rank-tainted unknown in a collective name: make the
                # symbolic names differ across ranks so the schedule
                # diff exposes it (mirrors the lexical
                # rank-dependent-name rule interprocedurally).
                parts.append("<?r%d>" % self.rank)
            else:
                parts.append("<?>")
        return const("".join(parts), rank_dep)

    def _binop_val(self, left, op, right):
        rank_dep = left.rank_dep or right.rank_dep
        if left.kind == "const" and right.kind == "const":
            try:
                lv, rv = left.value, right.value
                if isinstance(op, ast.Add):
                    return const(lv + rv, rank_dep)
                if isinstance(op, ast.Sub):
                    return const(lv - rv, rank_dep)
                if isinstance(op, ast.Mult):
                    return const(lv * rv, rank_dep)
                if isinstance(op, ast.Div):
                    return const(lv / rv, rank_dep)
                if isinstance(op, ast.FloorDiv):
                    return const(lv // rv, rank_dep)
                if isinstance(op, ast.Mod):
                    return const(lv % rv, rank_dep)
                if isinstance(op, ast.Pow):
                    return const(lv ** rv, rank_dep)
                if isinstance(op, ast.BitAnd):
                    return const(lv & rv, rank_dep)
                if isinstance(op, ast.BitOr):
                    return const(lv | rv, rank_dep)
            except Exception:
                return unknown(rank_dep)
        # "prefix.%s" % unknown-rank-dep: keep the divergence visible.
        if isinstance(op, ast.Mod) and left.kind == "const" and \
                isinstance(left.value, str):
            filler = "<?r%d>" % self.rank if right.rank_dep else "<?>"
            try:
                n = left.value.count("%") - 2 * left.value.count("%%")
                return const(left.value.replace("%%", "%")
                             .replace("%d", filler).replace("%s", filler)
                             .replace("%i", filler) if n else left.value,
                             rank_dep)
            except Exception:
                return unknown(rank_dep)
        return unknown(rank_dep)

    def _eval_boolop(self, node, env, module, funcname):
        """Python semantics: `or`/`and` return an OPERAND, not a bool
        — `args.name or "grad.w"` must evaluate to the operand value
        (collective names routinely use the idiom). Left-to-right:
        the first operand with an undecidable truth makes the result
        unknown; a deciding operand's VALUE is returned only when
        every operand before it decided the other way."""
        # Lazy, left-to-right: once an operand DECIDES the result, the
        # remaining operands are not evaluated at all — at runtime they
        # never run, so any collectives inside them must not leak into
        # this rank's schedule (`rank() != 0 and hvd.allreduce(...)`
        # short-circuits on rank 0). Undecidable operands keep the scan
        # going (their successors may or may not run; evaluating them
        # is the same every-rank-does-the-same convention as
        # uniform-unknown branches).
        want_continue = isinstance(node.op, ast.And)  # And: skip Trues
        rank_dep = False
        for i, sub in enumerate(node.values):
            val = self._eval(sub, env, module, funcname)
            rank_dep = rank_dep or val.rank_dep
            if i == len(node.values) - 1:
                break
            t = self._truth(val)
            if t is None:
                continue
            if t is not want_continue:
                # short-circuit: `and` stops at the first False,
                # `or` at the first True — returning that operand
                return SymVal(val.kind, val.value, rank_dep) \
                    if val.kind == "const" else val
        if val.kind == "const":
            return SymVal("const", val.value, rank_dep)
        if val.kind == "unknown":
            # `rank-ish and unknown` is still rank-derived: the taint
            # of every operand reaches the result
            return unknown(rank_dep)
        return val

    def _eval_compare(self, node, env, module, funcname):
        left = self._eval(node.left, env, module, funcname)
        rank_dep = left.rank_dep
        result = True
        known = left.kind == "const"
        prev = left
        for op, comp in zip(node.ops, node.comparators):
            cur = self._eval(comp, env, module, funcname)
            rank_dep = rank_dep or cur.rank_dep
            if not (known and cur.kind == "const"):
                known = False
                prev = cur
                continue
            try:
                lv, rv = prev.value, cur.value
                if isinstance(op, ast.Eq):
                    ok = lv == rv
                elif isinstance(op, ast.NotEq):
                    ok = lv != rv
                elif isinstance(op, ast.Lt):
                    ok = lv < rv
                elif isinstance(op, ast.LtE):
                    ok = lv <= rv
                elif isinstance(op, ast.Gt):
                    ok = lv > rv
                elif isinstance(op, ast.GtE):
                    ok = lv >= rv
                elif isinstance(op, ast.In):
                    ok = lv in rv
                elif isinstance(op, ast.NotIn):
                    ok = lv not in rv
                elif isinstance(op, ast.Is):
                    ok = lv is rv or lv == rv
                elif isinstance(op, ast.IsNot):
                    ok = not (lv is rv or lv == rv)
                else:
                    known = False
                    prev = cur
                    continue
                result = result and ok
            except Exception:
                known = False
            prev = cur
        if known:
            return const(result, rank_dep)
        return unknown(rank_dep)

    def _eval_subscript(self, node, env, module, funcname):
        base = self._eval(node.value, env, module, funcname)
        if isinstance(node.slice, ast.Slice):
            for part in (node.slice.lower, node.slice.upper,
                         node.slice.step):
                if part is not None:
                    self._eval(part, env, module, funcname)
            return unknown(base.rank_dep)
        idx = self._eval(node.slice, env, module, funcname)
        rank_dep = base.rank_dep or idx.rank_dep
        if base.kind == "const" and idx.kind == "const":
            try:
                return const(base.value[idx.value], rank_dep)
            except Exception:
                return unknown(rank_dep)
        return unknown(rank_dep)

    def _eval_attribute(self, node, env, module, funcname):
        base = self._eval(node.value, env, module, funcname)
        if base.kind == "module":
            minfo = base.value
            menv = self._module_env(minfo)
            if node.attr in menv:
                return menv[node.attr]
            if node.attr in minfo.functions:
                return SymVal("func", minfo.functions[node.attr])
            return unknown()
        if base.kind == "group":
            if node.attr == "ranks":
                return const(base.value.ranks) \
                    if base.value.ranks is not None else unknown()
            if node.attr == "id":
                return const(base.value.gid)
        return unknown(base.rank_dep)

    def _eval_comp(self, node, env, module, funcname):
        """Single-generator comprehensions over concrete iterables
        evaluate concretely (new_group/name lists); the rest degrade."""
        if len(node.generators) != 1 or node.generators[0].ifs or \
                node.generators[0].is_async:
            return unknown()
        gen = node.generators[0]
        it = self._eval(gen.iter, env, module, funcname)
        items = self._iter_items(it)
        if items is None:
            self._bind(gen.target, unknown(it.rank_dep), None, env,
                       module, funcname)
            self._eval(node.elt, env, module, funcname)
            return unknown(it.rank_dep)
        out = []
        ok = True
        for item in items[:MAX_UNROLL]:
            self._bind(gen.target, const(item, it.rank_dep), None, env,
                       module, funcname)
            val = self._eval(node.elt, env, module, funcname)
            if val.kind == "const":
                out.append(val.value)
            else:
                ok = False
        if ok and len(items) <= MAX_UNROLL:
            return const(out, it.rank_dep)
        return unknown(it.rank_dep)

    # -- calls ------------------------------------------------------------

    def _eval_call(self, node, env, module, funcname):
        # Evaluate arguments first, IN ORDER — nested collective calls
        # inside argument lists must land in the schedule before the
        # outer call acts.
        args = [self._eval(a, env, module, funcname) for a in node.args]
        kwargs = {}
        for kw in node.keywords:
            val = self._eval(kw.value, env, module, funcname)
            if kw.arg:
                kwargs[kw.arg] = val

        model = module.model
        cname = collective_call_name(model, node)
        base, attr = _call_base_attr(node.func)
        hvd_call = base is not None and _is_hvd_base(model, base) or \
            (base is None and attr in model.hvd_members)

        # 1. hvd informational/topology calls -> concrete values.
        if hvd_call and attr in _INFO_FUNCS:
            return self._info_value(attr)
        # 2. hvd structural constructors.
        if hvd_call and attr == "new_group":
            return self._make_group(node, args, kwargs, module,
                                    funcname)
        if hvd_call and attr in ("model_group", "batch_group"):
            return SymVal("group", self._implicit_group(attr))
        if cname in ("DistributedOptimizer", "DistributedGradientTape"):
            return self._make_opt(node, kwargs, module, funcname)
        if hvd_call and (attr or "").endswith("State"):
            return SymVal("state", None)
        if hvd_call and attr == "DurableCheckpointer":
            return SymVal("ckpt", None)
        if hvd_call and attr == "run" and args and \
                args[0].kind == "func":
            return args[0]  # hvd.elastic.run(train) decorator-as-call
        # The receiver of an attribute call is evaluated exactly ONCE
        # (its expression may itself contain collective calls — they
        # must land in the schedule a single time).
        receiver = None
        if isinstance(node.func, ast.Attribute):
            receiver = self._eval(node.func.value, env, module, funcname)
        # 3. collectives (and commit/sync/checkpoint entry points).
        if cname is not None:
            return self._emit_collective(cname, node, args, kwargs,
                                         receiver, env, module, funcname)
        # 4. receiver-dispatched methods (opt.step, state.restore,
        #    g.rank(), mod.helper()).
        if receiver is not None:
            handled = self._method_call(receiver, attr, node, args,
                                        kwargs, module, funcname)
            if handled is not None:
                return handled
            if receiver.kind == "module":
                minfo = receiver.value
                menv = self._module_env(minfo)
                target = menv.get(attr)
                if target is None and attr in minfo.functions:
                    target = SymVal("func", minfo.functions[attr])
                if target is not None and target.kind == "func":
                    return self._inline(target.value, node, args,
                                        kwargs, module, funcname)
            if receiver.kind == "func":
                return self._inline(receiver.value, node, args, kwargs,
                                    module, funcname)
            # str methods on consts: "g.{}".format(...) / "-".join(...)
            if attr in ("format", "join"):
                return self._str_method(receiver, attr, args)
        # 5. user functions and builtins by name.
        if isinstance(node.func, ast.Name):
            target = env.get(node.func.id)
            if target is None:
                menv = self._module_envs.get(
                    os.path.realpath(module.path))
                if menv is not None and menv is not env:
                    target = menv.get(node.func.id)
            if target is None and node.func.id in module.functions:
                target = SymVal("func", module.functions[node.func.id])
            if target is not None and target.kind == "func":
                return self._inline(target.value, node, args, kwargs,
                                    module, funcname)
            builtin = self._eval_builtin(node.func.id, args, kwargs)
            if builtin is not None:
                return builtin
        return unknown(any(a.rank_dep for a in args) or
                       any(v.rank_dep for v in kwargs.values()))

    def _info_value(self, attr):
        # One symbolic host: local == world, cross == 1. Rank values
        # carry the rank_dep taint so rank-derived UNKNOWNS (e.g.
        # `table[hvd.rank()]` with an opaque table) still trigger the
        # world-split branch in _exec_if; decidable predicates are
        # unaffected (const-ness is checked before the taint).
        if attr in ("rank", "local_rank"):
            return const(self.rank, rank_dep=True)
        if attr == "cross_rank":
            return const(0, rank_dep=True)
        if attr in ("size", "local_size"):
            return const(self.world)
        if attr == "cross_size":
            return const(1)
        return const(True)  # is_initialized / is_homogeneous

    def _site(self, node, module, funcname):
        return (module.path, getattr(node, "lineno", 1), funcname)

    def _chain(self, node, module, funcname):
        return self.stack + (self._site(node, module, funcname),)

    def _make_group(self, node, args, kwargs, module, funcname):
        self.group_counter += 1
        ranks = None
        # groups.py: new_group(ranks) — the keyword spelling is valid
        ranks_val = args[0] if args else kwargs.get("ranks")
        if ranks_val is not None:
            items = self._iter_items(ranks_val)
            if items is not None and not ranks_val.rank_dep and \
                    all(isinstance(i, int) for i in items):
                ranks = tuple(sorted(items))
        chain = self._chain(node, module, funcname)
        group = GroupVal(self.group_counter, ranks,
                         "group#%d" % self.group_counter, chain)
        # Registration IS ordering-relevant: every rank must call
        # new_group with the same lists in the same order.
        name = "new_group#%d" % self.group_counter
        if ranks is not None:
            name = "new_group[%s]" % ",".join(str(r) for r in ranks)
        elif ranks_val is not None and ranks_val.rank_dep:
            name = "new_group[<?r%d>]" % self.rank
        self._push_event(Event(
            "new_group", name, group=None, collective=True,
            chain=chain, path=module.path,
            line=getattr(node, "lineno", 1)))
        return SymVal("group", group)

    def _implicit_group(self, label):
        self.group_counter += 1
        return GroupVal(self.group_counter, None, label, self.stack)

    def _make_opt(self, node, kwargs, module, funcname):
        sharded = False
        su = kwargs.get("sharded_update")
        if su is not None:
            if su.kind == "const":
                sharded = bool(su.value)
            else:
                sharded = None  # dynamic
        compression = None
        comp = kwargs.get("compression")
        if comp is not None:
            if comp.kind == "const":
                compression = comp.value
            else:
                compression = "<?>"
        group = None
        g = kwargs.get("group")
        if g is not None and g.kind == "group":
            group = g.value
        prefix = None
        pf = kwargs.get("name_prefix")
        if pf is not None and pf.kind == "const":
            prefix = str(pf.value)
        return SymVal("opt", OptVal(
            sharded, compression, group,
            self._chain(node, module, funcname), prefix=prefix))

    def _method_call(self, receiver, attr, node, args, kwargs, module,
                     funcname):
        """Returns a SymVal when the method call was modeled, else None."""
        if receiver.kind in ("opt", "optunion") and \
                attr in _OPT_STEP_METHODS:
            opts = receiver.value if receiver.kind == "optunion" \
                else (receiver.value,)
            for opt in opts:
                self._push_event(Event(
                    "allreduce", opt.grads_name(), group=opt.group,
                    compression=opt.compression,
                    sharded=opt.sharded,  # True | False | None (dynamic)
                    collective=True,
                    chain=opt.chain if len(opts) > 1
                    else self._chain(node, module, funcname),
                    path=module.path, line=getattr(node, "lineno", 1)))
            if attr == "update":
                return const((None, None))  # (updates, new_state) shape
            return unknown()
        if receiver.kind == "state":
            if attr == "restore":
                self._push_event(Event(
                    "restore", "<state>", collective=False,
                    chain=self._chain(node, module, funcname),
                    path=module.path, line=getattr(node, "lineno", 1)))
                return const(None)
            if attr in ("save", "check_host_updates", "check_drain",
                        "register"):
                return const(None)
        if receiver.kind == "ckpt" and attr == "restore_into":
            self._push_event(Event(
                "restore", "<durable>", collective=False,
                chain=self._chain(node, module, funcname),
                path=module.path, line=getattr(node, "lineno", 1)))
            return unknown()
        if receiver.kind == "group":
            g = receiver.value
            if attr == "rank":
                # rank_dep taint, like hvd.rank(): opaque lookups fed
                # by a group position must still split the world
                if g.ranks is not None:
                    pos = g.ranks.index(self.rank) \
                        if self.rank in g.ranks else -1
                    return const(pos, rank_dep=True)
                return unknown(True)
            if attr == "size":
                if g.ranks is not None:
                    return const(len(g.ranks))
                return unknown()
        return None

    def _str_method(self, recv, attr, args):
        if recv.kind != "const" or not isinstance(recv.value, str):
            return unknown(recv.rank_dep or
                           any(a.rank_dep for a in args))
        rank_dep = recv.rank_dep or any(a.rank_dep for a in args)
        if attr == "format":
            out = recv.value
            for a in args:
                filler = str(a.value) if a.kind == "const" else (
                    "<?r%d>" % self.rank if a.rank_dep else "<?>")
                out = out.replace("{}", filler, 1)
            return const(out, rank_dep)
        if attr == "join" and args:
            items = self._iter_items(args[0])
            if items is not None:
                return const(recv.value.join(str(i) for i in items),
                             rank_dep)
        return unknown(rank_dep)

    def _eval_builtin(self, name, args, kwargs):
        rank_dep = any(a.rank_dep for a in args)
        consts = [a.value for a in args if a.kind == "const"]
        all_const = len(consts) == len(args) and not kwargs
        try:
            if name == "range" and all_const and args:
                return const(range(*consts), rank_dep)
            if name == "len" and all_const and args:
                return const(len(consts[0]), rank_dep)
            if name == "sorted" and all_const and args:
                return const(sorted(consts[0]), rank_dep)
            if name == "list" and all_const:
                return const(list(consts[0]) if consts else [], rank_dep)
            if name == "tuple" and all_const:
                return const(tuple(consts[0]) if consts else (),
                             rank_dep)
            if name in ("int", "str", "float", "bool") and all_const \
                    and len(consts) == 1:
                return const({"int": int, "str": str, "float": float,
                              "bool": bool}[name](consts[0]), rank_dep)
            if name in ("min", "max") and all_const and args:
                fn = min if name == "min" else max
                if len(consts) == 1:
                    return const(fn(consts[0]), rank_dep)
                return const(fn(consts), rank_dep)
            if name == "enumerate" and all_const and args:
                return const(list(enumerate(consts[0])), rank_dep)
            if name == "print":
                return const(None)
        except Exception:
            return unknown(rank_dep)
        return None

    def _inline(self, finfo, node, args, kwargs, module, funcname):
        """Bounded inlining of a user function with the call site
        recorded on the chain. Recursion and over-depth calls degrade
        to unknown."""
        key = (finfo.module.path, finfo.name)
        if self.depth >= MAX_DEPTH or key in self.inlining:
            return unknown()
        site = self._site(node, module, funcname)
        # The callee's globals are its module's env; locals start from
        # a copy so callee assignments never leak back.
        fenv = dict(self._module_env(finfo.module))
        self._bind_params(finfo, fenv, args, kwargs, module, funcname)
        self.depth += 1
        old_stack, old_inlining = self.stack, self.inlining
        self.stack = self.stack + (site,)
        self.inlining = self.inlining + (key,)
        result = const(None)
        try:
            self._exec_body(finfo.node.body, fenv, finfo.module,
                            finfo.name)
        except _Return as ret:
            result = ret.value
        finally:
            self.depth -= 1
            self.stack, self.inlining = old_stack, old_inlining
        return result

    def _bind_params(self, finfo, fenv, args, kwargs, module, funcname):
        node = finfo.node
        params = [a.arg for a in node.args.args]
        posonly = getattr(node.args, "posonlyargs", [])
        params = [a.arg for a in posonly] + params
        defaults = node.args.defaults
        # defaults align to the tail of params
        for i, p in enumerate(params):
            fenv[p] = unknown()
        offset = len(params) - len(defaults)
        for i, d in enumerate(defaults):
            fenv[params[offset + i]] = self._eval(
                d, fenv, finfo.module, finfo.name)
        for i, a in enumerate(args):
            if i < len(params):
                fenv[params[i]] = a
        for k, v in kwargs.items():
            fenv[k] = v
        for kwarg in node.args.kwonlyargs:
            if kwarg.arg not in fenv:
                fenv[kwarg.arg] = unknown()

    # -- the recursion-marker stack needs the emit/real split above;
    #    events always use self.stack at emission time -------------------

    def _push_event(self, event):
        if len(self.events) >= MAX_EVENTS:
            raise _Budget()
        self.events.append(event)

    # -- collective emission ----------------------------------------------

    _KIND = {
        "allreduce": "allreduce", "allreduce_async": "allreduce",
        "allreduce_gradients": "allreduce", "allreduce_sparse":
        "allreduce", "grouped_allreduce": "allreduce",
        "metric_average": "allreduce",
        "reduce_scatter": "reducescatter",
        "reduce_scatter_async": "reducescatter",
        "allgather": "allgather", "allgather_async": "allgather",
        "alltoall": "alltoall",
        "broadcast": "broadcast", "broadcast_async": "broadcast",
        "broadcast_object": "broadcast", "broadcast_parameters":
        "broadcast", "broadcast_optimizer_state": "broadcast",
        "broadcast_variables": "broadcast",
        "broadcast_global_variables": "broadcast",
        "BroadcastGlobalVariablesHook": "broadcast",
        "BroadcastGlobalVariablesCallback": "broadcast",
        "commit": "commit", "sync": "sync",
        "checkpoint.save": "checkpoint.save",
        "checkpoint.restore": "checkpoint.restore",
    }

    def _emit_collective(self, cname, node, args, kwargs, receiver, env,
                         module, funcname):
        if cname in ("commit", "sync") and receiver is not None and \
                receiver.kind not in ("state", "unknown"):
            return unknown()  # definitely not an elastic state
        kind = self._KIND.get(cname)
        if kind is None:
            return unknown()

        # name / name_prefix — from the ALREADY-EVALUATED kwargs (the
        # expression may contain collective calls; re-evaluating it
        # would duplicate their schedule events)
        name_val = kwargs.get("name")
        if name_val is None:
            name_val = kwargs.get("name_prefix")
        if name_val is None:
            for pos in COLLECTIVES.get(cname, ()):
                if pos < len(node.args):
                    cand = args[pos]
                    if cand.kind == "const" and \
                            isinstance(cand.value, str):
                        name_val = cand
                        break
                    if cand.rank_dep:
                        name_val = cand
                        break
        if name_val is None:
            if cname in ("checkpoint.save", "checkpoint.restore"):
                # kind-qualified: save and restore are different
                # negotiations, not one name with two kinds
                name = "<%s>" % cname
            elif cname in ("commit", "sync"):
                name = "<%s>" % cname
            elif cname in INITIAL_BROADCASTS or \
                    cname == "broadcast_global_variables":
                name = "<params>"
            elif cname in ("allreduce_gradients",):
                name = "<grads>"
            else:
                self.auto_counter += 1
                name = "<auto#%d>" % self.auto_counter
        elif name_val.kind == "const":
            name = str(name_val.value)
        elif name_val.rank_dep:
            name = "<?r%d>" % self.rank
        else:
            name = "<?>"

        group = None
        g = kwargs.get("group")
        if g is not None:
            if g.kind == "group":
                group = g.value
            elif g.kind == "const" and g.value is None:
                group = None
            else:
                self.group_counter += 1
                group = GroupVal(self.group_counter, None, "group<?>",
                                 self.stack)

        compression = None
        comp = kwargs.get("compression")
        if comp is not None:
            compression = comp.value if comp.kind == "const" else "<?>"

        chain = self._chain(node, module, funcname)
        line = getattr(node, "lineno", 1)

        # Non-member reachability: a group collective on a rank outside
        # the group's membership is the static form of the runtime
        # "submitted by rank(s) outside the group" rejection.
        if group is not None and group.ranks is not None and \
                self.rank not in group.ranks:
            anchor = chain[0]  # outermost frame: always the entry file
            self.findings.append(ExecFinding(
                "verify-non-member-group-call",
                "group collective `%s` '%s' in %s is reachable on "
                "symbolic rank %d, which is NOT a member of the group "
                "(runtime: the coordinator rejects the report naming "
                "the rank, or the member ranks hang waiting). Guard the "
                "call with the group's membership. call chain: %s; "
                "group registration chain: %s"
                % (cname, name, group.describe(), self.rank,
                   format_chain(chain),
                   format_chain(group.chain) or "unknown"),
                anchor[0], anchor[1],
                getattr(node, "end_lineno", None)
                if len(chain) == 1 else anchor[1]))
            return unknown()

        self._push_event(Event(
            kind, name, group=group, compression=compression,
            sharded=False, collective=True, chain=chain,
            path=module.path, line=line))
        return unknown()
