"""hvd-verify: whole-program collective-schedule verification.

Runs the symbolic executor (symbolic.py) once per rank of an abstract
W-rank world over the interprocedural program model (callgraph.py) and
*diffs the resulting schedules*. The runtime divergence detector
(native/divergence.cc) proves these bugs only after the job is launched
and has hung for the grace window; here the same classes of bug are
proven before launch, each reported with BOTH conflicting call-site
chains — mirroring the runtime error's "submitted by / went on to"
format.

Finding classes (rule ids are suppression keys like every other rule):

* ``verify-divergent-schedule`` — two symbolic ranks disagree on the
  ordered sequence of collectives they must both join (the
  cross-function generalization of rank-conditional-collective);
* ``verify-kind-mismatch`` — one tensor name negotiated as different
  op kinds on different paths/ranks;
* ``verify-non-member-group-call`` — a group collective reachable on a
  rank outside the group's membership;
* ``verify-mixed-modes`` — one tensor name negotiated with different
  compression or sharded-update modes on different paths/ranks;
* ``verify-missing-restore-broadcast`` — a rank-local state restore
  (``state.restore()`` / ``DurableCheckpointer.restore_into``) followed
  by gradient averaging with no broadcast/sync in between: ranks train
  on silently different weights.
"""

import collections
import os

from .rules import ERROR, WARNING, Finding, RULES, register_meta
from .symbolic import Executor, format_chain

DEFAULT_WORLD = 4

register_meta("verify-divergent-schedule", ERROR,
              "symbolic ranks disagree on the collective sequence")
register_meta("verify-kind-mismatch", ERROR,
              "one name negotiated as different collective kinds")
register_meta("verify-non-member-group-call", ERROR,
              "group collective reachable on a non-member rank")
register_meta("verify-mixed-modes", ERROR,
              "one name negotiated with mixed compression/sharded modes")
register_meta("verify-missing-restore-broadcast", ERROR,
              "state restore with no broadcast before gradient averaging")
register_meta("verify-crash", WARNING,
              "the schedule verifier itself failed on this file")

def _wildcard_name(name):
    """Names with unresolved parts: identity across call sites is
    unknowable, so the per-name analyses must not compare them."""
    return "<?" in name or name.startswith("<auto#")


class Schedules(object):
    """Per-rank schedules plus exec-time findings for one entry file."""

    def __init__(self, path, world):
        self.path = path
        self.world = world
        self.per_rank = []       # rank -> [Event] (full, incl. rank-local)
        self.exec_findings = []  # ExecFinding, all ranks
        self.truncated = False
        self.graph = None        # the shared ProgramGraph (one parse)


def extract_schedules(path, source=None, world=DEFAULT_WORLD):
    """Runs the symbolic world; returns a Schedules (or raises
    SyntaxError when the ENTRY file does not parse)."""
    from .callgraph import ProgramGraph

    out = Schedules(path, world)
    # One parse for all ranks: the graph holds only immutable data
    # (sources, ASTs, alias models); every mutable bit of execution
    # state lives on the per-rank Executor.
    graph = ProgramGraph(path, source=source)
    out.graph = graph
    for rank in range(world):
        ex = Executor(graph, rank, world)
        events, findings = ex.run()
        if ex.truncated:
            out.truncated = True
        out.per_rank.append(events)
        out.exec_findings.extend(findings)
    return out


# --------------------------------------------------------------------------
# analyses over the extracted schedules


def _anchor(chain, entry_path):
    """(line, end_line) for a finding: the DEEPEST frame of the chain
    that sits in the entry file — the line the user can actually act on
    (and the line a suppression comment must target)."""
    entry_real = os.path.realpath(entry_path)
    line = chain[0][1] if chain else 1
    for frame in chain:
        if os.path.realpath(frame[0]) == entry_real:
            line = frame[1]
    return line


def _mk(path, line, rule, message):
    return Finding(path=path, line=line, col=1, rule=rule,
                   severity=RULES[rule].default_severity,
                   message=message, end_line=line)


def _participates(event, rank):
    if event.group is None or event.group.ranks is None:
        return True
    return rank in event.group.ranks


def _group_keys_touched(events):
    return {e.group_key() for e in events
            if e.group is not None and e.group.ranks is None}


def _diff_pair(sched, a, b, path, findings, truncated):
    """First disagreement between ranks a and b on the collectives they
    must BOTH join. Events in a group of UNKNOWN membership
    (model_group()/batch_group(), dynamic rank lists) are compared only
    between ranks that each touch that group at all: a rank that
    (correctly) sits the group out via `if g.rank() >= 0:` must not
    read as a divergence, and whether it was SUPPOSED to sit out is
    exactly what the verifier cannot know — the runtime group-scoped
    divergence detection is the backstop there."""
    keys_a = _group_keys_touched(sched.per_rank[a])
    keys_b = _group_keys_touched(sched.per_rank[b])
    shared = keys_a & keys_b

    def relevant(e, other_rank):
        if not e.collective or not _participates(e, other_rank):
            return False
        if e.group is not None and e.group.ranks is None:
            return e.group_key() in shared
        return True

    sa = [e for e in sched.per_rank[a] if relevant(e, b)]
    sb = [e for e in sched.per_rank[b] if relevant(e, a)]
    n = min(len(sa), len(sb))
    for i in range(n):
        ea, eb = sa[i], sb[i]
        if ea.identity() == eb.identity():
            continue
        if ea.kind == eb.kind and ea.name == eb.name:
            # Same slot, same name, DIFFERENT group identity: e.g. two
            # same-member registrations (distinct runtime group ids)
            # with half the ranks submitting under each — the
            # coordinator sees mixed groups for one name.
            findings.append((
                "verify-divergent-schedule",
                _anchor(ea.chain, path),
                ("grp", _anchor(ea.chain, path),
                 _anchor(eb.chain, path)),
                (ea.name, eb.name),
                "collective '%s' is submitted under DIFFERENT process "
                "groups by different ranks: symbolic rank %d uses %s "
                "but symbolic rank %d uses %s — one name must ride one "
                "group (runtime: mixed-membership rejection naming the "
                "rank, docs/GROUPS.md). rank %d call chain: %s; rank "
                "%d call chain: %s"
                % (ea.name, a, ea.group.describe() if ea.group else
                   "the world group", b, eb.group.describe() if
                   eb.group else "the world group", a,
                   format_chain(ea.chain), b, format_chain(eb.chain))))
            return
        findings.append((
            "verify-divergent-schedule",
            _anchor(ea.chain, path),
            ("pos", _anchor(ea.chain, path), _anchor(eb.chain, path)),
            (ea.name, eb.name),
            "collective schedule divergence at shared position %d: "
            "symbolic rank %d submits %s but symbolic rank %d submits "
            "%s — every rank must issue the same collectives in the "
            "same order (runtime: divergence cross-check names both "
            "sides after the grace window). rank %d call chain: %s; "
            "rank %d call chain: %s"
            % (i, a, ea.describe(), b, eb.describe(), a,
               format_chain(ea.chain), b, format_chain(eb.chain))))
        return
    if len(sa) != len(sb) and not truncated:
        longer, shorter = (a, b) if len(sa) > len(sb) else (b, a)
        extra = (sa if len(sa) > len(sb) else sb)[n]
        findings.append((
            "verify-divergent-schedule",
            _anchor(extra.chain, path),
            ("extra", _anchor(extra.chain, path)),
            (extra.name,),
            "collective schedule divergence: symbolic rank %d submits "
            "%s that symbolic rank %d never submits (its schedule ends "
            "after %d shared collectives) — the submitting ranks hang "
            "in negotiation (runtime: divergence cross-check / stall "
            "inspector). rank %d call chain: %s"
            % (longer, extra.describe(), shorter, n, longer,
               format_chain(extra.chain))))


def _per_name_events(sched):
    by_name = collections.OrderedDict()
    for events in sched.per_rank:
        for e in events:
            if e.collective and not _wildcard_name(e.name):
                by_name.setdefault(e.name, []).append(e)
    return by_name


def _kind_mismatches(sched, path, findings):
    for name, events in _per_name_events(sched).items():
        kinds = collections.OrderedDict()
        for e in events:
            kinds.setdefault(e.kind, e)
        if len(kinds) < 2:
            continue
        (k1, e1), (k2, e2) = list(kinds.items())[:2]
        findings.append((
            "verify-kind-mismatch", _anchor(e1.chain, path),
            (name, k1, k2), (name,),
            "collective name '%s' is negotiated as %s on one path but "
            "as %s on another: whichever rank reaches the second path "
            "submits incompatible metadata for the same tensor name "
            "and the coordinator rejects it (runtime: cross-rank "
            "validation names the mismatched field). %s chain: %s; %s "
            "chain: %s"
            % (name, k1, k2, k1, format_chain(e1.chain), k2,
               format_chain(e2.chain))))


def _norm_comp(comp):
    if comp in (None, "", "none", 0, False):
        return "none"
    return comp


def _mode_mismatches(sched, path, findings):
    for name, events in _per_name_events(sched).items():
        comps = collections.OrderedDict()
        shardeds = collections.OrderedDict()
        for e in events:
            c = _norm_comp(e.compression)
            if c != "<?>":
                comps.setdefault(c, e)
            if e.sharded is not None:
                shardeds.setdefault(bool(e.sharded), e)
        if len(comps) > 1:
            (c1, e1), (c2, e2) = list(comps.items())[:2]
            findings.append((
                "verify-mixed-modes", _anchor(e1.chain, path),
                (name, "compression", c1, c2), (name,),
                "collective name '%s' rides compression mode '%s' on "
                "one path and '%s' on another: the mode is part of the "
                "negotiated wire format, so mixed modes for one name "
                "either corrupt the decoded values or are rejected "
                "cross-rank (docs/COMPRESSION.md). '%s' chain: %s; "
                "'%s' chain: %s"
                % (name, c1, c2, c1, format_chain(e1.chain), c2,
                   format_chain(e2.chain))))
        if len(shardeds) > 1:
            (s1, e1), (s2, e2) = list(shardeds.items())[:2]
            findings.append((
                "verify-mixed-modes", _anchor(e1.chain, path),
                (name, "sharded", s1, s2), (name,),
                "collective name '%s' runs with sharded_update=%s on "
                "one path and sharded_update=%s on another: sharded "
                "ranks negotiate REDUCESCATTER while replicated ranks "
                "negotiate ALLREDUCE for the same name — the runtime "
                "rejects the mix naming both ranks and modes "
                "(docs/ZERO.md). sharded=%s chain: %s; sharded=%s "
                "chain: %s"
                % (name, s1, s2, s1, format_chain(e1.chain), s2,
                   format_chain(e2.chain))))


_SYNCING_KINDS = {"sync", "broadcast", "checkpoint.restore"}


def _missing_restore_broadcast(sched, path, findings):
    # EVERY restore site is audited (a later unsynced restore after an
    # earlier synced one is the classic elastic re-init bug); each
    # site is inspected once, on the first rank that reaches it.
    seen_sites = set()
    for rank, events in enumerate(sched.per_rank):
        for i, e in enumerate(events):
            if e.kind != "restore":
                continue
            site = (e.path, e.line)
            if site in seen_sites:
                continue
            seen_sites.add(site)
            for later in events[i + 1:]:
                if later.kind in _SYNCING_KINDS:
                    break
                if later.kind in ("allreduce", "reducescatter"):
                    findings.append((
                        "verify-missing-restore-broadcast",
                        _anchor(e.chain, path),
                        (e.name, later.name), (),
                        "state restore at %s is followed by gradient "
                        "averaging (%s at %s) with no broadcast or "
                        "state.sync() in between: restore is "
                        "rank-local, so after an elastic restart "
                        "survivors and fresh ranks average gradients "
                        "from different weights and silently train "
                        "unsynchronized (runtime: no error at all — "
                        "the job completes with wrong results). "
                        "restore chain: %s; allreduce chain: %s"
                        % (format_chain(e.chain[-1:]), later.describe(),
                           format_chain(later.chain[-1:]),
                           format_chain(e.chain),
                           format_chain(later.chain))))
                    break


def analyze(sched):
    """All schedule analyses; returns a list of
    (rule, line, dedupe_key, names, message) tuples."""
    raw = []
    _kind_mismatches(sched, sched.path, raw)
    _mode_mismatches(sched, sched.path, raw)
    # Pairwise diffs. A kind mismatch also shows up as a sequence diff
    # of the SAME name on both sides — report that once with the
    # sharper per-name message. A diff pairing two DIFFERENT names is
    # its own divergence even when one of them happens to carry an
    # unrelated kind/mode finding, so it is kept.
    owned = set()
    for rule, line, key, names, msg in raw:
        owned.update(names)
    diffs = []
    for a in range(sched.world):
        for b in range(a + 1, sched.world):
            _diff_pair(sched, a, b, sched.path, diffs, sched.truncated)
    for rule, line, key, names, msg in diffs:
        if len(names) == 2 and names[0] == names[1] and \
                names[0] in owned:
            continue
        raw.append((rule, line, key, names, msg))
    _missing_restore_broadcast(sched, sched.path, raw)
    return raw


# --------------------------------------------------------------------------
# public entry points


def verify_source(source, path="<string>", world=DEFAULT_WORLD,
                  rules=None):
    """Verifies one entry script; returns a list of Findings
    (suppressions applied; a syntax error returns [] — the lexical pass
    already reports parse-error for it)."""
    try:
        sched = extract_schedules(path, source=source, world=world)
    except SyntaxError:
        return []
    except RecursionError:
        return [_mk(path, 1, "verify-crash",
                    "schedule verification hit the recursion limit; "
                    "the file was NOT verified")]
    except Exception as e:  # a verifier bug must not mask the report
        return [_mk(path, 1, "verify-crash",
                    "schedule verification failed (%s: %s); the file "
                    "was NOT verified" % (type(e).__name__, e))]

    findings = []
    seen = set()
    for f in sched.exec_findings:
        key = (f.rule, f.line, f.message.split("symbolic rank")[0])
        if key in seen:
            continue
        seen.add(key)
        findings.append(Finding(
            path=path, line=f.line, col=1, rule=f.rule,
            severity=RULES[f.rule].default_severity,
            message=f.message, end_line=f.end_line))
    for rule, line, key, names, msg in analyze(sched):
        dkey = (rule,) + tuple(key)
        if dkey in seen:
            continue
        seen.add(dkey)
        findings.append(_mk(path, line, rule, msg))

    # Suppressions come from the entry module's walker model — the
    # same parse the executors ran on (one per file, not three).
    model = sched.graph.entry.model
    out = []
    for f in findings:
        if rules is not None and f.rule not in rules:
            continue
        if model.is_suppressed(f.line, f.rule, f.end_line):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.line, f.col, f.rule))
    return out


def verify_paths(paths, world=DEFAULT_WORLD, rules=None):
    """Verifies files/directories (each .py file is its own entry
    script); returns (findings, files_checked)."""
    from . import iter_python_files

    findings = []
    files_checked = 0
    for fpath in iter_python_files(paths):
        try:
            with open(fpath, "r", encoding="utf-8",
                      errors="replace") as fh:
                source = fh.read()
        except OSError:
            continue  # the lexical pass reports io-error for it
        files_checked += 1
        findings.extend(verify_source(source, path=fpath, world=world,
                                      rules=rules))
    return findings, files_checked
