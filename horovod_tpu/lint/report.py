"""Finding reporters: human (path:line:col, grep/editor-friendly), JSON
(stable schema for CI and the launcher preflight), and SARIF 2.1.0 (so
CI can annotate diffs and track suppressions).

Every finding carries a stable *fingerprint* — a hash over the rule,
the file's basename, the message with volatile parts (line/col numbers,
absolute paths) normalized out, and the text of the anchored source
line. Fingerprints survive unrelated edits that shift line numbers, so
CI baselines and SARIF result-matching keep recognizing a finding
after a refactor above it.
"""

import hashlib
import json
import os
import re

# Volatile message parts that must not feed the fingerprint: line/col
# references inside chains ("foo.py:123") and bare "position N" /
# "after N" counters that shift with unrelated edits.
_LINE_REF = re.compile(r"(:)\d+")
_COUNTER = re.compile(r"\b(position|after) \d+")


def _read_lines_cached(path, _cache={}):
    """One read per file per process — the reporters fingerprint every
    finding, and a noisy file would otherwise be re-read per finding."""
    if path not in _cache:
        if len(_cache) > 256:
            _cache.clear()
        try:
            with open(path, "r", encoding="utf-8",
                      errors="replace") as fh:
                _cache[path] = fh.read().splitlines()
        except OSError:
            _cache[path] = None
    return _cache[path]


def _anchored_line_text(finding, source_lines=None):
    if source_lines is None:
        source_lines = _read_lines_cached(finding.path)
    if source_lines is not None and 0 < finding.line <= len(source_lines):
        return source_lines[finding.line - 1].strip()
    return ""


def fingerprint(finding, source_lines=None):
    """Stable hex id for a finding (16 chars): immune to line shifts
    and to directory moves, sensitive to rule, file name, normalized
    message, and the anchored line's code."""
    msg = _LINE_REF.sub(r"\1N", finding.message)
    msg = _COUNTER.sub(r"\1 N", msg)
    payload = "\x1f".join([
        finding.rule,
        os.path.basename(finding.path),
        msg,
        _anchored_line_text(finding, source_lines),
    ])
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()[:16]


def format_human(findings, out):
    for f in findings:
        out.write("%s:%d:%d: %s [%s] %s\n" %
                  (f.path, f.line, f.col, f.severity, f.rule, f.message))


def summarize_human(findings, files_checked, out):
    errors = sum(1 for f in findings if f.severity == "error")
    warnings = sum(1 for f in findings if f.severity == "warning")
    if findings:
        out.write("hvd-lint: %d error(s), %d warning(s) in %d file(s)\n"
                  % (errors, warnings, files_checked))
    else:
        out.write("hvd-lint: %d file(s) clean\n" % files_checked)


def format_json(findings, files_checked, out):
    payload = {
        "files_checked": files_checked,
        "findings": [
            {
                "path": f.path,
                "line": f.line,
                "col": f.col,
                "rule": f.rule,
                "severity": f.severity,
                "message": f.message,
                "fingerprint": fingerprint(f),
            }
            for f in findings
        ],
    }
    json.dump(payload, out, indent=2, sort_keys=True)
    out.write("\n")


_SARIF_LEVEL = {"error": "error", "warning": "warning", "info": "note"}


def format_sarif(findings, files_checked, out, tool_name="hvd-lint",
                 information_uri="docs/LINT.md"):
    """SARIF 2.1.0: one run, rules from the registry, results with
    partialFingerprints so SARIF consumers (GitHub code scanning et
    al.) match findings across commits even when lines shift."""
    from .rules import RULES

    used = []
    seen = set()
    for f in findings:
        if f.rule not in seen:
            seen.add(f.rule)
            used.append(f.rule)
    rules = []
    for rule_id in used:
        rule = RULES.get(rule_id)
        rules.append({
            "id": rule_id,
            "shortDescription": {
                "text": rule.summary if rule is not None else rule_id},
            "defaultConfiguration": {
                "level": _SARIF_LEVEL.get(
                    rule.default_severity if rule is not None
                    else "warning", "warning")},
        })
    index = {rule_id: i for i, rule_id in enumerate(used)}

    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "ruleIndex": index[f.rule],
            "level": _SARIF_LEVEL.get(f.severity, "warning"),
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace(os.sep, "/")},
                    "region": {"startLine": f.line,
                               "startColumn": f.col,
                               "endLine": f.end_line or f.line},
                },
            }],
            "partialFingerprints": {
                "hvdLintFingerprint/v1": fingerprint(f),
            },
        })

    payload = {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": tool_name,
                    "informationUri": information_uri,
                    "rules": rules,
                },
            },
            "results": results,
        }],
    }
    json.dump(payload, out, indent=2, sort_keys=True)
    out.write("\n")
