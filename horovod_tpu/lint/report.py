"""Finding reporters: human (path:line:col, grep/editor-friendly) and JSON
(stable schema for CI and the launcher preflight)."""

import json


def format_human(findings, out):
    for f in findings:
        out.write("%s:%d:%d: %s [%s] %s\n" %
                  (f.path, f.line, f.col, f.severity, f.rule, f.message))


def summarize_human(findings, files_checked, out):
    errors = sum(1 for f in findings if f.severity == "error")
    warnings = sum(1 for f in findings if f.severity == "warning")
    if findings:
        out.write("hvd-lint: %d error(s), %d warning(s) in %d file(s)\n"
                  % (errors, warnings, files_checked))
    else:
        out.write("hvd-lint: %d file(s) clean\n" % files_checked)


def format_json(findings, files_checked, out):
    payload = {
        "files_checked": files_checked,
        "findings": [
            {
                "path": f.path,
                "line": f.line,
                "col": f.col,
                "rule": f.rule,
                "severity": f.severity,
                "message": f.message,
            }
            for f in findings
        ],
    }
    json.dump(payload, out, indent=2, sort_keys=True)
    out.write("\n")
