"""AST walker: builds the semantic model the per-rule checkers consume.

One pass over the tree collects, with full lexical context:

* every horovod collective call site (resolved through import aliases, so
  ``import horovod_tpu.jax as hx; hx.allreduce(...)`` and
  ``from horovod_tpu.jax import allreduce`` both count);
* the stack of enclosing conditionals/loops each site sits under, with
  each condition classified as rank-dependent or uniform;
* a one-level dataflow of variables assigned from ``rank()``-like calls
  (so ``r = hvd.rank(); if r == 0:`` is recognized) and of unordered
  iterables;
* inline suppressions (``# hvd-lint: disable=<rule>[,<rule>...]``) from
  the token stream, applying to their own line, or to the next code line
  when the comment stands alone.

The model is purely lexical: collectives reached through helper-function
*calls* under a rank conditional are not traced inter-procedurally (the
runtime digest cross-check is the backstop for those — docs/LINT.md).
"""

import ast
import io
import tokenize

# --- what counts as a collective -------------------------------------------

# callable name -> candidate positional indices of the `name`/`name_prefix`
# argument (keyword always wins). Positions cover both the framework-level
# APIs (horovod_tpu.jax etc.: allreduce(tensor, average, name)) and the
# host-ops layer (common.ops: allreduce(tensor, name)).
COLLECTIVES = {
    "allreduce": (1, 2),
    "allreduce_async": (1,),
    "allreduce_gradients": (2,),
    "allreduce_sparse": (2,),
    "grouped_allreduce": (1,),
    # host-ops layer: reduce_scatter(tensor, name); the jax binding
    # takes name= at position 2
    "reduce_scatter": (1, 2),
    "reduce_scatter_async": (1,),
    "allgather": (1,),
    "allgather_async": (1,),
    "alltoall": (1,),
    "broadcast": (2,),
    "broadcast_async": (2,),
    "broadcast_object": (2,),
    "broadcast_parameters": (2,),
    "broadcast_optimizer_state": (2,),
    "broadcast_variables": (2,),
    "metric_average": (1,),
}

# Collectives whose names are derived from a prefix + stable pytree order;
# calling these in a loop re-negotiates the SAME names (cache-friendly), so
# the loop-auto-name rule must not fire on them.
PREFIX_NAMED = {
    "allreduce_gradients", "allreduce_sparse", "broadcast_object",
    "broadcast_parameters", "broadcast_optimizer_state",
    "broadcast_variables",
}

# Presence of any of these marks a script as "training with gradient
# averaging" for the missing-initial-broadcast rule...
TRAIN_MARKERS = {
    "DistributedOptimizer", "DistributedGradientTape", "allreduce_gradients",
}
# ...and any of these satisfies it.
INITIAL_BROADCASTS = {
    "broadcast_parameters", "broadcast_optimizer_state",
    "broadcast_variables", "broadcast_object", "broadcast_global_variables",
    "BroadcastGlobalVariablesHook", "BroadcastGlobalVariablesCallback",
}

# hvd.elastic commit points: divergence hazards under rank conditionals
# exactly like collectives (state.commit()/sync() run coordinated
# collectives internally).
ELASTIC_COMMITS = {"commit", "sync"}

# hvd.jax.checkpoint entry points: save()/restore() contain collectives
# (the success-flag broadcast + barrier / value broadcast), so they are
# collective call sites for lexical purposes — recorded with the
# canonical names "checkpoint.save"/"checkpoint.restore" so the
# dedicated checkpoint-in-rank-guard rule (not the generic
# rank-conditional-collective one) owns them.
CHECKPOINT_CALLS = {"save", "restore"}

# Calls returning per-rank values: conditions and collective names derived
# from these diverge across ranks. (size()/cross_size() are uniform;
# local_size() differs on heterogeneous hosts, so it is included.)
RANK_FUNCS = {"rank", "local_rank", "cross_rank", "local_size"}

# Nondeterministic / per-process name sources for the rank-dependent-name
# rule: (module-ish base, attr) pairs matched loosely on the call chain.
NONDET_CALLS = {
    ("socket", "gethostname"), ("platform", "node"), ("os", "getpid"),
    ("time", "time"), ("time", "monotonic"), ("time", "perf_counter"),
    ("time", "time_ns"), ("uuid", "uuid1"), ("uuid", "uuid4"),
}
NONDET_BASES = {"random"}  # random.random(), np.random.*, ...

# Calls that produce integer-valued tensors (argmax/argmin/randint/
# bincount/unique...) — quantizing these silently corrupts. `astype`/
# `dtype=` arguments are additionally inspected textually for int/bool.
INT_PRODUCING_CALLS = {
    "argmax", "argmin", "argsort", "randint", "bincount", "searchsorted",
    "digitize", "count_nonzero", "nonzero",
}

# Calls that read embedding tables by index: their gradients are
# index-selected rows whose magnitudes vary wildly per block, the case
# EQuARX-style block quantization handles worst (and lossy compression
# of the LOOKUP ids themselves is outright corruption).
EMBEDDING_LOOKUP_CALLS = {
    "take", "take_along_axis", "embedding_lookup",
    "embedding_lookup_sparse", "gather",
}

HOROVOD_ROOT = "horovod_tpu"
# Module names whose attributes we also accept when imported without an
# alias map hit (plain `horovod` scripts being migrated).
_HVD_FALLBACK_PREFIXES = ("horovod",)


class Condition(object):
    """One enclosing `if`/`while` test (or a boolean guard)."""

    __slots__ = ("node", "rank_dependent", "source")

    def __init__(self, node, rank_dependent, source):
        self.node = node
        self.rank_dependent = rank_dependent
        self.source = source  # short human description, e.g. "rank() == 0"


class Loop(object):
    """One enclosing `for`/`while` loop."""

    __slots__ = ("node", "target_names", "unordered", "unordered_kind")

    def __init__(self, node, target_names=(), unordered=False,
                 unordered_kind=None):
        self.node = node
        self.target_names = set(target_names)
        self.unordered = unordered
        self.unordered_kind = unordered_kind  # "set" | "dict"


class CallSite(object):
    """A collective (or elastic-commit) call with its lexical context."""

    __slots__ = ("node", "func", "is_commit", "name_node", "conditions",
                 "loops", "kwargs", "args")

    def __init__(self, node, func, is_commit, name_node, conditions, loops,
                 args, kwargs):
        self.node = node
        self.func = func                # canonical collective name
        self.is_commit = is_commit
        self.name_node = name_node      # AST expr of name/name_prefix or None
        self.conditions = conditions    # tuple of Condition (outermost first)
        self.loops = loops              # tuple of Loop (outermost first)
        self.args = args
        self.kwargs = kwargs            # dict name -> AST expr


class Model(object):
    def __init__(self, path, source, tree):
        self.path = path
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()
        self.hvd_aliases = set()        # names bound to horovod modules
        self.hvd_members = set()        # collective names imported directly
        self.rank_vars = set()          # variables holding rank-like values
        self.unordered_vars = {}        # var -> "set"|"dict"
        self.int_vars = set()           # variables holding integer tensors
        self.embed_vars = set()         # variables from embedding lookups
        self.call_sites = []
        self.suppressed = {}            # line -> set of rule ids ({"*"}=all)
        self.uses_elastic = False

    # -- suppression queries -------------------------------------------

    def is_suppressed(self, line, rule_id, end_line=None):
        """True when any line of [line, end_line] carries a suppression
        for `rule_id` (multi-line statements accept the comment on any
        of their lines, e.g. after the closing paren)."""
        for ln in range(line, (end_line or line) + 1):
            rules = self.suppressed.get(ln)
            if rules is not None and ("*" in rules or rule_id in rules):
                return True
        return False


# --- suppression comments ---------------------------------------------------

def _scan_suppressions(source, model):
    """Fills model.suppressed from `# hvd-lint: disable=...` comments.

    A trailing comment suppresses its own line; a comment-only line
    suppresses the next non-blank, non-comment line.
    """
    pending = set()  # rules from standalone comments awaiting a code line
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return
    for tok in tokens:
        if tok.type == tokenize.COMMENT:
            rules = _parse_suppression(tok.string)
            if rules is None:
                continue
            line_text = model.lines[tok.start[0] - 1] \
                if tok.start[0] - 1 < len(model.lines) else ""
            if line_text.strip().startswith("#"):
                pending.update(rules)  # stacked comments accumulate
            else:
                model.suppressed.setdefault(tok.start[0], set()).update(rules)
        elif tok.type in (tokenize.NL, tokenize.NEWLINE, tokenize.INDENT,
                          tokenize.DEDENT):
            continue
        elif pending and tok.type not in (tokenize.ENDMARKER,):
            model.suppressed.setdefault(tok.start[0], set()).update(pending)
            pending = set()


def _parse_suppression(comment):
    """Returns the rule-id set for a `# hvd-lint: disable[=...]` comment,
    or None when the comment is not a suppression."""
    text = comment.lstrip("#").strip()
    if not text.startswith("hvd-lint:"):
        return None
    text = text[len("hvd-lint:"):].strip()
    if not text.startswith("disable"):
        return None
    rest = text[len("disable"):].strip()
    if not rest:
        return {"*"}
    if rest.startswith("="):
        ids = [r.strip() for r in rest[1:].split("#")[0].split(",")]
        return {r for r in ids if r} or {"*"}
    return None


# --- expression classification ----------------------------------------------

def _dotted(node):
    """'a.b.c' for an attribute/name chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _call_base_attr(func):
    """For a call's func node, returns (base_name_or_None, attr_name)."""
    if isinstance(func, ast.Name):
        return None, func.id
    if isinstance(func, ast.Attribute):
        base = _dotted(func.value)
        return base, func.attr
    return None, None


def _is_hvd_base(model, base):
    if base is None:
        return False
    root = base.split(".")[0]
    if root in model.hvd_aliases:
        return True
    return base.startswith((HOROVOD_ROOT,) + _HVD_FALLBACK_PREFIXES)


def is_rank_call(model, node):
    """True when `node` is a call like hvd.rank() / local_rank()."""
    if not isinstance(node, ast.Call):
        return False
    base, attr = _call_base_attr(node.func)
    if attr not in RANK_FUNCS:
        return False
    if base is None:
        return attr in model.hvd_members
    return _is_hvd_base(model, base)


def expr_rank_dependent(model, node):
    """True when any subexpression derives from a per-rank value."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and is_rank_call(model, sub):
            return True
        if isinstance(sub, ast.Name) and sub.id in model.rank_vars:
            return True
    return False


def expr_nondeterministic(model, node):
    """True when the expression draws on per-process entropy (time,
    random, uuid, pid, hostname) — unusable in a collective name."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        base, attr = _call_base_attr(sub.func)
        if base is None:
            continue
        root = base.split(".")[0]
        tail = base.split(".")[-1]
        if (root, attr) in NONDET_CALLS or (tail, attr) in NONDET_CALLS:
            return True
        if root in NONDET_BASES or tail in NONDET_BASES:
            return True
    return False


def _dtype_text_is_integer(node):
    """True when a dtype-ish AST expr textually names an int/bool dtype
    (`jnp.int32`, `np.dtype('int64')`, `"int32"`, `bool_`, ...)."""
    for sub in ast.walk(node):
        text = None
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            text = sub.value
        elif isinstance(sub, ast.Attribute):
            text = sub.attr
        elif isinstance(sub, ast.Name):
            text = sub.id
        if text and (text.startswith(("int", "uint")) or
                     text.startswith("bool")):
            return True
    return False


def expr_integer_valued(model, node):
    """True when the expression provably produces an integer/bool
    tensor: an astype/dtype= naming an int dtype, an int-producing call
    (argmax/randint/...), or a variable assigned from one."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in model.int_vars:
            return True
        if not isinstance(sub, ast.Call):
            continue
        _, attr = _call_base_attr(sub.func)
        if attr == "astype" and sub.args and \
                _dtype_text_is_integer(sub.args[0]):
            return True
        if attr in INT_PRODUCING_CALLS:
            return True
        for kw in sub.keywords:
            if kw.arg == "dtype" and _dtype_text_is_integer(kw.value):
                return True
    return False


def expr_embedding_lookup(model, node):
    """True when the expression flows from an embedding-style indexed
    read (take/gather/embedding_lookup) or a variable assigned from
    one."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in model.embed_vars:
            return True
        if isinstance(sub, ast.Call):
            _, attr = _call_base_attr(sub.func)
            if attr in EMBEDDING_LOOKUP_CALLS:
                return True
    return False


def describe_expr(model, node):
    """Short source snippet for messages."""
    try:
        text = ast.get_source_segment(model.source, node)
    except Exception:  # pragma: no cover - ancient ast
        text = None
    if text is None:
        return "<expr>"
    text = " ".join(text.split())
    return text if len(text) <= 60 else text[:57] + "..."


def _unordered_iter_kind(model, node):
    """Classifies a `for` iterable: returns "set"/"dict" when iteration
    order is process-dependent (set hashing) or construction-dependent
    (dict), None when ordered. `sorted(...)` launders anything."""
    if isinstance(node, ast.Call):
        base, attr = _call_base_attr(node.func)
        if attr in ("sorted",) or (base is None and attr == "sorted"):
            return None
        if base is None and attr in ("set", "frozenset"):
            return "set"
        if attr in ("keys", "values", "items"):
            return "dict"
        if base is None and attr == "enumerate" and node.args:
            return _unordered_iter_kind(model, node.args[0])
    if isinstance(node, ast.Set):
        return "set"
    if isinstance(node, ast.Name):
        return model.unordered_vars.get(node.id)
    return None


def _target_names(target):
    names = []
    for sub in ast.walk(target):
        if isinstance(sub, ast.Name):
            names.append(sub.id)
    return names


# --- the visitor ------------------------------------------------------------

class _Walker(ast.NodeVisitor):
    def __init__(self, model):
        self.m = model
        self.conditions = []
        self.loops = []

    # imports ---------------------------------------------------------------

    def visit_Import(self, node):
        for alias in node.names:
            mod = alias.name
            if mod.split(".")[0] in (HOROVOD_ROOT,) or \
                    mod.startswith(_HVD_FALLBACK_PREFIXES):
                self.m.hvd_aliases.add(alias.asname or mod.split(".")[0])
                if ".elastic" in mod or mod.endswith("elastic"):
                    self.m.uses_elastic = True
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        mod = node.module or ""
        if mod.split(".")[0] in (HOROVOD_ROOT,) or \
                mod.startswith(_HVD_FALLBACK_PREFIXES):
            for alias in node.names:
                bound = alias.asname or alias.name
                if alias.name in COLLECTIVES or \
                        alias.name in TRAIN_MARKERS or \
                        alias.name in INITIAL_BROADCASTS or \
                        alias.name in RANK_FUNCS:
                    self.m.hvd_members.add(bound)
                else:
                    # `from horovod_tpu import jax as hvd_jax` binds a module
                    self.m.hvd_aliases.add(bound)
                if alias.name == "elastic":
                    self.m.uses_elastic = True
        self.generic_visit(node)

    # dataflow --------------------------------------------------------------

    def visit_Assign(self, node):
        self._track_assign(node.targets, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None and node.target is not None:
            self._track_assign([node.target], node.value)
        self.generic_visit(node)

    def _track_assign(self, targets, value):
        pairs = []
        for target in targets:
            if isinstance(target, ast.Name):
                pairs.append((target, value))
            elif isinstance(target, (ast.Tuple, ast.List)) and \
                    isinstance(value, (ast.Tuple, ast.List)) and \
                    len(target.elts) == len(value.elts):
                pairs.extend(zip(target.elts, value.elts))
        for tgt, val in pairs:
            if not isinstance(tgt, ast.Name):
                continue
            if expr_rank_dependent(self.m, val) or \
                    expr_nondeterministic(self.m, val):
                self.m.rank_vars.add(tgt.id)
            else:
                self.m.rank_vars.discard(tgt.id)
            kind = None
            if isinstance(val, (ast.Set, ast.SetComp)):
                kind = "set"
            elif isinstance(val, (ast.Dict, ast.DictComp)):
                kind = "dict"
            elif isinstance(val, ast.Call):
                _, attr = _call_base_attr(val.func)
                if attr in ("set", "frozenset"):
                    kind = "set"
                elif attr in ("dict",):
                    kind = "dict"
            if kind is not None:
                self.m.unordered_vars[tgt.id] = kind
            else:
                self.m.unordered_vars.pop(tgt.id, None)
            # Integer / embedding-lookup provenance (one-level, like the
            # rank_vars dataflow) for compression-on-integer-tensor.
            if expr_integer_valued(self.m, val):
                self.m.int_vars.add(tgt.id)
            else:
                self.m.int_vars.discard(tgt.id)
            if expr_embedding_lookup(self.m, val):
                self.m.embed_vars.add(tgt.id)
            else:
                self.m.embed_vars.discard(tgt.id)

    # control flow ----------------------------------------------------------

    def visit_If(self, node):
        cond = Condition(node, expr_rank_dependent(self.m, node.test),
                         describe_expr(self.m, node.test))
        self.visit(node.test)
        self.conditions.append(cond)
        for child in node.body:
            self.visit(child)
        for child in node.orelse:
            self.visit(child)
        self.conditions.pop()

    def visit_IfExp(self, node):
        cond = Condition(node, expr_rank_dependent(self.m, node.test),
                         describe_expr(self.m, node.test))
        self.visit(node.test)
        self.conditions.append(cond)
        self.visit(node.body)
        self.visit(node.orelse)
        self.conditions.pop()

    def visit_While(self, node):
        cond = Condition(node, expr_rank_dependent(self.m, node.test),
                         describe_expr(self.m, node.test))
        self.conditions.append(cond)
        self.loops.append(Loop(node))
        self.generic_visit(node)
        self.loops.pop()
        self.conditions.pop()

    def visit_For(self, node):
        kind = _unordered_iter_kind(self.m, node.iter)
        loop = Loop(node, _target_names(node.target), kind is not None, kind)
        self.visit(node.iter)
        self.loops.append(loop)
        for child in node.body:
            self.visit(child)
        for child in node.orelse:
            self.visit(child)
        self.loops.pop()

    # call sites ------------------------------------------------------------

    def visit_Call(self, node):
        func = collective_call_name(self.m, node)
        if func is not None:
            name_node = self._name_argument(node, func)
            self.m.call_sites.append(CallSite(
                node, func, func in ELASTIC_COMMITS, name_node,
                tuple(self.conditions), tuple(self.loops),
                list(node.args),
                {kw.arg: kw.value for kw in node.keywords if kw.arg}))
        self.generic_visit(node)

    def _name_argument(self, node, func):
        for kw in node.keywords:
            if kw.arg in ("name", "name_prefix"):
                return kw.value
        for pos in COLLECTIVES.get(func, ()):
            if pos < len(node.args):
                arg = node.args[pos]
                if _looks_like_name(arg):
                    return arg
        return None


def collective_call_name(model, node):
    """Canonical collective name for a Call node, or None when the call
    is not a horovod collective in `model`'s alias context. Shared by
    the lexical walker and the hvd-verify symbolic executor."""
    base, attr = _call_base_attr(node.func)
    if attr is None:
        return None
    interesting = (attr in COLLECTIVES or attr in TRAIN_MARKERS or
                   attr in INITIAL_BROADCASTS)
    if interesting:
        if base is None:
            if attr in model.hvd_members or attr in INITIAL_BROADCASTS \
                    and attr[0].isupper():
                return attr
            return None
        if _is_hvd_base(model, base):
            return attr
        return None
    # elastic commit points: state.commit()/state.sync() — only when the
    # file actually uses hvd.elastic (keeps `dict.sync()`-ish code on
    # unrelated objects out).
    if attr in ELASTIC_COMMITS and model.uses_elastic and \
            base is not None:
        return attr
    # checkpoint.save()/restore(): only when the receiver is the
    # horovod checkpoint module (`from horovod_tpu.jax import
    # checkpoint` binds it as an hvd alias; dotted access like
    # hvd.jax.checkpoint.save resolves through the alias root) —
    # bare `model.save(...)` / `state.save()` never match.
    if attr in CHECKPOINT_CALLS and base is not None and \
            (base == "checkpoint" or base.endswith(".checkpoint")) \
            and _is_hvd_base(model, base):
        return "checkpoint." + attr
    return None


def _looks_like_name(node):
    """Heuristic: positional args only count as the name when string-ish."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, str)
    if isinstance(node, ast.JoinedStr):
        return True
    if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Mod, ast.Add)):
        return _looks_like_name(node.left) or _looks_like_name(node.right)
    if isinstance(node, ast.Call):
        _, attr = _call_base_attr(node.func)
        return attr in ("format", "join", "str")
    return False


def literal_name(site):
    """The constant string value of a site's name argument, or None."""
    node = site.name_node
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def build_model(path, source):
    """Parses `source` and returns the populated Model.

    Raises SyntaxError (with filename set) when the source does not parse.
    """
    tree = ast.parse(source, filename=path)
    model = Model(path, source, tree)
    _scan_suppressions(source, model)
    _Walker(model).visit(tree)
    return model
