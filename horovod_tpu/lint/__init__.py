"""hvd-lint — collective-consistency static analysis for horovod_tpu
training scripts.

The hardest failure mode of an allreduce-negotiated framework is not a
crash but a silent hang: one rank submits a collective the others never
will. The stall inspector catches that *reactively* after a timeout; this
package catches the pattern *statically, before launch*:

* ``lint_source`` / ``lint_paths`` — library API (also used by the
  ``horovodrun_tpu --lint`` preflight and the repo's self-lint test);
* ``horovod_tpu.lint.cli`` / ``bin/hvd-lint`` — the CLI;
* rules and suppression keys are documented in docs/LINT.md, each with
  its runtime counterpart (the digest cross-check error message the same
  bug produces after launch).

Suppress a finding inline with ``# hvd-lint: disable=<rule>`` on the
offending line (or alone on the line above); bare ``disable`` silences
every rule for that line.
"""

import os

from . import checkers as _checkers  # noqa: F401  (registers rules)
from . import schedule as _schedule  # noqa: F401  (registers verify-*)
from .rules import CHECKERS, ERROR, INFO, RULES, WARNING, Finding
from .schedule import verify_paths, verify_source
from .walker import build_model

__all__ = [
    "CHECKERS", "ERROR", "Finding", "INFO", "RULES", "WARNING",
    "lint_paths", "lint_source", "verify_paths", "verify_source",
]


def lint_source(source, path="<string>", rules=None):
    """Lints one source string; returns a list of Findings (suppressions
    applied, sorted by line). A syntax error yields a single
    ``parse-error`` finding rather than raising."""
    try:
        model = build_model(path, source)
    except SyntaxError as e:
        return [Finding(path=path, line=e.lineno or 1, col=(e.offset or 0),
                        rule="parse-error", severity=ERROR,
                        message="could not parse: %s" % e.msg,
                        end_line=e.lineno or 1)]
    findings = []
    for rule_id, checker in CHECKERS.items():
        if rules is not None and rule_id not in rules:
            continue
        for finding in checker(model):
            if not model.is_suppressed(finding.line, finding.rule,
                                       finding.end_line):
                findings.append(finding)
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def iter_python_files(paths):
    """Expands files/directories into .py files (dirs walked recursively,
    sorted for stable output)."""
    for path in paths:
        if os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs.sort()
                for fname in sorted(files):
                    if fname.endswith(".py"):
                        yield os.path.join(root, fname)
        else:
            yield path


def lint_paths(paths, rules=None):
    """Lints files/directories; returns (findings, files_checked)."""
    findings = []
    files_checked = 0
    for fpath in iter_python_files(paths):
        try:
            with open(fpath, "r", encoding="utf-8", errors="replace") as fh:
                source = fh.read()
        except OSError as e:
            findings.append(Finding(
                path=fpath, line=1, col=1, rule="io-error", severity=ERROR,
                message="cannot read: %s" % e, end_line=1))
            continue
        files_checked += 1
        findings.extend(lint_source(source, path=fpath, rules=rules))
    return findings, files_checked
