"""Per-rule checkers. Each consumes the walker's Model and yields Findings.

Rule ids are the stable suppression keys; docs/LINT.md documents each with
a minimal failing example and the runtime error it corresponds to.
"""

import collections

from . import walker
from .rules import ERROR, WARNING, make_finding, register
from .walker import (COLLECTIVES, INITIAL_BROADCASTS, PREFIX_NAMED,
                     TRAIN_MARKERS, describe_expr, expr_embedding_lookup,
                     expr_integer_valued, expr_nondeterministic,
                     expr_rank_dependent, literal_name)


@register("rank-conditional-collective", ERROR,
          "collective reachable only under rank-dependent control flow")
def check_rank_conditional(model):
    import ast as _ast
    for site in model.call_sites:
        if site.func in TRAIN_MARKERS and site.func != "allreduce_gradients":
            continue  # wrapping an optimizer is not itself a collective
        if site.func.startswith("checkpoint."):
            continue  # owned by checkpoint-in-rank-guard below
        # Group-scoped calls (docs/GROUPS.md): a collective passed
        # `group=` is SUPPOSED to run on a rank subset — "only members
        # call it" is the contract, and membership guards are
        # rank-dependent by nature (`if g.rank() >= 0:`). Whether the
        # guard matches the membership is undecidable statically; the
        # runtime's group-scoped divergence detection names the group
        # and both call sites when it does not, so the lexical rule
        # stands down instead of flagging every legitimate mesh program.
        group_arg = site.kwargs.get("group")
        if group_arg is not None and not (
                isinstance(group_arg, _ast.Constant) and
                group_arg.value is None):
            continue
        for cond in site.conditions:
            if cond.rank_dependent:
                kind = "elastic commit point" if site.is_commit \
                    else "collective"
                yield make_finding(
                    model, site.node, "rank-conditional-collective",
                    "%s `%s` is only reachable under the rank-dependent "
                    "condition `%s`; ranks that skip this branch never "
                    "submit it and the job hangs in negotiation "
                    "(runtime: divergence cross-check / stall inspector)"
                    % (kind, site.func, cond.source))
                break


@register("checkpoint-in-rank-guard", ERROR,
          "hvd checkpoint save/restore guarded by a rank condition")
def check_checkpoint_rank_guard(model):
    """``hvd.jax.checkpoint.save()``/``restore()`` CONTAIN collectives
    (the root broadcasts a success flag — the torn-save deadlock fix —
    and restore broadcasts the values), so the classic
    ``if hvd.rank() == 0: checkpoint.save(...)`` guard deadlocks: rank 0
    waits in the flag broadcast for peers that never entered the call.
    The API already rank-splits internally — call it from EVERY rank."""
    for site in model.call_sites:
        if not site.func.startswith("checkpoint."):
            continue
        for cond in site.conditions:
            if cond.rank_dependent:
                yield make_finding(
                    model, site.node, "checkpoint-in-rank-guard",
                    "`%s` is only reachable under the rank-dependent "
                    "condition `%s`, but it contains collectives (the "
                    "success-flag broadcast and the restore value "
                    "broadcast) — ranks skipping this branch never "
                    "join them and the job deadlocks. The call already "
                    "no-ops filesystem work off the root rank; invoke "
                    "it unconditionally on every rank"
                    % (site.func, cond.source))
                break


def _compression_mode_requested(site):
    """The site's compression= expression when it selects a LOSSY wire
    mode, else None. 'none'/Compression.none/None literals are clean;
    anything else (strings, Compression attrs, variables) counts — a
    dynamic mode may be lossy, and the cost of a false negative is
    silent corruption."""
    import ast
    node = site.kwargs.get("compression")
    if node is None:
        return None
    if isinstance(node, ast.Constant) and \
            node.value in (None, "none", "", 0):
        return None
    if isinstance(node, ast.Attribute) and node.attr == "none":
        return None
    return node


@register("compression-on-integer-tensor", ERROR,
          "lossy gradient compression applied to an integer or "
          "embedding-lookup tensor")
def check_compression_on_integer_tensor(model):
    """bf16/int8 wire compression quantizes: an integer tensor (ids,
    counts, masks, argmax results) decodes to DIFFERENT integers, and
    embedding-lookup rows have per-block magnitude spreads that
    quantization flattens — both corrupt silently (the run completes,
    the numbers are wrong). The native core degrades non-f32 dtypes to
    'none' at enqueue as a backstop, but int ids cast to f32 (or
    embedding gradients) sail through — flag them at the call site."""
    for site in model.call_sites:
        comp_node = _compression_mode_requested(site)
        if comp_node is None:
            continue
        # The tensor argument: positional 0 for the tensor-taking
        # collectives, grads= / positional 0 for allreduce_gradients.
        tensor_node = None
        if site.args:
            tensor_node = site.args[0]
        for kw_name in ("tensor", "grads"):
            if kw_name in site.kwargs:
                tensor_node = site.kwargs[kw_name]
        if tensor_node is None:
            continue
        comp_text = describe_expr(model, comp_node)
        if expr_integer_valued(model, tensor_node):
            yield make_finding(
                model, site.node, "compression-on-integer-tensor",
                "`%s` applies lossy compression `%s` to the integer "
                "tensor `%s`: quantize/dequantize returns DIFFERENT "
                "integers (ids, counts and masks corrupt silently — the "
                "job keeps running on wrong values). Pass "
                "compression='none' here (an explicit none overrides "
                "HVD_TPU_COMPRESSION; merely deleting the argument "
                "falls back to the env default), or keep the tensor in "
                "its integer dtype so the core's dtype filter rides it "
                "uncompressed"
                % (site.func, comp_text, describe_expr(model, tensor_node)))
        elif expr_embedding_lookup(model, tensor_node):
            yield make_finding(
                model, site.node, "compression-on-integer-tensor",
                "`%s` applies lossy compression `%s` to embedding-lookup "
                "data `%s`: looked-up rows (and their sparse gradients) "
                "mix near-zero and hot rows in one quantization block, "
                "exactly where block-scaled int8 loses the small values; "
                "use compression='none' for embedding planes "
                "(hvd.jax.sparse already ships indices+values compactly)"
                % (site.func, comp_text, describe_expr(model, tensor_node)),
                severity=WARNING)


@register("sharded-update-rank-local-param-read", ERROR,
          "optimizer state read directly under sharded_update (the "
          "state is a rank-local 1/N shard)")
def check_sharded_rank_local_param_read(model):
    """Under ``DistributedOptimizer(sharded_update=True)`` the
    optimizer state holds moments for THIS RANK'S 1/N shard only
    (docs/ZERO.md): the torch wrapper's ``.state`` is empty by design
    (the real moments live on an inner flat-shard optimizer), and the
    jax state dict's ``["inner"]`` leaves are shard-length arrays.
    Reading them as if they were global silently processes 1/N of the
    elements — on every rank, each a DIFFERENT 1/N. Materialize the
    world-independent full form first via ``sharded_state_full()`` (a
    collective — call it on every rank at the same point)."""
    import ast

    # Pass 1: variables bound to a sharded DistributedOptimizer. Like
    # the compression rule, anything but an explicitly-falsy constant
    # counts (a dynamic sharded_update= may be True, and the cost of a
    # false negative is a silent 1/N read).
    sharded_opts = set()
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        base, attr = walker._call_base_attr(node.value.func)
        if attr != "DistributedOptimizer":
            continue
        if base is not None and not walker._is_hvd_base(model, base):
            continue
        su = next((kw.value for kw in node.value.keywords
                   if kw.arg == "sharded_update"), None)
        if su is None or (isinstance(su, ast.Constant)
                          and not su.value):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                sharded_opts.add(tgt.id)
    if not sharded_opts:
        return

    # Pass 2: state variables produced by the sharded optimizer —
    # `s = opt.init(...)` and the `u, s = opt.update(...)` re-binding.
    state_vars = set()
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        base, attr = walker._call_base_attr(node.value.func)
        if base not in sharded_opts:
            continue
        if attr == "init":
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    state_vars.add(tgt.id)
        elif attr == "update":
            for tgt in node.targets:
                if isinstance(tgt, ast.Tuple) and len(tgt.elts) == 2 \
                        and isinstance(tgt.elts[1], ast.Name):
                    state_vars.add(tgt.elts[1].id)

    # Pass 3: flag the rank-local reads.
    for node in ast.walk(model.tree):
        if isinstance(node, ast.Attribute) and node.attr == "state" and \
                isinstance(node.value, ast.Name) and \
                node.value.id in sharded_opts and \
                isinstance(node.ctx, ast.Load):
            yield make_finding(
                model, node, "sharded-update-rank-local-param-read",
                "`%s.state` is read under sharded_update: the wrapper's "
                "state dict is EMPTY by design — momentum/Adam moments "
                "live on an inner optimizer over this rank's 1/N flat "
                "shard, so any value found here covers a different 1/N "
                "on every rank. Materialize the full state with "
                "sharded_state_full() (a collective) before reading "
                "moments" % node.value.id)
        elif isinstance(node, ast.Subscript) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in state_vars:
            sl = node.slice
            if isinstance(sl, ast.Constant) and sl.value == "inner":
                yield make_finding(
                    model, node, "sharded-update-rank-local-param-read",
                    "`%s[\"inner\"]` reads the sharded optimizer state "
                    "directly: its array leaves are THIS RANK'S 1/N "
                    "shard of each moment, not the full tensor — every "
                    "rank sees a different slice. Pass the whole state "
                    "through sharded_state_full() (collective, "
                    "world-size independent) and read the full form "
                    "instead" % node.value.id)


@register("missing-initial-broadcast", WARNING,
          "gradient averaging without an initial parameter broadcast")
def check_missing_initial_broadcast(model):
    markers = [s for s in model.call_sites if s.func in TRAIN_MARKERS]
    if not markers:
        return
    if any(s.func in INITIAL_BROADCASTS for s in model.call_sites):
        return
    site = markers[0]
    yield make_finding(
        model, site.node, "missing-initial-broadcast",
        "`%s` is used but no initial broadcast_parameters / "
        "broadcast_optimizer_state (or BroadcastGlobalVariables hook/"
        "callback) is reachable: ranks start averaging gradients from "
        "different initial weights and silently train unsynchronized"
        % site.func)


# torch BN module constructors whose instances carry running-stat
# BUFFERS (state_dict()-visible, parameters()-invisible).
_TORCH_BN_CTORS = {"BatchNorm1d", "BatchNorm2d", "BatchNorm3d",
                   "SyncBatchNorm"}
# Broadcast-argument call names that cover torch BN buffers.
_TORCH_BUFFER_SOURCES = {"state_dict", "named_buffers", "buffers"}


@register("missing-bn-stats-broadcast", WARNING,
          "mutable BN state trained without broadcasting/syncing the "
          "running statistics")
def check_missing_bn_stats_broadcast(model):
    """The mutable-BN-state extension of ``missing-initial-broadcast``:
    a model carrying BatchNorm RUNNING STATISTICS (a flax
    ``batch_stats`` collection, or torch BN buffers) trained under a
    gradient-averaging wrapper updates those stats PER RANK from
    per-rank batches — they are never averaged by the gradient
    allreduce, so ranks silently diverge and evaluation results depend
    on which rank you ask. Unlike weights (where the initial broadcast
    plus synchronized updates keep ranks identical), BN stats need
    either an explicit broadcast/sync of the stats collection or
    cross-replica (sync) BN. A plain ``broadcast_parameters(params)``
    does NOT cover them: flax keeps them in a separate collection, and
    torch's ``model.parameters()`` excludes buffers —
    ``state_dict()`` includes them."""
    import ast as _ast

    markers = [s for s in model.call_sites if s.func in TRAIN_MARKERS]
    if not markers:
        return
    flax_bn = any(isinstance(n, _ast.Constant) and n.value == "batch_stats"
                  for n in _ast.walk(model.tree))
    torch_bn = False
    for n in _ast.walk(model.tree):
        if isinstance(n, _ast.Call):
            _, attr = walker._call_base_attr(n.func)
            if attr in _TORCH_BN_CTORS:
                torch_bn = True
            # Sync BN satisfies: statistics are reduced across replicas
            # inside the step, so every rank holds identical stats by
            # construction (axis_name=/sync_group= on a *Norm module,
            # or a model's bn_axis_name=/bn_sync_group=).
            norm_ctor = attr is not None and "Norm" in attr
            for kw in n.keywords:
                sync_arg = (norm_ctor and
                            kw.arg in ("axis_name", "sync_group")) or \
                    kw.arg in ("bn_axis_name", "bn_sync_group")
                if sync_arg and not (isinstance(kw.value, _ast.Constant)
                                     and kw.value.value is None):
                    return
    if not flax_bn and not torch_bn:
        return

    # Variables known to hold the FULL flax variables dict (something
    # subscripted with "batch_stats" elsewhere): broadcasting one of
    # those covers the stats.
    vars_with_stats = set()
    for n in _ast.walk(model.tree):
        if isinstance(n, _ast.Subscript) and \
                isinstance(n.value, _ast.Name) and \
                isinstance(n.slice, _ast.Constant) and \
                n.slice.value == "batch_stats":
            vars_with_stats.add(n.value.id)

    def covers_stats(arg):
        if isinstance(arg, _ast.Name) and arg.id in vars_with_stats:
            return True
        for sub in _ast.walk(arg):
            if isinstance(sub, _ast.Constant) and \
                    sub.value == "batch_stats":
                return True
            if isinstance(sub, _ast.Call):
                _, attr = walker._call_base_attr(sub.func)
                if attr in _TORCH_BUFFER_SOURCES:
                    return True
        return False

    for site in model.call_sites:
        if site.func not in INITIAL_BROADCASTS:
            continue
        if site.func in ("BroadcastGlobalVariablesHook",
                         "BroadcastGlobalVariablesCallback",
                         "broadcast_global_variables"):
            return  # TF globals include the moving-average variables
        for arg in list(site.args) + list(site.kwargs.values()):
            if covers_stats(arg):
                return

    kind = "flax `batch_stats` collection" if flax_bn else \
        "torch BatchNorm buffers (running_mean/running_var)"
    yield make_finding(
        model, markers[0].node, "missing-bn-stats-broadcast",
        "`%s` trains a model carrying mutable BN state (%s) but nothing "
        "broadcasts or syncs those running statistics: each rank "
        "updates them from its OWN batches, so they silently diverge — "
        "training looks healthy (gradients are averaged) and eval "
        "results differ per rank. Broadcast the stats collection "
        "alongside the params (flax: broadcast_parameters(variables["
        "\"batch_stats\"]); torch: broadcast_parameters(model."
        "state_dict()) — parameters() excludes buffers), periodically "
        "re-sync before eval, or use sync BN (axis_name=/sync_group=), "
        "which keeps every rank's statistics identical by construction"
        % (markers[0].func, kind))


@register("unordered-name-iteration", ERROR,
          "collective name derived from unordered set/dict iteration")
def check_unordered_iteration(model):
    for site in model.call_sites:
        loop = _unordered_loop_feeding_name(site)
        if loop is None:
            continue
        if loop.unordered_kind == "set":
            yield make_finding(
                model, site.node, "unordered-name-iteration",
                "collective `%s` named from iteration over a set: set "
                "order depends on per-process string hashing "
                "(PYTHONHASHSEED), so ranks negotiate names in different "
                "orders and deadlock; iterate `sorted(...)` instead"
                % site.func)
        else:
            yield make_finding(
                model, site.node, "unordered-name-iteration",
                "collective `%s` named from dict iteration: dict order "
                "follows insertion order, which silently diverges across "
                "ranks when the dicts were built differently; iterate "
                "`sorted(...)` to make the negotiation order explicit"
                % site.func, severity=WARNING)


def _unordered_loop_feeding_name(site):
    """The innermost unordered enclosing loop whose target feeds the
    site's name (or an auto-generated name), else None."""
    for loop in reversed(site.loops):
        if not loop.unordered:
            continue
        if site.name_node is None:
            return loop
        import ast
        for sub in ast.walk(site.name_node):
            if isinstance(sub, ast.Name) and sub.id in loop.target_names:
                return loop
    return None


@register("rank-dependent-name", ERROR,
          "collective name derived from rank / host / time / random")
def check_rank_dependent_name(model):
    for site in model.call_sites:
        if site.name_node is None:
            continue
        if expr_rank_dependent(model, site.name_node):
            yield make_finding(
                model, site.node, "rank-dependent-name",
                "collective `%s` name `%s` depends on a per-rank value "
                "(rank/local_rank/cross_rank/local_size): every rank "
                "negotiates a different tensor name, so no name ever "
                "completes and the job hangs"
                % (site.func, describe_expr(model, site.name_node)))
        elif expr_nondeterministic(model, site.name_node):
            yield make_finding(
                model, site.node, "rank-dependent-name",
                "collective `%s` name `%s` draws on per-process entropy "
                "(time/random/uuid/pid/hostname): ranks cannot agree on "
                "the name and the negotiation never matches"
                % (site.func, describe_expr(model, site.name_node)))


@register("loop-auto-name", WARNING,
          "auto-named collective inside a loop")
def check_loop_auto_name(model):
    for site in model.call_sites:
        if site.func in PREFIX_NAMED or site.func in TRAIN_MARKERS or \
                site.is_commit or site.func in INITIAL_BROADCASTS:
            continue
        if site.func not in COLLECTIVES:
            continue
        if site.name_node is not None or not site.loops:
            continue
        yield make_finding(
            model, site.node, "loop-auto-name",
            "collective `%s` inside a loop without an explicit name=: "
            "every iteration auto-generates a fresh name, so the response "
            "cache grows without bound and never hits, and after an "
            "elastic restart surviving and fresh ranks disagree on the "
            "counter; pass a name stable across iterations (include the "
            "step only if each step's tensor is distinct)" % site.func)


@register("duplicate-collective-name", WARNING,
          "one literal name used by several collective call sites")
def check_duplicate_name(model):
    by_name = _sites_by_literal_name(model)
    for name, sites in sorted(by_name.items()):
        if len(sites) < 2:
            continue
        if _attrs_mismatch(sites):
            continue  # escalated by name-attr-mismatch instead
        first = sites[0]
        for site in sites[1:]:
            yield make_finding(
                model, site.node, "duplicate-collective-name",
                "collective name '%s' is also used at line %d: distinct "
                "call sites sharing one name alias the same response-"
                "cache entry and negotiate as the same tensor; make the "
                "names unique" % (name, first.node.lineno))


@register("name-attr-mismatch", ERROR,
          "call sites sharing a name disagree on op/average/root")
def check_name_attr_mismatch(model):
    by_name = _sites_by_literal_name(model)
    for name, sites in sorted(by_name.items()):
        if len(sites) < 2 or not _attrs_mismatch(sites):
            continue
        kinds = sorted({_op_kind(s) for s in sites})
        averages = sorted({repr(_average_literal(s)) for s in sites
                           if _average_literal(s) is not None})
        detail = []
        if len(kinds) > 1:
            detail.append("ops %s" % "/".join(kinds))
        if len(averages) > 1:
            detail.append("average= values %s" % "/".join(averages))
        yield make_finding(
            model, sites[1].node, "name-attr-mismatch",
            "collective name '%s' is used with mismatched %s across call "
            "sites (first at line %d): whichever rank reaches the other "
            "site negotiates incompatible metadata for the same tensor "
            "name and the coordinator rejects or mis-caches it"
            % (name, " and ".join(detail), sites[0].node.lineno))


def _sites_by_literal_name(model):
    by_name = collections.OrderedDict()
    for site in model.call_sites:
        if site.func in TRAIN_MARKERS and site.func != "allreduce_gradients":
            continue
        name = literal_name(site)
        if name is None:
            continue
        by_name.setdefault(name, []).append(site)
    return by_name


def _op_kind(site):
    f = site.func
    for kind in ("allreduce", "allgather", "broadcast", "alltoall"):
        if f.startswith(kind) or f == "metric_average" and kind == "allreduce":
            return kind
    return f


def _average_literal(site):
    """The site's explicit average= literal, or None when absent/dynamic.

    An absent average= is NOT resolved to a default: the default differs
    by layer (the framework bindings average, the host-ops layer sums),
    so guessing would flag two identical default-calls as mismatched.
    Only explicit, differing literals count as evidence."""
    import ast
    node = site.kwargs.get("average")
    if isinstance(node, ast.Constant):
        return node.value
    return None


def _attrs_mismatch(sites):
    if len({_op_kind(s) for s in sites}) > 1:
        return True
    averages = {repr(_average_literal(s)) for s in sites
                if _average_literal(s) is not None}
    return len(averages) > 1


def _handler_classes(tree):
    """ClassDefs deriving (lexically) from an http.server request
    handler — the repo's serving front-door idiom (serve/server.py,
    _metrics.py). Nested classes count: the handler-factory pattern
    (`def _make_handler(ctx): class Handler(BaseHTTPRequestHandler)`)
    is the idiomatic way to close over replica state."""
    import ast

    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for base in node.bases:
            dotted = walker._dotted(base)
            if dotted and dotted.split(".")[-1].endswith(
                    "HTTPRequestHandler"):
                yield node
                break


def _reach_collective(model, start, methods, module_funcs, limit=40):
    """Bounded intra-module reachability: DFS from `start` through
    plain-name calls (module functions) and self.method calls (same
    class), returning (collective call node, collective name, chain of
    function names) for the first collective found, else None."""
    import ast

    seen = set()
    stack = [(start, (start.name,))]
    visited = 0
    while stack and visited < limit:
        func_node, chain = stack.pop()
        if id(func_node) in seen:
            continue
        seen.add(id(func_node))
        visited += 1
        for node in ast.walk(func_node):
            if not isinstance(node, ast.Call):
                continue
            name = walker.collective_call_name(model, node)
            if name is not None:
                return node, name, chain
            callee = None
            if isinstance(node.func, ast.Name):
                callee = module_funcs.get(node.func.id)
            elif (isinstance(node.func, ast.Attribute)
                  and isinstance(node.func.value, ast.Name)
                  and node.func.value.id == "self"):
                callee = methods.get(node.func.attr)
            if callee is not None and id(callee) not in seen:
                stack.append((callee, chain + (callee.name,)))
    return None


@register("collective-in-serve-handler", ERROR,
          "collective reachable from an HTTP request handler")
def check_collective_in_serve_handler(model):
    """A serve replica is a SINGLE process outside any rendezvous
    generation: a collective submitted from a request handler thread
    waits forever for peers that will never negotiate — the handler
    thread hangs holding its request, the client times out, and every
    retry stacks another hung thread (runtime: negotiation stall, but
    only visible on the SERVING plane where no stall inspector runs).
    Handlers must stay collective-free: inference state arrives via the
    weight-swap watcher, never via broadcast (docs/SERVE.md)."""
    import ast

    module_funcs = {
        n.name: n for n in model.tree.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for cls in _handler_classes(model.tree):
        methods = {n.name: n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        for name, meth in sorted(methods.items()):
            if not (name.startswith("do_")
                    or name in ("handle", "handle_one_request")):
                continue
            hit = _reach_collective(model, meth, methods, module_funcs)
            if hit is None:
                continue
            node, coll, chain = hit
            via = (" (via %s)" % " -> ".join(chain)
                   if len(chain) > 1 else "")
            yield make_finding(
                model, node, "collective-in-serve-handler",
                "collective `%s` is reachable from request handler "
                "`%s.%s`%s; a serve replica has no peers in a "
                "rendezvous generation, so the call never completes — "
                "the handler thread hangs with the request and every "
                "client retry stacks another. Move collective work off "
                "the serving plane (weights arrive via the swap "
                "watcher)" % (coll, cls.name, name, via))


def _self_attr_name(node):
    import ast
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}


def _class_has_lock(methods):
    """True when any method stores a threading lock/condition on self —
    the class has a locking discipline, and whether each access holds
    it is beyond a lexical pass (that is the native audit's job; in
    Python we stand down rather than flag disciplined code)."""
    import ast
    for meth in methods.values():
        for node in ast.walk(meth):
            if not isinstance(node, ast.Assign):
                continue
            if not any(_self_attr_name(t) for t in node.targets):
                continue
            call = node.value
            if not isinstance(call, ast.Call):
                continue
            fn = call.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if name in _LOCK_FACTORIES:
                return True
    return False


def _thread_entry_methods(methods):
    """Method names handed to `threading.Thread(target=self.m)` anywhere
    in the class, plus everything transitively reachable from them via
    `self.helper()` calls — the full set of code the spawned thread can
    run."""
    import ast
    entries = set()
    for meth in methods.values():
        for node in ast.walk(meth):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if name != "Thread":
                continue
            for kw in node.keywords:
                if kw.arg == "target":
                    tgt = _self_attr_name(kw.value)
                    if tgt and tgt in methods:
                        entries.add(tgt)
    # transitive closure over self-method calls
    frontier = list(entries)
    while frontier:
        meth = methods.get(frontier.pop())
        if meth is None:
            continue
        for node in ast.walk(meth):
            if isinstance(node, ast.Call):
                callee = _self_attr_name(node.func)
                if (callee in methods and callee not in entries):
                    entries.add(callee)
                    frontier.append(callee)
    return entries


def _attr_mutations(meth):
    """{attr: first mutating node} for self-attribute stores, skipping
    plain constant assigns (`self._stop = True` is a GIL-atomic flag —
    the benign signaling idiom); `+=`-style read-modify-write is never
    atomic and always counts."""
    import ast
    out = {}
    for node in ast.walk(meth):
        if isinstance(node, ast.AugAssign):
            attr = _self_attr_name(node.target)
            if attr:
                out.setdefault(attr, node)
        elif isinstance(node, ast.Assign):
            if isinstance(node.value, ast.Constant):
                continue
            for t in node.targets:
                attr = _self_attr_name(t)
                if attr:
                    out.setdefault(attr, node)
    return out


def _attr_references(meth):
    import ast
    out = set()
    for node in ast.walk(meth):
        attr = _self_attr_name(node)
        if attr:
            out.add(attr)
    return out


@register("thread-shared-mutable-without-lock", WARNING,
          "attribute shared between a spawned thread and the rest of "
          "its class with no lock anywhere in the class")
def check_thread_shared_mutable(model):
    """A class that spawns `threading.Thread(target=self.m)` and
    mutates `self.x` on one side while the other side reads or writes
    it — with NO threading.Lock/RLock/Condition attribute anywhere in
    the class — is relying on the GIL making compound operations look
    atomic. It does not: `self.n += 1` is a read-modify-write that
    loses updates under preemption, and a non-constant assign can
    publish a half-built object to a reader between bytecodes. Plain
    constant flags (`self._stop = True`) are the one idiomatic
    exception and are not flagged. WARNING, not ERROR: the pattern is
    sometimes externally serialized (e.g. the thread only runs while
    the caller is parked in join()) — suppress those with an inline
    `# hvd-lint: disable=thread-shared-mutable-without-lock` naming
    the serialization."""
    import ast

    for cls in ast.walk(model.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods = {n.name: n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        if not methods or _class_has_lock(methods):
            continue
        thread_names = _thread_entry_methods(methods)
        if not thread_names:
            continue
        main_names = [n for n in methods
                      if n not in thread_names and n != "__init__"]
        thread_muts = {}
        thread_refs = set()
        for n in sorted(thread_names):
            for attr, node in _attr_mutations(methods[n]).items():
                thread_muts.setdefault(attr, (n, node))
            thread_refs |= _attr_references(methods[n])
        main_muts = {}
        main_refs = set()
        for n in sorted(main_names):
            for attr, node in _attr_mutations(methods[n]).items():
                main_muts.setdefault(attr, (n, node))
            main_refs |= _attr_references(methods[n])

        hit = []
        for attr, (meth, node) in sorted(thread_muts.items()):
            if attr in main_refs:
                hit.append((attr, meth, node, "thread", "the class"))
        for attr, (meth, node) in sorted(main_muts.items()):
            if attr in thread_refs and attr not in thread_muts:
                hit.append((attr, meth, node, "main", "the thread"))
        for attr, meth, node, side, other in hit:
            yield make_finding(
                model, node, "thread-shared-mutable-without-lock",
                "`self.%s` is mutated in `%s.%s` (the %s side) and "
                "touched from %s, but %s has no Lock/RLock/Condition "
                "attribute at all — a `+=` or compound update here "
                "loses writes under preemption; guard the attribute "
                "with a threading.Lock, hand values over via "
                "queue.Queue, or reduce the shared state to a "
                "constant flag"
                % (attr, cls.name, meth, side, other, cls.name))
