"""Interprocedural program model for hvd-verify (docs/LINT.md).

The per-call-site rules in checkers.py are deliberately lexical: they
see one file and one statement at a time. The schedule verifier needs
more — a helper function that issues a collective from a rank-dependent
branch three calls deep is invisible lexically — so this module builds
the minimal whole-program view the symbolic executor consumes:

* the ENTRY module (the user's training script, ``__name__`` bound to
  ``"__main__"``), parsed with the same walker Model the lexical rules
  use (import-alias resolution, suppression table);
* its LOCAL imports, resolved on disk relative to the entry script's
  directory (``import helpers`` / ``from helpers import reduce_all``
  where ``helpers.py`` or ``helpers/__init__.py`` sits next to the
  script) — third-party and stdlib imports stay opaque;
* a function table per module (top-level ``def``s, including decorated
  and async ones) for bounded inlining.

Everything is bounded: at most ``MAX_MODULES`` local modules load, and
unresolvable imports degrade to unknown values instead of erroring —
the verifier proves what it can see and says nothing about the rest.
"""

import ast
import os

from .walker import build_model

# Local-import budget: a training script's helper closure is a handful
# of files; hitting this bound means we wandered into a vendored tree.
MAX_MODULES = 64


class FunctionInfo(object):
    """One inlinable function: its def node plus the module it lives in
    (the module supplies alias context and the file path for chains)."""

    __slots__ = ("name", "node", "module")

    def __init__(self, name, node, module):
        self.name = name
        self.node = node
        self.module = module

    def __repr__(self):  # pragma: no cover - debug aid
        return "<FunctionInfo %s at %s:%d>" % (
            self.name, self.module.path, self.node.lineno)


class ModuleInfo(object):
    """One parsed module: tree + walker Model + top-level function and
    class tables + the on-disk directory its own imports resolve in."""

    def __init__(self, path, source, model, run_name):
        self.path = path
        self.source = source
        self.model = model          # walker Model (aliases, suppressions)
        self.tree = model.tree
        self.run_name = run_name    # value of __name__ when executed
        self.functions = {}         # top-level name -> FunctionInfo
        self.classes = {}           # top-level name -> ClassDef node
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = FunctionInfo(
                    node.name, node, self)
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node

    @property
    def directory(self):
        return os.path.dirname(os.path.abspath(self.path))


class ProgramGraph(object):
    """The entry module plus every local module reachable from it.

    ``load_local(directory, modname)`` is the single resolution point:
    it maps a dotted module name to a file under ``directory`` and
    parses it once (modules are cached by real path, so diamond imports
    share one ModuleInfo and one symbolic top-level execution).
    """

    def __init__(self, entry_path, source=None):
        self.modules = {}           # realpath -> ModuleInfo
        self.entry = self._load(entry_path, source=source,
                                run_name="__main__")

    def _load(self, path, source=None, run_name=None):
        real = os.path.realpath(path)
        cached = self.modules.get(real)
        if cached is not None:
            return cached
        if len(self.modules) >= MAX_MODULES:
            return None
        if source is None:
            with open(path, "r", encoding="utf-8", errors="replace") as fh:
                source = fh.read()
        # SyntaxError propagates to the caller: the entry file's parse
        # error becomes the standard parse-error finding; a helper's
        # parse error degrades that import to unknown.
        model = build_model(path, source)
        if run_name is None:
            run_name = os.path.splitext(os.path.basename(path))[0]
        info = ModuleInfo(path, source, model, run_name)
        self.modules[real] = info
        return info

    def load_local(self, directory, modname):
        """ModuleInfo for ``modname`` (dotted) resolved under
        ``directory``, or None when it is not a local file (third-party,
        stdlib, or the horovod_tpu package itself — the verifier models
        the framework natively rather than tracing its internals)."""
        root = modname.split(".")[0]
        if root in ("horovod_tpu", "horovod"):
            return None
        parts = modname.split(".")
        candidates = (
            os.path.join(directory, *parts) + ".py",
            os.path.join(directory, *parts, "__init__.py"),
        )
        for cand in candidates:
            if os.path.isfile(cand):
                try:
                    return self._load(cand)
                except (SyntaxError, OSError):
                    return None
        return None
