"""Rule registry for hvd-lint.

Every rule is a cross-rank divergence hazard class: a static pattern that
can make one rank submit a collective the others never will (silent hang —
the failure mode the stall inspector and the runtime digest cross-check
catch only *after* launch; see docs/LINT.md for the mapping between each
rule and its runtime error message).
"""

import collections

# Severities, ordered weakest to strongest.
INFO = "info"
WARNING = "warning"
ERROR = "error"

_SEVERITY_ORDER = {INFO: 0, WARNING: 1, ERROR: 2}


def severity_at_least(severity, floor):
    return _SEVERITY_ORDER[severity] >= _SEVERITY_ORDER[floor]


Rule = collections.namedtuple("Rule", ["id", "default_severity", "summary"])

# Registry: rule id -> Rule. Checkers register themselves in checkers.py;
# the ids here are the public, stable suppression keys
# (`# hvd-lint: disable=<id>`).
RULES = collections.OrderedDict()
# rule id -> checker callable(Model) -> iterable of Finding.
CHECKERS = {}


def register(rule_id, default_severity, summary):
    """Decorator: registers `fn(model)` as the checker for `rule_id`."""
    RULES[rule_id] = Rule(rule_id, default_severity, summary)

    def deco(fn):
        CHECKERS[rule_id] = fn
        return fn

    return deco


def register_meta(rule_id, default_severity, summary):
    """Registers a rule id WITHOUT a per-model checker — the hvd-verify
    schedule analyses run over the whole program, not one Model, but
    their ids still live in the registry so `--disable`, `--list-rules`
    and inline suppressions treat them like any other rule."""
    RULES[rule_id] = Rule(rule_id, default_severity, summary)


# `end_line` exists so suppression comments work on multi-line statements
# (a trailing `# hvd-lint: disable=...` on the closing line of a wrapped
# call must suppress the finding anchored at its first line).
Finding = collections.namedtuple(
    "Finding", ["path", "line", "col", "rule", "severity", "message",
                "end_line"])


def make_finding(model, node, rule_id, message, severity=None):
    line = getattr(node, "lineno", 1)
    return Finding(
        path=model.path,
        line=line,
        col=getattr(node, "col_offset", 0) + 1,
        rule=rule_id,
        severity=severity or RULES[rule_id].default_severity,
        message=message,
        end_line=getattr(node, "end_lineno", None) or line,
    )
