"""Pipeline parallelism (GPipe-style) over homogeneous stages.

No reference analogue (the reference only moves gradients). Stages are
groups of identical transformer blocks whose stacked parameters are
sharded over a ``pp`` mesh axis; activations flow stage-to-stage via
``lax.ppermute`` while a microbatch schedule keeps every stage busy:
at schedule step t, stage d processes microbatch t - d (devices run
the same ``lax.scan``; out-of-range steps compute on don't-care data
and are masked at collection). Forward-only latency is
(M + P - 1) stage-times for M microbatches on P stages — the standard
GPipe fill/drain. Autodiff flows through the scan + ppermute, so the
same schedule trains (activations for the backward are scan
residuals; wrap `stage_fn` in ``jax.checkpoint`` for O(stages)
memory).

Usage (see tests/test_pipeline.py): embed on every device, pipeline
the blocks, then norm/head on every device — stages must be
structurally identical, so the embedding/head live OUTSIDE the
pipelined region.

Training INSIDE shard_map (a local loss differentiated per rank): the
output collection below is a psum whose transpose SUMS the pp ranks'
identical loss cotangents, so pipeline-internal cotangents arrive
pp-fold. The gradient contract (pinned by
tests/test_pipeline.py::test_pipeline_inprocess_grad_sync_contract):
scale the local loss by ``1/psum(1, pp_axis)``; then staged block
grads are complete as-is and every NON-staged param (embed before the
pipeline, norm/head after) needs a ``psum`` over the pp axis.
"""

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn, stage_params, x_microbatches, pp_axis,
                   remat=False):
    """Runs sequence-of-stages over microbatches inside shard_map.

    Args:
      stage_fn: ``stage_fn(local_stage_params, x) -> y`` with x and y
        the SAME shape (one pipeline stage; typically a scan over the
        stage's transformer blocks).
      stage_params: the calling shard's stage parameters (placed with
        a leading stage dim sharded over `pp_axis`, squeezed by the
        caller or consumed as-is by stage_fn).
      x_microbatches: [M, ...] microbatched input, replicated across
        the pp axis (only stage 0 reads it).
      pp_axis: mesh axis name the stages are sharded over.
      remat: wrap the stage in ``jax.checkpoint`` — the backward then
        stores only each schedule step's stage INPUT (one activation
        per in-flight microbatch) and recomputes the stage internals,
        which is exactly the per-device activation footprint a
        hand-scheduled 1F1B would give. This is the deliberate design:
        under jax, autodiff through the scan + ppermute already yields
        a valid reverse pipeline schedule, and remat controls the
        memory — hand-interleaving forward/backward steps would fight
        the compiler instead of letting XLA overlap the reverse
        ppermutes with recompute.

    Returns [M, ...] outputs of the LAST stage, replicated across the
    pp axis.
    """
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    n_stages = lax.psum(1, pp_axis)
    d = lax.axis_index(pp_axis)
    M = x_microbatches.shape[0]
    steps = M + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    zero = jnp.zeros_like(x_microbatches[0])

    def step(buf, t):
        # Stage 0 feeds microbatch t (clamped: past-M steps are drain
        # steps whose stage-0 compute is discarded); later stages
        # consume what the previous stage sent last step.
        feed = x_microbatches[jnp.minimum(t, M - 1)]
        inp = jnp.where(d == 0, feed, buf)
        out = stage_fn(stage_params, inp)
        return lax.ppermute(out, pp_axis, perm), out

    _, outs = lax.scan(step, zero, jnp.arange(steps))
    # The last stage's real outputs sit at schedule steps
    # [n_stages-1, n_stages-1+M); every device slices there (static
    # bounds) and a masked psum replicates the last stage's values.
    tail = lax.dynamic_slice_in_dim(outs, n_stages - 1, M, axis=0)
    return lax.psum(jnp.where(d == n_stages - 1, tail, 0.0), pp_axis)


def stack_block_params(params, num_layers, prefix="block_%d"):
    """Stacks per-layer block param trees ([num_layers, ...] leaves)
    for stage sharding; layers must be structurally identical."""
    blocks = [params[prefix % i] for i in range(num_layers)]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *blocks)
