"""Device-mesh and topology utilities.

The reference derives a three-level communicator structure — global, local
(intra-node), cross (inter-node) — from MPI at init
(`common/mpi/mpi_context.cc:133-165`, splits at :149-158). On TPU the
analogous split is ICI (chips within a slice, fast torus links) vs DCN
(hosts/slices over the data-center network); XLA routes collectives
per-axis, so encoding the split in the Mesh axes is all that is needed —
no hierarchical op implementations, the compiler emits the two-level
reduction itself when the mesh is built contiguously.
"""

import numpy as np

import jax
from jax.sharding import Mesh


def _devices(backend=None):
    return jax.devices(backend) if backend else jax.devices()


def data_parallel_mesh(axis_name="hvd", backend=None, devices=None):
    """1-D mesh over every addressable device — the Horovod world.

    `mesh_utils.create_device_mesh` orders devices so neighbouring ranks
    are ICI neighbours (ring collectives ride the torus).
    """
    devs = list(devices) if devices is not None else _devices(backend)
    try:
        from jax.experimental import mesh_utils
        arr = mesh_utils.create_device_mesh((len(devs),), devices=devs)
    except Exception:  # CPU/virtual backends have no topology info
        arr = np.array(devs)
    return Mesh(arr, (axis_name,))


def hybrid_mesh(axis_shape, axis_names, backend=None, devices=None):
    """N-D mesh, e.g. ``hybrid_mesh((-1, 4), ("dp", "sp"))``.

    One axis may be -1 (inferred). On multi-slice TPU deployments prefer
    `mesh_utils.create_hybrid_device_mesh` semantics: the *leading* axes
    span DCN (cross-slice — the reference's `cross_comm`), trailing axes
    stay inside a slice on ICI (the reference's `local_comm`). Collectives
    over trailing axes therefore ride ICI only.
    """
    devs = list(devices) if devices is not None else _devices(backend)
    shape = list(axis_shape)
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        if len(devs) % known != 0:
            raise ValueError(
                "cannot infer -1 in mesh shape %r over %d devices"
                % (axis_shape, len(devs)))
        shape[shape.index(-1)] = len(devs) // known
    if int(np.prod(shape)) != len(devs):
        raise ValueError("mesh shape %r != %d devices" % (shape, len(devs)))
    try:
        from jax.experimental import mesh_utils
        arr = mesh_utils.create_device_mesh(tuple(shape), devices=devs)
    except Exception:
        arr = np.array(devs).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def mesh_2d(model_parallel, axis_names=("batch", "model"), backend=None,
            devices=None):
    """(batch, model) 2-D mesh matching ``hvd.init(model_parallel=k)``
    (docs/GROUPS.md) — the SNIPPETS NamedSharding pattern for the in-jit
    plane.

    Shape is ``(ndev // k, k)`` with the MODEL axis trailing, so model
    groups are k consecutive devices (ICI neighbors on a real slice,
    matching the host plane's consecutive-rank model groups) and batch
    rows stride across them. Shard parameters with
    ``NamedSharding(mesh, P(None, "model"))``-style specs
    (``tensor_parallel.tp_param_specs``), psum activations over the
    ``model`` axis and gradients over the ``batch`` axis only.
    """
    devs = list(devices) if devices is not None else _devices(backend)
    k = int(model_parallel)
    if k <= 0 or len(devs) % k != 0:
        raise ValueError(
            "model_parallel=%d does not divide %d devices" % (k, len(devs)))
    return hybrid_mesh((len(devs) // k, k), tuple(axis_names),
                       devices=devs)


def hvd_mesh_2d(axis_names=("batch", "model"), backend=None, devices=None):
    """The jax-side mesh for THIS process's hvd mesh state: a 2-D mesh
    with the model-parallel width ``hvd.init(model_parallel=k)``
    established (1-D data-parallel mesh collapses out when k == 1 —
    the batch axis then spans every device)."""
    import horovod_tpu as hvd
    return mesh_2d(hvd.model_parallel_size(), axis_names=axis_names,
                   backend=backend, devices=devices)


def mesh_axis_size(mesh, axis_name):
    return mesh.shape[axis_name]


def topology_summary(backend=None):
    """Human-readable device/topology description (the `--check-build`
    analogue of the reference's capability matrix, `run/run.py:262-298`)."""
    devs = _devices(backend)
    lines = ["%d device(s), platform=%s" % (len(devs), devs[0].platform)]
    for d in devs:
        coords = getattr(d, "coords", None)
        lines.append("  id=%d process=%d kind=%s%s" % (
            d.id, d.process_index, d.device_kind,
            " coords=%s" % (coords,) if coords is not None else ""))
    return "\n".join(lines)
