"""Expert parallelism: Switch-style top-1 mixture-of-experts with the
expert dimension sharded over an ``ep`` mesh axis.

The reference framework has no MoE (it is a gradient-reduction library);
this is the TPU-first ``ep`` member of the parallelism family
(dp/sp/tp/pp/ep), built the way GShard/Switch map onto XLA:

* **static shapes everywhere** — each expert has a fixed capacity
  ``C = ceil(T/E * capacity_factor)``; overflow tokens are dropped
  (their residual path passes through untouched), so the program never
  depends on routing decisions at compile time;
* **dispatch/combine as einsums** — routing is a [T, E, C] one-hot
  tensor contraction (MXU work), not gather/scatter;
* **all_to_all over ICI** — with ``ep_axis`` set (inside shard_map),
  expert inputs [E, C, D] are exchanged so each rank runs only its
  E/ep local experts on every rank's tokens, then exchanged back:
  ``lax.all_to_all`` split on the expert dim, concat on capacity —
  the MoE analogue of Ulysses' sequence all-to-all.

Router weights are replicated (every rank routes over all E experts);
expert FFN weights are sharded [E/ep, ...] along the expert dim
(PartitionSpec("ep") on axis 0 — see tests/test_expert.py and
__graft_entry__.dryrun_multichip phase 4).
"""

import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


def switch_dispatch(router_logits, capacity):
    """Top-1 (Switch) routing with a static per-expert capacity.

    router_logits: [T, E] (any float dtype; softmax in f32).
    Returns (dispatch [T, E, C] f32 one-hot, combine [T, E, C] f32
    gate-weighted, aux_loss scalar — the Switch load-balancing loss
    E * sum(frac_tokens_e * mean_prob_e)).
    """
    return topk_dispatch(router_logits, capacity, k=1)


def topk_dispatch(router_logits, capacity, k=2):
    """Top-k (GShard-style for k=2) routing with a static per-expert
    capacity; gates of the chosen experts renormalized to sum to 1 per
    token. Each choice occupies one capacity slot; queue positions
    count both choices (first choices of all tokens enqueue before
    second choices, GShard's ordering). Returns (dispatch [T, E, C],
    combine [T, E, C], aux_loss) like :func:`switch_dispatch` — the
    aux loss uses first-choice fractions (Switch eq. 4 / GShard's
    l_aux)."""
    T, E = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)

    onehots = []
    gates = []
    masked = probs
    for _ in range(k):
        idx = jnp.argmax(masked, axis=-1)
        oh = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        onehots.append(oh)
        gates.append(jnp.sum(probs * oh, axis=-1))
        masked = masked * (1.0 - oh)
    if k > 1:
        # GShard renormalizes the chosen gates; Switch (k=1) keeps the
        # raw top-1 probability (that term is what trains the router).
        denom = sum(gates)
        gates = [g / jnp.maximum(denom, 1e-9) for g in gates]

    # Queue positions: choice rounds enqueue in order — round r's
    # tokens arrive after ALL of round r-1's (prior_counts offsets).
    prior = jnp.zeros((E,), jnp.float32)
    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    for oh, gate in zip(onehots, gates):
        pos = jnp.sum((jnp.cumsum(oh, axis=0) + prior) * oh, axis=-1) \
            .astype(jnp.int32) - 1                             # [T]
        # one_hot of >= capacity (or negative) is all-zero: the drop.
        d = oh[:, :, None] * \
            jax.nn.one_hot(pos, capacity, dtype=jnp.float32)[:, None, :]
        dispatch = dispatch + d
        combine = combine + d * gate[:, None, None]
        prior = prior + jnp.sum(oh, axis=0)

    frac = jnp.mean(onehots[0], axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


def moe_capacity(tokens, num_experts, capacity_factor):
    """Static per-expert capacity (python int)."""
    return max(1, int(math.ceil(tokens / num_experts * capacity_factor)))


def moe_ffn(x, router_w, w_in, w_out, capacity_factor=1.25,
            ep_axis=None, act=nn.silu, top_k=1):
    """Switch (top_k=1) / GShard-style (top_k=2) MoE feed-forward over
    flattened tokens.

    x: [T, D]; router_w: [D, E] (replicated); w_in: [E_local, D, F],
    w_out: [E_local, F, D] — E_local = E with ``ep_axis=None``, E/ep
    inside shard_map with the expert dim sharded. With top_k>1 each
    token consumes top_k capacity slots — size capacity_factor
    accordingly (>= top_k for comparable drop rates).

    Returns (y [T, D] in x.dtype, aux_loss scalar f32).
    """
    T, D = x.shape
    E = router_w.shape[1]
    ep = 1 if ep_axis is None else lax.axis_size(ep_axis)
    if w_in.shape[0] * ep != E:
        raise ValueError(
            "expert shards (%d local x ep=%d) != num_experts %d" %
            (w_in.shape[0], ep, E))
    capacity = moe_capacity(T, E, capacity_factor)
    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)
    dispatch, combine, aux = topk_dispatch(logits, capacity, k=top_k)

    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    if ep_axis is not None:
        # [E, C, D] -> [E/ep, ep*C, D]: each rank keeps its local
        # experts' slots from EVERY rank's tokens.
        expert_in = lax.all_to_all(expert_in, ep_axis, split_axis=0,
                                   concat_axis=1, tiled=True)
    h = act(jnp.einsum("ecd,edf->ecf", expert_in, w_in))
    out = jnp.einsum("ecf,efd->ecd", h, w_out)
    if ep_axis is not None:
        # Reverse exchange: [E/ep, ep*C, D] -> [E, C, D].
        out = lax.all_to_all(out, ep_axis, split_axis=1,
                             concat_axis=0, tiled=True)
    y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), out)
    return y.astype(x.dtype), aux


class MoeMlp(nn.Module):
    """Drop-in MoE replacement for a transformer MLP: [B, L, D] ->
    [B, L, D] plus a sown ``intermediates/moe_aux_loss``.

    ``num_experts`` is GLOBAL; ``ep_size`` is the expert-parallel
    degree the module will be APPLIED under — inside shard_map each
    rank holds [num_experts/ep_size, ...] expert weights, so the
    declared param shapes divide by it (the tp path's `cfg.local()`
    trick). Initialize with ``ep_size=1`` (full shapes), place with
    `ep_param_specs`, apply with the ep-sized module."""
    num_experts: int
    mlp_dim: int
    capacity_factor: float = 1.25
    ep_axis: Optional[str] = None
    ep_size: int = 1
    top_k: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        B, L, D = x.shape
        if self.num_experts % self.ep_size:
            raise ValueError("ep_size=%d must divide num_experts=%d" %
                             (self.ep_size, self.num_experts))
        e_local = self.num_experts // self.ep_size
        router_w = self.param("router", nn.initializers.normal(0.02),
                              (D, self.num_experts), jnp.float32)
        w_in = self.param("w_in", nn.initializers.normal(0.02),
                          (e_local, D, self.mlp_dim),
                          jnp.float32)
        w_out = self.param("w_out", nn.initializers.normal(0.02),
                           (e_local, self.mlp_dim, D),
                           jnp.float32)
        y, aux = moe_ffn(x.reshape(-1, D), router_w,
                         w_in.astype(self.dtype), w_out.astype(self.dtype),
                         capacity_factor=self.capacity_factor,
                         ep_axis=self.ep_axis, top_k=self.top_k)
        self.sow("intermediates", "moe_aux_loss", aux)
        return y.reshape(B, L, D)


def ep_grad_sync(grads, ep_axis="ep", dp_axis=None, average=False):
    """Synchronizes a raw per-shard gradient tree inside shard_map
    under expert parallelism.

    Contract: differentiate a LOCAL (un-psummed) loss per rank, then
    call this. With tokens sharded over (dp x ep), raw gradients are:

    * expert-sharded leaves (param name ``w_in``/``w_out``): already
      summed along ep (the all_to_all transpose routes every ep peer's
      cotangents back to the owning rank) — psum over the dp axes only;
    * replicated leaves (router, norms, ...): this rank's token shard
      only — psum over dp AND ep.

    ``average=False`` (default) yields the gradient of the SUM of
    per-rank local losses; ``average=True`` divides by the total shard
    count (dp x ep), yielding the gradient of their MEAN — use this to
    match `tensor_parallel.tp_grad_sync`'s dp-averaging convention.
    `dp_axis` may be a name or tuple of names.
    """
    dp_axes = ()
    if dp_axis is not None:
        dp_axes = (dp_axis,) if isinstance(dp_axis, str) else tuple(dp_axis)
    total = 1.0
    if average:
        for ax in dp_axes + (ep_axis,):
            total = total * lax.axis_size(ax)

    def sync(path, g):
        names = [getattr(k, "key", None) for k in path]
        axes = list(dp_axes)
        # Same final-key rule as ep_param_specs — the two halves of
        # the placement/sync contract must classify leaves identically.
        if not (names and names[-1] in ("w_in", "w_out")):
            axes.append(ep_axis)
        for ax in axes:
            g = lax.psum(g, ax)
        if average:
            g = g / total
        return g

    return jax.tree_util.tree_map_with_path(sync, grads)


def ep_param_specs(params, ep_axis, replicated_spec=None):
    """PartitionSpecs for a params tree containing MoeMlp leaves:
    expert-dim sharding for w_in/w_out, replication elsewhere.

    Walks the tree by key name (the MoeMlp param names are the
    contract), mirroring `tensor_parallel.tp_param_specs`."""
    from jax.sharding import PartitionSpec as P

    rep = replicated_spec if replicated_spec is not None else P()

    def spec_for(path, leaf):
        names = [getattr(k, "key", None) for k in path]
        if names and names[-1] in ("w_in", "w_out"):
            return P(ep_axis)
        return rep

    return jax.tree_util.tree_map_with_path(spec_for, params)
