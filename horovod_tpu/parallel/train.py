"""Data-parallel train-step builder — the in-XLA DistributedOptimizer loop.

Reference equivalent: `_DistributedOptimizer.apply_gradients`
(`horovod/tensorflow/__init__.py:231-258`) + the allreduce data plane. On
TPU the whole step (forward, backward, gradient allreduce, optimizer
update) is one XLA program over the mesh: the gradient psum lowers to an
ICI AllReduce that XLA fuses and overlaps with the backward pass — the
compiler-scheduled analogue of the reference's tensor-fusion/cycle
machinery (`common/controller.cc:551-672`), which the host core still
provides for eager/host tensors.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu.jax as hvd_jax


def _aval_cache_key(*trees):
    """Cache key for per-structure compiled steps: tree structure PLUS
    leaf shapes/dtypes (sharding specs depend on shapes — same
    structure with different shapes must not reuse a compiled step)."""
    leaves, treedef = jax.tree_util.tree_flatten(trees)
    return (treedef, tuple(
        (tuple(x.shape), str(x.dtype)) if hasattr(x, "shape") else x
        for x in leaves))


def _structure_cached_step(build):
    """step(params, opt_state, batch) dispatching through a cache of
    compiled callables keyed on (structure, shapes, dtypes); exposes
    .lower for XLA cost analysis (bench.py's contract)."""
    cache = {}

    def compiled(params, opt_state):
        key = _aval_cache_key(params, opt_state)
        if key not in cache:
            cache[key] = build(params, opt_state)
        return cache[key]

    def step(params, opt_state, batch):
        return compiled(params, opt_state)(params, opt_state, batch)

    step.lower = lambda params, opt_state, batch: \
        compiled(params, opt_state).lower(params, opt_state, batch)
    return step


def make_train_step(loss_fn, optimizer, mesh, axis_name="hvd",
                    compression=None, donate=True, zero1=False,
                    accum_steps=1, agc=None):
    """Builds a jitted data-parallel train step over `mesh`.

    Args:
      loss_fn: ``loss_fn(params, batch) -> scalar loss`` (per-shard batch).
      optimizer: an optax GradientTransformation (unwrapped — the
        allreduce wrapping happens here).
      mesh: a 1-D `jax.sharding.Mesh` over `axis_name`.
      compression: optional gradient compression. Wire modes
        ('bf16'/'int8'/`horovod_tpu.compression` modes) work on BOTH
        paths — under zero1 the gradient scatter runs the explicit
        compressed ring (``ring_reduce_scatter``) while the parameter
        allgather stays exact. Legacy tensor codecs
        (``hvd_jax.Compression.fp16``) are plain-path only.
      donate: donate params/opt_state buffers (in-place update on TPU).
      zero1: ZeRO-stage-1 optimizer-state sharding. Gradients are
        reduce_scattered over the mesh (each device averages 1/n of
        every flattened gradient), the optimizer updates only its
        1/n shard — optimizer STATE per device shrinks n-fold (Adam:
        2x params -> 2x params/n) — and updated parameter shards are
        all_gathered back. reduce_scatter + all_gather move the same
        bytes as the ring allreduce they replace, so step cost is
        unchanged. Numerically identical to the plain path for
        ELEMENTWISE optax transforms (sgd/momentum/adam/adamw...);
        transforms that mix elements across a parameter (e.g.
        global-norm clipping) would see flattened shards instead of
        whole tensors. ``place()`` builds the sharded optimizer state
        itself (pass ``opt_state=None`` or the plain init — it is
        replaced).
      agc: adaptive-gradient-clipping factor (e.g. 0.01; None = off).
        Applied by the wrapped DistributedOptimizer after the gradient
        psum — the norm-free zoo variants' trainability knob
        (ops/agc.py, arxiv 2102.06171). Rejected with zero1: the
        sharded update sees 1/N flat shards, which destroys the
        per-unit norm structure AGC clips against.
      accum_steps: gradient accumulation — the flagship analogue of
        the torch binding's ``backward_passes_per_step`` (reference
        torch/__init__.py). The per-shard batch is split into
        ``accum_steps`` microbatches along dim 0 (must divide the
        shard size); a ``lax.scan`` accumulates the mean of their
        gradients, then ONE optimizer update (and, in the plain path,
        one allreduce of the already-accumulated gradients — the same
        deferred-allreduce semantics as the reference).

    Returns ``step(params, opt_state, batch) -> (params, opt_state, loss)``
    where params are replicated, batch is sharded on dim 0, and
    opt_state is replicated (plain) or dim-0-sharded (zero1).
    """
    from horovod_tpu import compression as _wire
    # zero1 + WIRE compression composes: the gradient scatter runs the
    # explicit ring_reduce_scatter with the codec fused per hop (f32
    # accumulation), and the parameter allgather stays uncompressed so
    # every rank agrees on the updated weights exactly (docs/ZERO.md).
    # Legacy tensor codecs (cast-the-tensor) stay rejected under zero1
    # — they would change the dtype the shard-local optimizer sees —
    # except the no-op Compression.none codec (replicated-era call
    # sites); the shared resolve_wire_arg keeps this in lockstep with
    # the three DistributedOptimizer wrappers.
    zero1_mode = _wire.resolve_wire_arg(
        compression, hvd_jax.Compression.none) \
        if zero1 else _wire.Compression.none
    if agc is not None and zero1:
        raise ValueError(
            "agc= does not compose with zero1: the sharded update "
            "applies the optimizer to 1/N flat shards, which destroys "
            "the per-unit (output-row) norm structure AGC clips "
            "against — every rank would clip a different slice of "
            "each filter")
    # Library helper, not a training script: the caller owns the initial
    # parameter sync (place() replicates params over the mesh, and host
    # checkpoint restore broadcasts before entering the step).
    # hvd-lint: disable=missing-initial-broadcast
    dist_opt = hvd_jax.DistributedOptimizer(
        optimizer, compression=compression, axis_name=axis_name, agc=agc)
    n_shards = int(mesh.shape[axis_name])

    def _flat_pad(x):
        # Dtype preserved: the shard-local update must apply the same
        # arithmetic the plain path would (f32 master copies are the
        # caller's choice via param dtype, not imposed here). Under wire
        # compression shards additionally pad to the int8 block so the
        # grad scatter (ring_reduce_scatter) and the param slicing agree
        # on chunk boundaries.
        v = jnp.ravel(x)
        unit = n_shards
        if zero1_mode != _wire.Compression.none:
            unit = n_shards * _wire.BLOCK
        pad = (-v.size) % unit
        return jnp.pad(v, (0, pad)) if pad else v

    def _local_loss_and_grads(params, batch):
        if accum_steps == 1:
            return jax.value_and_grad(loss_fn)(params, batch)
        # Microbatch scan: mean of microbatch losses/grads == the
        # full-shard value for mean-reduction losses.
        micro = jax.tree_util.tree_map(
            lambda x: x.reshape((accum_steps, x.shape[0] // accum_steps)
                                + x.shape[1:]), batch)

        def body(carry, mb):
            loss_acc, grads_acc = carry
            loss, grads = jax.value_and_grad(loss_fn)(params, mb)
            grads_acc = jax.tree_util.tree_map(
                lambda a, g: a + g / accum_steps, grads_acc, grads)
            return (loss_acc + loss / accum_steps, grads_acc), None

        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, p.dtype), params)
        (loss, grads), _ = jax.lax.scan(body, (0.0, zeros), micro)
        return loss, grads

    def shard_step(params, opt_state, batch):
        loss, grads = _local_loss_and_grads(params, batch)
        if zero1:
            idx = jax.lax.axis_index(axis_name)

            def scatter(g):
                if zero1_mode != _wire.Compression.none:
                    # Compressed scatter: the explicit ppermute ring with
                    # quant/dequant fused per hop (f32 accumulation);
                    # _flat_pad already block-aligned the input so the
                    # ring's chunk == my_slice's chunk.
                    from horovod_tpu.parallel.ring import \
                        ring_reduce_scatter
                    return ring_reduce_scatter(
                        _flat_pad(g), axis_name,
                        compression=zero1_mode) / n_shards
                v = jax.lax.psum_scatter(_flat_pad(g), axis_name,
                                         scatter_dimension=0, tiled=True)
                return v / n_shards

            def my_slice(p):
                v = _flat_pad(p)
                chunk = v.shape[0] // n_shards
                return jax.lax.dynamic_slice_in_dim(v, idx * chunk, chunk)

            g_shards = jax.tree_util.tree_map(scatter, grads)
            p_shards = jax.tree_util.tree_map(my_slice, params)
            updates, opt_state = optimizer.update(g_shards, opt_state,
                                                 p_shards)
            new_shards = jax.tree_util.tree_map(lambda p, u: p + u,
                                                p_shards, updates)
            params = jax.tree_util.tree_map(
                lambda ns, p: jax.lax.all_gather(
                    ns, axis_name, tiled=True)[:p.size]
                .reshape(p.shape).astype(p.dtype),
                new_shards, params)
        else:
            updates, opt_state = dist_opt.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(
                lambda p, u: (p + u).astype(p.dtype), params, updates)
        loss = jax.lax.pmean(loss, axis_name)
        return params, opt_state, loss

    replicated = P()
    sharded = P(axis_name)
    donate_argnums = (0, 1) if donate else ()

    if not zero1:
        # Plain path: P() is a valid pytree-PREFIX spec for the whole
        # optimizer state, so the step IS the jitted callable (C++
        # fast-path dispatch — no per-step Python wrapper).
        step = jax.jit(jax.shard_map(
            shard_step, mesh=mesh,
            in_specs=(replicated, replicated, sharded),
            out_specs=(replicated, replicated, replicated),
            check_vma=False), donate_argnums=donate_argnums)
    else:
        # zero1: the opt-state spec tree depends on the state's
        # STRUCTURE (1-D array leaves sharded, scalars like Adam's
        # count replicated), so the shard_map is built from the live
        # tree, cached per (structure, shapes).
        def _build(_params, opt_state):
            spec = jax.tree_util.tree_map(
                lambda x: sharded if getattr(x, "ndim", 0) >= 1
                else replicated, opt_state)
            return jax.jit(jax.shard_map(
                shard_step, mesh=mesh,
                in_specs=(replicated, spec, sharded),
                out_specs=(replicated, spec, replicated),
                check_vma=False), donate_argnums=donate_argnums)

        step = _structure_cached_step(_build)

    def place(params, opt_state, batch=None):
        """Places params (replicated), optimizer state (replicated, or
        built flat-padded and dim-0 sharded under zero1 — the passed
        opt_state is ignored then), and batch (dim-0 sharded)."""
        rep = NamedSharding(mesh, replicated)
        dat = NamedSharding(mesh, sharded)
        params = jax.device_put(params, rep)
        if zero1:
            # Build the state WITH sharded out_shardings so the full
            # moments are never materialized per device (the whole
            # point of zero1 is that they don't fit).
            def init_flat(p):
                return optimizer.init(
                    jax.tree_util.tree_map(_flat_pad, p))

            template = jax.eval_shape(init_flat, params)
            out_shardings = jax.tree_util.tree_map(
                lambda x: NamedSharding(mesh, sharded)
                if getattr(x, "ndim", 0) >= 1 else rep, template)
            opt_state = jax.jit(
                init_flat, out_shardings=out_shardings)(params)
        else:
            opt_state = jax.device_put(opt_state, rep)
        if batch is None:
            return params, opt_state
        batch = jax.tree_util.tree_map(
            partial(jax.device_put, device=dat), batch)
        return params, opt_state, batch

    step.place = place
    return step


def make_fsdp_train_step(loss_fn, optimizer, mesh, axis_name="hvd",
                         donate=True, min_size=1024):
    """Fully-sharded data parallelism (ZeRO-3-style) the XLA-native
    way: parameters, gradients AND optimizer state live sharded over
    the dp axis; the step is a plain ``jax.jit`` whose in/out
    shardings constrain the layout and GSPMD inserts the collectives —
    all_gather for each parameter right before use, reduce_scatter for
    its gradient — exactly the scaling-book recipe (pick a mesh,
    annotate shardings, let XLA insert collectives).

    Contrast with ``make_train_step``: that one is shard_map'd SPMD
    with explicit psums (Horovod semantics, replicated state);
    ``zero1=True`` shards only optimizer state. Here per-device memory
    for params+grads+state all drop ~n-fold; XLA overlaps the gathers
    with compute. Leaves whose dim 0 is not divisible by the mesh (or
    smaller than ``min_size`` elements) stay replicated.

    loss_fn sees GLOBAL arrays (plain jit semantics): write it exactly
    as the single-device loss — no pmean, no axis names.

    Returns ``step(params, opt_state, batch)`` plus ``step.place``.
    """
    n = int(mesh.shape[axis_name])

    def _spec(p):
        if getattr(p, "ndim", 0) >= 1 and p.size >= min_size \
                and p.shape[0] % n == 0:
            return P(axis_name)
        return P()

    def train(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(
            lambda p, u: (p + u).astype(p.dtype), params, updates)
        return params, opt_state, loss

    def _build(params, opt_state):
        pspec = jax.tree_util.tree_map(_spec, params)
        ospec = jax.tree_util.tree_map(_spec, opt_state)
        to_sh = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda s: NamedSharding(mesh, s), t)
        in_sh = (to_sh(pspec), to_sh(ospec),
                 NamedSharding(mesh, P(axis_name)))
        out_sh = (to_sh(pspec), to_sh(ospec),
                  NamedSharding(mesh, P()))
        return jax.jit(train, in_shardings=in_sh, out_shardings=out_sh,
                       donate_argnums=(0, 1) if donate else ())

    step = _structure_cached_step(_build)

    def place(params, opt_state=None, batch=None):
        """Shards params per the FSDP rule, BUILDS the optimizer state
        under jit with sharded out_shardings (the full state is never
        materialized on one device — any passed opt_state is ignored,
        like the zero1 path), and shards the batch on dim 0."""
        params = jax.tree_util.tree_map(
            lambda x: jax.device_put(
                x, NamedSharding(mesh, _spec(x))), params)
        template = jax.eval_shape(optimizer.init, params)
        out_shardings = jax.tree_util.tree_map(
            lambda x: NamedSharding(mesh, _spec(x)), template)
        opt_state = jax.jit(optimizer.init,
                            out_shardings=out_shardings)(params)
        if batch is None:
            return params, opt_state
        batch = jax.tree_util.tree_map(
            partial(jax.device_put,
                    device=NamedSharding(mesh, P(axis_name))), batch)
        return params, opt_state, batch

    step.place = place
    return step


def cross_entropy_loss(logits, labels):
    """Mean softmax cross entropy with integer labels (benchmark loss)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))
