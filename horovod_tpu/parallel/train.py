"""Data-parallel train-step builder — the in-XLA DistributedOptimizer loop.

Reference equivalent: `_DistributedOptimizer.apply_gradients`
(`horovod/tensorflow/__init__.py:231-258`) + the allreduce data plane. On
TPU the whole step (forward, backward, gradient allreduce, optimizer
update) is one XLA program over the mesh: the gradient psum lowers to an
ICI AllReduce that XLA fuses and overlaps with the backward pass — the
compiler-scheduled analogue of the reference's tensor-fusion/cycle
machinery (`common/controller.cc:551-672`), which the host core still
provides for eager/host tensors.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu.jax as hvd_jax


def make_train_step(loss_fn, optimizer, mesh, axis_name="hvd",
                    compression=None, donate=True):
    """Builds a jitted data-parallel train step over `mesh`.

    Args:
      loss_fn: ``loss_fn(params, batch) -> scalar loss`` (per-shard batch).
      optimizer: an optax GradientTransformation (unwrapped — the
        allreduce wrapping happens here).
      mesh: a 1-D `jax.sharding.Mesh` over `axis_name`.
      compression: optional `hvd_jax.Compression` codec for gradients.
      donate: donate params/opt_state buffers (in-place update on TPU).

    Returns ``step(params, opt_state, batch) -> (params, opt_state, loss)``
    where params/opt_state are replicated and batch is sharded on dim 0.
    """
    compression = compression or hvd_jax.Compression.none
    dist_opt = hvd_jax.DistributedOptimizer(
        optimizer, compression=compression, axis_name=axis_name)

    def shard_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = dist_opt.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(
            lambda p, u: (p + u).astype(p.dtype), params, updates)
        loss = jax.lax.pmean(loss, axis_name)
        return params, opt_state, loss

    replicated = P()
    sharded = P(axis_name)
    mapped = jax.shard_map(
        shard_step, mesh=mesh,
        in_specs=(replicated, replicated, sharded),
        out_specs=(replicated, replicated, replicated),
        check_vma=False)

    donate_argnums = (0, 1) if donate else ()
    step = jax.jit(mapped, donate_argnums=donate_argnums)

    def place(params, opt_state, batch=None):
        """Places params/opt_state (replicated) and batch (dim-0 sharded)
        onto the mesh."""
        rep = NamedSharding(mesh, replicated)
        dat = NamedSharding(mesh, sharded)
        params = jax.device_put(params, rep)
        opt_state = jax.device_put(opt_state, rep)
        if batch is None:
            return params, opt_state
        batch = jax.tree_util.tree_map(
            partial(jax.device_put, device=dat), batch)
        return params, opt_state, batch

    step.place = place
    return step


def cross_entropy_loss(logits, labels):
    """Mean softmax cross entropy with integer labels (benchmark loss)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))
