"""Tensor parallelism for the transformer (Megatron-style sharding).

No reference analogue (the reference only moves gradients); this is
part of the distributed-first-class extension. The flax module stays
SPMD-agnostic: parameters are initialized FULL-size once, placed with
`tp_param_specs` PartitionSpecs (attention heads and the MLP hidden
dim sharded over the tp axis), and applied inside ``shard_map`` by a
module built from ``cfg.local(tp_size)`` — each shard's local
parameter block matches the local module's declared shapes, and the
module psums the row-parallel partial products
(`models/transformer.py`, ``tp_axis``).

Gradient sync composes per leaf: tp-sharded leaves' gradients are
already local-complete; replicated leaves (norms, embedding, lm_head)
get partial gradients on every tp shard and must be psummed over tp.
`tp_grad_sync` applies exactly that rule (and the usual mean over a
data-parallel axis when given one).
"""

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

# Parameter-name -> sharded dim for the transformer's param tree:
# DenseGeneral query/key/value kernels are [D, H, Dh] (heads dim 1),
# the out projection is [H, Dh, D] (heads dim 0), mlp_in [D, M]
# (hidden dim 1), mlp_out [M, D] (hidden dim 0).
_TP_DIMS = {"query": 1, "key": 1, "value": 1, "out": 0,
            "mlp_in": 1, "mlp_out": 0}


def tp_param_specs(params, tp_axis="tp"):
    """PartitionSpec tree for `params` (a full-size transformer param
    tree): tp-shardable kernels get their head/hidden dim sharded on
    `tp_axis`; everything else is replicated."""

    def spec(path, leaf):
        names = [getattr(k, "key", None) for k in path]
        for name, dim in _TP_DIMS.items():
            if name in names:
                parts = [None] * leaf.ndim
                parts[dim] = tp_axis
                return P(*parts)
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


def is_tp_sharded(path):
    """True when the param at `path` is sharded by tp_param_specs."""
    names = [getattr(k, "key", None) for k in path]
    return any(name in names for name in _TP_DIMS)


# ---------------------------------------------------------------------------
# Host-plane Megatron f/g operators (docs/GROUPS.md): the cross-PROCESS
# analogue of the in-jit psum pair, riding the model-axis process group
# of hvd.init(model_parallel=k). With layers column-parallel then
# row-parallel:
#   y = g( x_colparallel @ W2_shard )  — g: allreduce fwd, identity bwd
#   x = f( input )                     — f: identity fwd, allreduce bwd
# Both are jax.custom_vjp wrappers over hvd.jax.allreduce(group=...),
# so autodiff never descends into the host collective, and the
# forward/backward collective ORDER is identical on every member
# (ordered io_callbacks when traced; eager host ops otherwise).
# ---------------------------------------------------------------------------


def copy_to_model_parallel(x, group, name=None):
    """Megatron's f operator: identity forward, model-group allreduce
    backward. Place at the INPUT of a column-parallel layer — each
    shard's input gradient is partial (its slice of the output), and
    the backward allreduce completes it."""
    import horovod_tpu.jax as hvd_jax

    @jax.custom_vjp
    def _f(v):
        return v

    def _fwd(v):
        return v, None

    def _bwd(_, dv):
        return (hvd_jax.allreduce(dv, average=False, group=group,
                                  name=name and name + ".bwd"),)

    _f.defvjp(_fwd, _bwd)
    return _f(x)


def reduce_from_model_parallel(x, group, name=None):
    """Megatron's g operator: model-group allreduce forward, identity
    backward. Place at the OUTPUT of a row-parallel layer — each shard
    holds a partial product; the forward allreduce completes the
    activation, and since out = sum(partials), d partial = d out."""
    import horovod_tpu.jax as hvd_jax

    def _sum(v):
        return hvd_jax.allreduce(v, average=False, group=group, name=name)

    @jax.custom_vjp
    def _g(v):
        return _sum(v)

    def _fwd(v):
        return _sum(v), None

    def _bwd(_, dv):
        return (dv,)

    _g.defvjp(_fwd, _bwd)
    return _g(x)


def tp_grad_sync(grads, tp_axis="tp", dp_axis=None):
    """Synchronizes a raw per-shard gradient tree inside shard_map
    under tensor parallelism.

    With the loss computed redundantly on every tp shard (the psums in
    the model make activations full everywhere), each shard's raw
    gradients carry a factor of tp_size from the psum transpose
    (verified empirically: sharded kernels come out exactly tp_size
    times the true slice; pre-psum replicated leaves are tp_size times
    a shard-dependent partial; post-psum leaves are exact). The
    unified correction: divide everything by tp_size and psum the
    replicated leaves — i.e. sharded leaves take g/n, replicated
    leaves take pmean(g) (which is also a no-op-preserving choice for
    the already-exact post-psum leaves). With `dp_axis`, every leaf is
    additionally pmean'd across data parallelism."""
    n = lax.psum(1, tp_axis)

    def sync(path, g):
        if is_tp_sharded(path):
            g = g / n
        else:
            g = lax.pmean(g, tp_axis)
        if dp_axis is not None:
            g = lax.pmean(g, dp_axis)
        return g

    return jax.tree_util.tree_map_with_path(sync, grads)
