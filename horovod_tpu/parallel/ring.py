"""Long-context sequence parallelism (ring attention, Ulysses) and the
compressed ring allreduce.

Not present in the reference (SURVEY.md §5.7 — it never sees activations);
first-class here because long context shapes the core design on TPU.

* :func:`ring_allreduce` — explicit ``lax.ppermute`` ring allreduce with
  EQuARX-style wire compression fused into the per-hop compute
  (quantize/dequantize as part of each hop, not a pre/post pass), for
  gradient bytes on the ICI/DCN links (docs/COMPRESSION.md).

* :func:`ring_attention` — blockwise (flash-style) attention where each
  device holds a sequence shard and k/v blocks rotate around the ICI ring
  via ``lax.ppermute``; compute on the current block overlaps the
  neighbour exchange (XLA schedules the ppermute concurrently with the
  matmuls since there is no data dependence until the next iteration).
  Softmax is accumulated online (running max + normaliser), so the result
  is exact full attention over the whole sequence at O(L/n) memory.
* :func:`ulysses_attention` — all-to-all alternative: reshard from
  sequence-sharded to head-sharded, run dense local attention, reshard
  back. Better when heads >= devices and the per-device sequence is short.

Both support GQA/MQA (k/v with fewer heads than q: [B, L, G, D] with
G | H) and fused rotary (``rotary_base`` — positions are the *global*
token positions implied by the schedule, so sequence shards agree).

Both are meant to run inside ``shard_map`` over a mesh axis (see
`horovod_tpu.parallel.mesh.hybrid_mesh`).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.ops.flash_attention import apply_rotary, shard_positions


def _block_attention(q, k, v, o, m, l, q_offset, kv_offset, causal, scale):
    """One flash-attention block update with online softmax.

    q [B,Lq,H,D]; k,v [B,Lk,H,D]; o [B,Lq,H,D] f32 accumulator;
    m,l [B,H,Lq] running max / normaliser. Offsets are *global* token
    offsets of the local q block and the current k/v block, for causal
    masking across devices.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_offset + lax.broadcasted_iota(jnp.int32, s.shape, 2)
        k_pos = kv_offset + lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # exp(-inf - -inf) guard: a fully-masked row keeps m == -inf; correct
    # the scale factor to 0 there instead of NaN.
    alpha = jnp.where(jnp.isneginf(m_new), 0.0, jnp.exp(m - m_new))
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(jnp.isneginf(m_new)[..., None], 0.0, p)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return o_new, m_new, l_new


def _interpret_mode():
    """HVD_TPU_PALLAS_INTERPRET=1 runs the ring kernel in Pallas
    interpret mode on any backend (test coverage of the kernel path
    without TPU hardware)."""
    import os
    return os.environ.get("HVD_TPU_PALLAS_INTERPRET", "0") == "1"


def _use_flash_ring(Lq, Lk, scale):
    """The Pallas carry-state kernel needs 128-aligned sequence shards
    (any head dim: blocks span the full D), a static scale (the kernel
    closes over it), and a TPU default backend. The backend check is a
    heuristic: a CPU mesh built on a TPU-attached host would be
    misrouted for aligned shards — set HVD_TPU_RING_KERNEL=0 to force
    the jnp path there (or HVD_TPU_PALLAS_INTERPRET=1 to run the kernel
    in interpret mode anywhere)."""
    import os

    if Lq % 128 != 0 or Lk % 128 != 0:
        return False
    if not isinstance(scale, (int, float)):
        return False  # traced scale: the jnp path differentiates it
    if os.environ.get("HVD_TPU_RING_KERNEL", "1") == "0":
        return False
    return jax.default_backend() == "tpu" or _interpret_mode()


def _shard_visible(src, idx, Lq, Lk):
    """Whether the kv shard starting at src*Lk overlaps the causal
    lower triangle of this rank's q rows [idx*Lq, (idx+1)*Lq)."""
    return src * Lk <= idx * Lq + (Lq - 1)


def _causal_skip_step(causal, src, idx, Lq, Lk, step, a, b, c,
                      k_blk, v_blk):
    """Run `step(a, b, c, k_blk, v_blk)` unless the held kv shard is
    entirely in this rank's future on a causal run (then pass the
    carry through untouched). ONE definition for the jnp, kernel-fwd
    and kernel-bwd rings so the predicate cannot desynchronize.

    What this buys: on the jnp ring it skips real masked-einsum FLOPs;
    on the kernel rings the per-block `pl.when` guards already skipped
    the FLOPs, so it skips the pallas_call dispatch, its block DMAs,
    and the carry copies. Either way it is per-rank work/energy, NOT
    ring latency: the schedule is lockstep and rank n-1 computes at
    every step, so the critical path is unchanged — that is what
    `ring_attention(schedule="zigzag")` below fixes."""
    if not causal:
        return step(a, b, c, k_blk, v_blk)
    return lax.cond(_shard_visible(src, idx, Lq, Lk), step,
                    lambda a, b, c, *_: (a, b, c),
                    a, b, c, k_blk, v_blk)


def _ring_jnp(q, k, v, axis_name, causal, scale, rotary_base=None):
    """Blockwise jnp ring (non-TPU / unaligned-shape fallback).
    q [B,Lq,H,D]; k/v [B,Lk,G,D] — GQA repeats kv across each head
    group (the kernel path never materializes that). Rotary is applied
    up front: q with this shard's global positions, k with the HOME
    shard's positions before it starts traveling (each k row's rotation
    is fixed by its own global position, not by who computes with it).
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, Lq, H, D = q.shape
    Lk, G = k.shape[1], k.shape[2]
    perm = [(j, (j + 1) % n) for j in range(n)]

    if rotary_base is not None:
        qpos = idx * Lq + jnp.arange(Lq, dtype=jnp.int32)
        kpos = idx * Lk + jnp.arange(Lk, dtype=jnp.int32)
        q = apply_rotary(q, qpos[None, :, None], rotary_base)
        k = apply_rotary(k, kpos[None, :, None], rotary_base)

    step = functools.partial(_block_attention, causal=causal, scale=scale)

    o0 = jnp.zeros((B, Lq, H, D), jnp.float32)
    m0 = jnp.full((B, H, Lq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Lq), jnp.float32)

    def body(i, carry):
        o, m, l, k_blk, v_blk = carry
        src = (idx - i) % n  # which global block we currently hold

        def compute(o, m, l, k_blk, v_blk):
            if G != H:
                # GQA: repeat the traveling G-head shard up to H just
                # for the local einsum (the ring moves the small one).
                k_blk = jnp.repeat(k_blk, H // G, axis=2)
                v_blk = jnp.repeat(v_blk, H // G, axis=2)
            return step(q, k_blk, v_blk, o, m, l,
                        q_offset=idx * Lq, kv_offset=src * Lk)

        o, m, l = _causal_skip_step(causal, src, idx, Lq, Lk, compute,
                                    o, m, l, k_blk, v_blk)
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return o, m, l, k_nxt, v_nxt

    o, m, l, _, _ = lax.fori_loop(0, n, body, (o0, m0, l0, k, v))
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> 0 output
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _to_rows_bl(x, group):
    """[B, L, H, D] (H = G*group) -> grouped kernel layout
    [B*G, L*group, D]; ONE row-ordering definition (the kernel
    module's `_to_rows`) so the ring and plain layouts cannot
    disagree. group=1 is the plain [B*H, L, D] layout."""
    from horovod_tpu.ops.flash_attention import _to_rows
    return _to_rows(x.transpose(0, 2, 1, 3), group)


def _from_rows_bl(x, B, group):
    """Inverse of `_to_rows_bl`: [B*G, L*group, D] -> [B, L, H, D]."""
    from horovod_tpu.ops.flash_attention import _from_rows
    return _from_rows(x, B, group).transpose(0, 2, 1, 3)


def _schedule_offsets(schedule, rank, n, L):
    """Global token offset(s) of the shard held by `rank` (traced).

    contiguous: one chunk at rank*L. zigzag: the sequence is split into
    2n chunks of L/2; rank r holds chunks (r, 2n-1-r) concatenated —
    the causal load-balancing layout (every rank's lower-triangle work
    is equal, so the lockstep ring's critical path halves vs the
    contiguous layout where rank n-1 does all n steps' work)."""
    if schedule == "zigzag":
        Lc = L // 2
        return jnp.stack([rank * Lc, (2 * n - 1 - rank) * Lc])
    return rank * L


def _ring_flash_impl(q, k, v, axis_name, causal, scale,
                     schedule="contiguous", rotary_base=None):
    """Pallas ring forward. q [B,Lq,H,D], k/v [B,Lk,G,D]. Returns
    (out [B,Lq,H,D], out_k, lse) where out_k is the normalized output
    in the grouped-rows kernel layout and lse [B*G, Lq*group, 8] is the
    per-row log-sum-exp stripe the backward ring consumes."""
    from horovod_tpu.ops.flash_attention import flash_ring_step

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, Lq, H, D = q.shape
    Lk, G = k.shape[1], k.shape[2]
    group = H // G
    perm = [(j, (j + 1) % n) for j in range(n)]

    # Transpose once; the ring circulates kernel-layout k/v shards.
    qk = _to_rows_bl(q, group)
    kk = _to_rows_bl(k, 1)
    vk = _to_rows_bl(v, 1)
    rows = Lq * group
    o0 = jnp.zeros((B * G, rows, D), jnp.float32)
    m0 = jnp.full((B * G, rows, 8), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B * G, rows, 8), jnp.float32)

    q_off = _schedule_offsets(schedule, idx, n, Lq)

    def body(i, carry):
        o, m, l, k_blk, v_blk = carry
        src = (idx - i) % n

        def compute(o, m, l, k_blk, v_blk):
            return flash_ring_step(
                qk, k_blk, v_blk, o, m, l,
                q_offset=q_off,
                kv_offset=_schedule_offsets(schedule, src, n, Lk),
                causal=causal, scale=scale,
                interpret=_interpret_mode(), group=group,
                rotary_base=rotary_base)

        if schedule == "zigzag":
            # Every step has at-or-below-diagonal work by construction
            # (rank r's high chunk sees every kv shard) — that balance
            # IS the point; no step-level skip exists to take.
            o, m, l = compute(o, m, l, k_blk, v_blk)
        else:
            o, m, l = _causal_skip_step(causal, src, idx, Lq, Lk,
                                        compute, o, m, l, k_blk, v_blk)
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return o, m, l, k_nxt, v_nxt

    o, m, l, _, _ = lax.fori_loop(0, n, body, (o0, m0, l0, kk, vk))
    l1 = jnp.where(l[:, :, :1] == 0.0, 1.0, l[:, :, :1])
    out_k = (o / l1).astype(q.dtype)
    # lse = m + log(l); untouched rows (m == -inf, l == 0) stay -inf.
    lse = jnp.broadcast_to(m[:, :, :1] + jnp.log(l1), m.shape)
    return _from_rows_bl(out_k, B, group), out_k, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash(q, k, v, axis_name, causal, scale,
                schedule="contiguous", rotary_base=None):
    """Pallas ring attention, wrapped in a custom VJP because Pallas
    kernels are not auto-differentiable. The backward is a second ring
    pass (FlashAttention-2 style) over the saved per-row log-sum-exp —
    no forward recompute: dq accumulates locally while dk/dv travel
    around the ring with their k/v shard."""
    return _ring_flash_impl(q, k, v, axis_name, causal, scale,
                            schedule, rotary_base)[0]


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, schedule,
                    rotary_base):
    out, out_k, lse = _ring_flash_impl(q, k, v, axis_name, causal,
                                       scale, schedule, rotary_base)
    return out, (q, k, v, out_k, lse)


def _ring_flash_bwd(axis_name, causal, scale, schedule, rotary_base,
                    res, g):
    from horovod_tpu.ops.flash_attention import flash_ring_bwd_step

    q, k, v, out_k, lse = res
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, Lq, H, D = q.shape
    Lk, G = k.shape[1], k.shape[2]
    group = H // G
    perm = [(j, (j + 1) % n) for j in range(n)]

    qk = _to_rows_bl(q, group)
    kk = _to_rows_bl(k, 1)
    vk = _to_rows_bl(v, 1)
    gk = _to_rows_bl(g, group)
    # delta = rowsum(dO * O): one fused XLA pass per shard, reused by
    # every ring step (both backward kernels stream it per q block).
    delta = jnp.broadcast_to(
        jnp.sum(gk.astype(jnp.float32) * out_k.astype(jnp.float32),
                axis=-1, keepdims=True), lse.shape)

    rows = Lq * group
    dq0 = jnp.zeros((B * G, rows, D), jnp.float32)
    dk0 = jnp.zeros((B * G, Lk, D), jnp.float32)
    dv0 = jnp.zeros((B * G, Lk, D), jnp.float32)

    q_off = _schedule_offsets(schedule, idx, n, Lq)

    def body(i, carry):
        dq, k_blk, v_blk, dk, dv = carry
        src = (idx - i) % n

        def compute(dq, dk, dv, k_blk, v_blk):
            return flash_ring_bwd_step(
                qk, k_blk, v_blk, gk, lse, delta, dq, dk, dv,
                q_offset=q_off,
                kv_offset=_schedule_offsets(schedule, src, n, Lk),
                causal=causal, scale=scale,
                interpret=_interpret_mode(), group=group,
                rotary_base=rotary_base)

        if schedule == "zigzag":
            dq, dk, dv = compute(dq, dk, dv, k_blk, v_blk)
        else:
            dq, dk, dv = _causal_skip_step(causal, src, idx, Lq, Lk,
                                           compute, dq, dk, dv, k_blk,
                                           v_blk)
        # dk/dv ride the ring with their k/v shard; after n steps each
        # shard's gradient arrives back on its home device.
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        dk_nxt = lax.ppermute(dk, axis_name, perm)
        dv_nxt = lax.ppermute(dv, axis_name, perm)
        return dq, k_nxt, v_nxt, dk_nxt, dv_nxt

    dq, _, _, dk, dv = lax.fori_loop(0, n, body, (dq0, kk, vk, dk0, dv0))
    if rotary_base is not None:
        # The ring kernels accumulate dq/dk in ROTATED space (the
        # accumulators persist across ring steps, so per-step counter-
        # rotation would corrupt later additions). One counter-rotation
        # at the end: dq by this shard's q-row positions, dk by its
        # HOME kv positions (it traveled the full ring and is home).
        qpos_rows = jnp.repeat(shard_positions(q_off, Lq), group)
        dq = apply_rotary(dq, qpos_rows[None, :], rotary_base, neg=True)
        kpos = shard_positions(
            _schedule_offsets(schedule, idx, n, Lk), Lk)
        dk = apply_rotary(dk, kpos[None, :], rotary_base, neg=True)
    return (_from_rows_bl(dq, B, group).astype(q.dtype),
            _from_rows_bl(dk, B, 1).astype(k.dtype),
            _from_rows_bl(dv, B, 1).astype(v.dtype))


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(q, k, v, axis_name, causal=True, scale=None,
                   schedule="contiguous", rotary_base=None):
    """Exact multi-head attention over a sequence sharded on `axis_name`.

    Args: q of shape [B, L_local, H, D], k/v [B, L_local, G, D] with
    G | H (GQA/MQA: query head h reads kv head h // (H//G); G == H is
    plain MHA) — per-device shards, equal L_local on every device,
    inside shard_map over `axis_name`. Returns [B, L_local, H, D] in
    q.dtype. ``rotary_base`` fuses rotary embedding into the kernels
    using the schedule's global positions — do not also rotate outside.

    schedule:
      * "contiguous" (default): rank r holds tokens [r*L_local,
        (r+1)*L_local). Causal runs dispatch nothing for kv shards
        entirely in a rank's future (see `_causal_skip_step` for
        exactly what that saves — and what it does not: ring latency
        is set by the last rank, which computes at every step).
      * "zigzag": the global sequence is split into 2n chunks; rank r
        holds chunks (r, 2n-1-r) concatenated (`zigzag_shard` /
        `zigzag_unshard` convert layouts). Every rank then does the
        same amount of causal lower-triangle work at every ring step,
        halving the lockstep critical path at large n. Kernel path
        only (per-block offset arrays; L_local must be a multiple of
        256 so each chunk is 128-aligned).

    On TPU with 128-aligned shards the per-step local compute runs as a
    Pallas flash kernel with carried online-softmax state
    (`horovod_tpu.ops.flash_attention.flash_ring_step`), so per-step
    memory is O(block) instead of the O(Lq * Lk) score matrix; other
    backends/shapes use the blockwise jnp path. Gradients flow on both
    paths; the kernel path's backward is a second ring pass over the
    saved per-row log-sum-exp (FlashAttention-2 style — no forward
    recompute), with dk/dv accumulators riding the ring alongside
    their k/v shard.
    """
    if schedule not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown ring schedule: {schedule!r}")
    B, Lq, H, D = q.shape
    Lk, G = k.shape[1], k.shape[2]
    if H % G:
        raise ValueError(
            f"num_heads={H} must be a multiple of num_kv_heads={G}")
    if scale is None:
        scale = D ** -0.5
    if schedule == "zigzag":
        if not causal:
            # Non-causal work is already balanced; the zigzag layout
            # buys nothing and only complicates offsets.
            raise ValueError("schedule='zigzag' is a causal load-"
                             "balancing layout; use contiguous for "
                             "non-causal attention")
        if Lq % 256 or Lk % 256:
            raise ValueError(
                f"zigzag needs 256-multiple shard lengths (two "
                f"128-aligned chunks per rank); got Lq={Lq}, Lk={Lk}")
        if not _use_flash_ring(Lq, Lk, scale):
            raise ValueError(
                "schedule='zigzag' runs on the Pallas kernel ring "
                "only (TPU backend, or HVD_TPU_PALLAS_INTERPRET=1, "
                "static scale)")
        return _ring_flash(q, k, v, axis_name, causal, scale, "zigzag",
                           rotary_base)
    if _use_flash_ring(Lq, Lk, scale):
        return _ring_flash(q, k, v, axis_name, causal, scale,
                           "contiguous", rotary_base)
    return _ring_jnp(q, k, v, axis_name, causal, scale, rotary_base)


def zigzag_shard(x, n, axis=1):
    """Re-layout a GLOBAL sequence axis into zigzag device order:
    split into 2n chunks, device r's shard = concat(chunk r,
    chunk 2n-1-r). The result, sharded contiguously over n devices
    (e.g. shard_map in_specs P(axis_name) on `axis`), gives each
    device exactly the layout `ring_attention(schedule='zigzag')`
    expects. Inverse: `zigzag_unshard`."""
    ch = jnp.split(x, 2 * n, axis=axis)
    return jnp.concatenate(
        [jnp.concatenate([ch[r], ch[2 * n - 1 - r]], axis=axis)
         for r in range(n)], axis=axis)


def zigzag_unshard(x, n, axis=1):
    """Inverse of `zigzag_shard` (zigzag device order -> the natural
    global sequence order)."""
    pairs = jnp.split(x, 2 * n, axis=axis)  # [r0, r0', r1, r1', ...]
    out = [None] * (2 * n)
    for r in range(n):
        out[r] = pairs[2 * r]
        out[2 * n - 1 - r] = pairs[2 * r + 1]
    return jnp.concatenate(out, axis=axis)


def ring_allreduce(x, axis_name, compression="none"):
    """Explicit ring allreduce (sum) over `axis_name` with wire
    compression fused into the per-hop compute (EQuARX-style; PAPERS.md
    arxiv 2506.17615). Runs inside shard_map/pmap over a mapped axis.

    The array is flattened and split into one chunk per rank. Phase 1
    (reduce-scatter, n-1 hops): each hop ENCODES the outgoing chunk
    (requant), ships the small payload via ``lax.ppermute``, DECODES the
    incoming one (dequant) and adds it in f32 — the accumulator never
    lives in the narrow format. Phase 2 (allgather, n-1 hops): the owner
    encodes its reduced chunk once, decodes its own copy back (so every
    rank ends with the identical dequantized values), and the encoded
    payload then travels the ring VERBATIM — each hop's ppermute of
    chunk k+1 has no data dependence on the local decode of chunk k, so
    XLA overlaps the dequantize with the neighbor transfer (the
    pipelining trick ring_attention uses for its k/v blocks).

    compression: 'none' | 'bf16' | 'int8' (or a
    `horovod_tpu.compression` mode). bf16 halves the bytes each hop
    moves; int8 cuts them ~3.9x with one f32 scale per 256-element
    block riding in-band (the (q, scales) pair IS the payload). Only
    f32 inputs compress; other dtypes ride 'none'.

    Returns the SUM over the axis in x's dtype/shape (callers divide
    for an average). With compression='none' this is numerically a
    psum (up to f32 sum order); prefer plain psum there — this path
    exists for the compressed modes.
    """
    from horovod_tpu import compression as _comp

    mode = _comp.resolve(compression)
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    orig_shape, orig_dtype = x.shape, x.dtype
    if mode.mode != _comp.NONE and orig_dtype != jnp.float32:
        mode = _comp.Compression.none
    # Only the compressed f32 path needs an f32 working copy; degraded
    # dtypes (int32/int64/f64...) stay in their own dtype so large ints
    # and f64 sum exactly, like psum would.
    work_dtype = jnp.float32 if mode.mode != _comp.NONE else orig_dtype
    flat = x.astype(work_dtype).reshape(-1)
    if n == 1:
        return flat.reshape(orig_shape).astype(orig_dtype)
    # Chunk length: rank-uniform, padded to the int8 block so every
    # chunk quantizes on block boundaries.
    c = -(-flat.size // n)
    c = -(-c // _comp.BLOCK) * _comp.BLOCK
    chunks = jnp.pad(flat, (0, n * c - flat.size)).reshape(n, c)
    perm = [(j, (j + 1) % n) for j in range(n)]
    enc, dec, ship = _ring_codec(mode)

    # Reduce-scatter: after n-1 hops this rank's chunk (idx+1)%n holds
    # the full sum. Each hop requantizes the freshly-reduced outgoing
    # chunk and dequant-adds the incoming one in f32.
    def rs_body(s, chunks):
        send_i = (idx - s) % n
        recv_i = (idx - s - 1) % n
        incoming = ship(enc(jnp.take(chunks, send_i, axis=0)), axis_name,
                        perm)
        upd = jnp.take(chunks, recv_i, axis=0) + dec(incoming)
        return lax.dynamic_update_index_in_dim(chunks, upd, recv_i, 0)

    chunks = lax.fori_loop(0, n - 1, rs_body, chunks)

    # Allgather: encode the owned chunk once; every rank decodes the
    # SAME bytes (the owner re-decodes its own copy), so results are
    # rank-identical — no per-hop requantization drift.
    owned = (idx + 1) % n
    payload = enc(jnp.take(chunks, owned, axis=0))
    chunks = lax.dynamic_update_index_in_dim(chunks, dec(payload), owned, 0)

    def ag_body(s, carry):
        chunks, payload = carry
        recv_i = (idx - s) % n
        # ppermute first: the transfer of this hop's payload and the
        # decode of the previous hop's chunk have no data dependence.
        incoming = ship(payload, axis_name, perm)
        chunks = lax.dynamic_update_index_in_dim(chunks, dec(incoming),
                                                 recv_i, 0)
        return chunks, incoming

    chunks, _ = lax.fori_loop(0, n - 1, ag_body, (chunks, payload))
    out = chunks.reshape(-1)[:flat.size]
    return out.reshape(orig_shape).astype(orig_dtype)


def _ring_codec(mode):
    """(enc, dec, ship) hop codec triple shared by the ring collectives
    (one definition so the allreduce and the split-out reduce-scatter /
    allgather legs cannot disagree on the wire format)."""
    from horovod_tpu import compression as _comp

    def enc(v):
        if mode.mode == _comp.BF16:
            return (v.astype(jnp.bfloat16),)
        if mode.mode == _comp.INT8:
            return _comp.quantize_int8_jax(v)
        return (v,)

    def dec(payload):
        if mode.mode == _comp.BF16:
            return payload[0].astype(jnp.float32)
        if mode.mode == _comp.INT8:
            return _comp.dequantize_int8_jax(*payload)
        return payload[0]

    def ship(payload, axis_name, perm):
        return tuple(lax.ppermute(p, axis_name, perm) for p in payload)

    return enc, dec, ship


def ring_reduce_scatter(x, axis_name, compression="none"):
    """Reduce-scatter leg of the ring as a standalone collective
    (docs/ZERO.md): flattens `x`, splits it into one chunk per rank
    (padded so chunks are equal and int8-block-aligned), and after n-1
    ppermute hops returns THIS rank's chunk of the cross-axis SUM — a
    1-D f32 array of ``ceil(size/n)`` (block-rounded) elements. Chunk r
    belongs to axis index r, so ``ring_allgather`` of per-rank results
    reassembles the full vector in order.

    Wire compression ('bf16'/'int8') encodes each hop's payload exactly
    like :func:`ring_allreduce`'s first phase — the accumulator stays
    f32. The chunk length is ``ceil(ceil(size/n)/BLOCK)*BLOCK`` (the
    int8 block padding applies in every mode so a mode change never
    changes shard shapes). With n == 1 returns the (padded) flat vector
    unchanged.
    """
    from horovod_tpu import compression as _comp

    mode = _comp.resolve(compression)
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    if mode.mode != _comp.NONE and x.dtype != jnp.float32:
        mode = _comp.Compression.none
    work_dtype = jnp.float32 if mode.mode != _comp.NONE else x.dtype
    flat = x.astype(work_dtype).reshape(-1)
    c = -(-flat.size // n)
    c = -(-c // _comp.BLOCK) * _comp.BLOCK
    if n == 1:
        return jnp.pad(flat, (0, c - flat.size))
    chunks = jnp.pad(flat, (0, n * c - flat.size)).reshape(n, c)
    perm = [(j, (j + 1) % n) for j in range(n)]
    enc, dec, ship = _ring_codec(mode)

    # The allreduce's schedule (send (idx-s), recv (idx-s-1)) leaves
    # rank r owning chunk (r+1)%n; shifting every chunk index by -1
    # leaves rank r owning chunk r — rank order == chunk order, so the
    # matching ring_allgather reassembles the vector without a permute.
    def body(s, chunks):
        send_i = (idx - s - 1) % n
        recv_i = (idx - s - 2) % n
        incoming = ship(enc(jnp.take(chunks, send_i, axis=0)), axis_name,
                        perm)
        upd = jnp.take(chunks, recv_i, axis=0) + dec(incoming)
        return lax.dynamic_update_index_in_dim(chunks, upd, recv_i, 0)

    chunks = lax.fori_loop(0, n - 1, body, chunks)
    return jnp.take(chunks, idx, axis=0)


def ring_allgather(x, axis_name, compression="none"):
    """Allgather leg of the ring as a standalone collective
    (docs/ZERO.md): every rank contributes an equal-shape 1-D shard
    (axis index r's shard is chunk r) and receives the concatenation of
    all of them — the parameter leg of the sharded weight update, where
    XLA can overlap each hop's ppermute with downstream compute on
    already-received chunks.

    With compression, each owner encodes its shard ONCE and decodes its
    own copy back, and the encoded payload travels the ring VERBATIM —
    every rank ends with bitwise-identical values (the allreduce's
    second phase, unchanged). Parameters usually ride 'none': the
    updated weights are the values every rank must agree on exactly.
    """
    from horovod_tpu import compression as _comp

    mode = _comp.resolve(compression)
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    if mode.mode != _comp.NONE and x.dtype != jnp.float32:
        mode = _comp.Compression.none
    if n == 1:
        return x.reshape(-1)
    c = x.size
    perm = [(j, (j + 1) % n) for j in range(n)]
    enc, dec, ship = _ring_codec(mode)
    chunks = jnp.zeros((n, c), x.dtype if mode.mode == _comp.NONE
                       else jnp.float32)
    payload = enc(x.reshape(-1).astype(chunks.dtype))
    chunks = lax.dynamic_update_index_in_dim(chunks, dec(payload), idx, 0)

    def body(s, carry):
        chunks, payload = carry
        recv_i = (idx - s - 1) % n
        # ppermute first: the transfer and the previous chunk's decode
        # have no data dependence, so XLA overlaps them.
        incoming = ship(payload, axis_name, perm)
        chunks = lax.dynamic_update_index_in_dim(chunks, dec(incoming),
                                                 recv_i, 0)
        return chunks, incoming

    chunks, _ = lax.fori_loop(0, n - 1, body, (chunks, payload))
    return chunks.reshape(-1)


def ulysses_attention(q, k, v, axis_name, causal=True, scale=None,
                      rotary_base=None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style).

    Input q [B, L_local, H, D] / k, v [B, L_local, G, D] sequence-
    sharded; all_to_all turns them into [B, L_full, H/n, D] (and
    [B, L_full, G/n, D]) head-sharded, local flash attention runs on
    the full sequence, and a second all_to_all restores sequence
    sharding. Both H and G must be divisible by the axis size (GQA
    keeps its head grouping because consecutive query heads share a kv
    head and the split is contiguous). ``rotary_base`` fuses rotary in
    the local kernel — positions are global (the gathered sequence
    starts at 0), so shards agree.
    """
    n = lax.psum(1, axis_name)
    B, Ll, H, D = q.shape
    G = k.shape[2]
    if H % G:
        raise ValueError(
            f"num_heads={H} must be a multiple of num_kv_heads={G}")
    if H % n or G % n:
        raise ValueError(
            f"ulysses needs the sp axis size ({n}) to divide both "
            f"num_heads={H} and num_kv_heads={G} (the all_to_all "
            f"splits the head dims)")
    if scale is None:
        scale = D ** -0.5

    def seq_to_heads(x):
        # [B, Ll, H, D] -> concat seq, split heads -> [B, Ll*n, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # Local attention over the full sequence: flash_attention keeps it
    # O(L) memory on TPU (custom VJP covers the backward) and itself
    # falls back to the numerically-identical blockwise implementation
    # on other backends/unaligned shapes.
    from horovod_tpu.ops import flash_attention
    og = flash_attention(qg, kg, vg, causal=causal, scale=scale,
                         rotary_base=rotary_base)
    return heads_to_seq(og)
