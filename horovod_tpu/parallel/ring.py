"""Long-context sequence parallelism: ring attention and Ulysses.

Not present in the reference (SURVEY.md §5.7 — it never sees activations);
first-class here because long context shapes the core design on TPU.

* :func:`ring_attention` — blockwise (flash-style) attention where each
  device holds a sequence shard and k/v blocks rotate around the ICI ring
  via ``lax.ppermute``; compute on the current block overlaps the
  neighbour exchange (XLA schedules the ppermute concurrently with the
  matmuls since there is no data dependence until the next iteration).
  Softmax is accumulated online (running max + normaliser), so the result
  is exact full attention over the whole sequence at O(L/n) memory.
* :func:`ulysses_attention` — all-to-all alternative: reshard from
  sequence-sharded to head-sharded, run dense local attention, reshard
  back. Better when heads >= devices and the per-device sequence is short.

Both are meant to run inside ``shard_map`` over a mesh axis (see
`horovod_tpu.parallel.mesh.hybrid_mesh`).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _block_attention(q, k, v, o, m, l, q_offset, kv_offset, causal, scale):
    """One flash-attention block update with online softmax.

    q [B,Lq,H,D]; k,v [B,Lk,H,D]; o [B,Lq,H,D] f32 accumulator;
    m,l [B,H,Lq] running max / normaliser. Offsets are *global* token
    offsets of the local q block and the current k/v block, for causal
    masking across devices.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_offset + lax.broadcasted_iota(jnp.int32, s.shape, 2)
        k_pos = kv_offset + lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # exp(-inf - -inf) guard: a fully-masked row keeps m == -inf; correct
    # the scale factor to 0 there instead of NaN.
    alpha = jnp.where(jnp.isneginf(m_new), 0.0, jnp.exp(m - m_new))
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(jnp.isneginf(m_new)[..., None], 0.0, p)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return o_new, m_new, l_new


def _interpret_mode():
    """HVD_TPU_PALLAS_INTERPRET=1 runs the ring kernel in Pallas
    interpret mode on any backend (test coverage of the kernel path
    without TPU hardware)."""
    import os
    return os.environ.get("HVD_TPU_PALLAS_INTERPRET", "0") == "1"


def _use_flash_ring(Lq, Lk, scale):
    """The Pallas carry-state kernel needs 128-aligned sequence shards
    (any head dim: blocks span the full D), a static scale (the kernel
    closes over it), and a TPU default backend. The backend check is a
    heuristic: a CPU mesh built on a TPU-attached host would be
    misrouted for aligned shards — set HVD_TPU_RING_KERNEL=0 to force
    the jnp path there (or HVD_TPU_PALLAS_INTERPRET=1 to run the kernel
    in interpret mode anywhere)."""
    import os

    if Lq % 128 != 0 or Lk % 128 != 0:
        return False
    if not isinstance(scale, (int, float)):
        return False  # traced scale: the jnp path differentiates it
    if os.environ.get("HVD_TPU_RING_KERNEL", "1") == "0":
        return False
    return jax.default_backend() == "tpu" or _interpret_mode()


def _shard_visible(src, idx, Lq, Lk):
    """Whether the kv shard starting at src*Lk overlaps the causal
    lower triangle of this rank's q rows [idx*Lq, (idx+1)*Lq)."""
    return src * Lk <= idx * Lq + (Lq - 1)


def _causal_skip_step(causal, src, idx, Lq, Lk, step, a, b, c,
                      k_blk, v_blk):
    """Run `step(a, b, c, k_blk, v_blk)` unless the held kv shard is
    entirely in this rank's future on a causal run (then pass the
    carry through untouched). ONE definition for the jnp, kernel-fwd
    and kernel-bwd rings so the predicate cannot desynchronize.

    What this buys: on the jnp ring it skips real masked-einsum FLOPs;
    on the kernel rings the per-block `pl.when` guards already skipped
    the FLOPs, so it skips the pallas_call dispatch, its block DMAs,
    and the carry copies. Either way it is per-rank work/energy, NOT
    ring latency: the schedule is lockstep and rank n-1 computes at
    every step, so the critical path is unchanged — that is what
    `ring_attention(schedule="zigzag")` below fixes."""
    if not causal:
        return step(a, b, c, k_blk, v_blk)
    return lax.cond(_shard_visible(src, idx, Lq, Lk), step,
                    lambda a, b, c, *_: (a, b, c),
                    a, b, c, k_blk, v_blk)


def _ring_jnp(q, k, v, axis_name, causal, scale):
    """Blockwise jnp ring (non-TPU / unaligned-shape fallback)."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    perm = [(j, (j + 1) % n) for j in range(n)]

    step = functools.partial(_block_attention, causal=causal, scale=scale)

    o0 = jnp.zeros((B, Lq, H, D), jnp.float32)
    m0 = jnp.full((B, H, Lq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Lq), jnp.float32)

    def body(i, carry):
        o, m, l, k_blk, v_blk = carry
        src = (idx - i) % n  # which global block we currently hold

        def compute(o, m, l, k_blk, v_blk):
            return step(q, k_blk, v_blk, o, m, l,
                        q_offset=idx * Lq, kv_offset=src * Lk)

        o, m, l = _causal_skip_step(causal, src, idx, Lq, Lk, compute,
                                    o, m, l, k_blk, v_blk)
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return o, m, l, k_nxt, v_nxt

    o, m, l, _, _ = lax.fori_loop(0, n, body, (o0, m0, l0, k, v))
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> 0 output
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _to_kernel(x, B, H):
    """[B, L, H, D] -> kernel layout [B*H, L, D]."""
    return x.transpose(0, 2, 1, 3).reshape(B * H, -1, x.shape[-1])


def _from_kernel(x, B, H):
    """Kernel layout [B*H, L, D] -> [B, L, H, D]."""
    BH, L, D = x.shape
    return x.reshape(B, H, L, D).transpose(0, 2, 1, 3)


def _schedule_offsets(schedule, rank, n, L):
    """Global token offset(s) of the shard held by `rank` (traced).

    contiguous: one chunk at rank*L. zigzag: the sequence is split into
    2n chunks of L/2; rank r holds chunks (r, 2n-1-r) concatenated —
    the causal load-balancing layout (every rank's lower-triangle work
    is equal, so the lockstep ring's critical path halves vs the
    contiguous layout where rank n-1 does all n steps' work)."""
    if schedule == "zigzag":
        Lc = L // 2
        return jnp.stack([rank * Lc, (2 * n - 1 - rank) * Lc])
    return rank * L


def _ring_flash_impl(q, k, v, axis_name, causal, scale,
                     schedule="contiguous"):
    """Pallas ring forward. Returns (out [B,Lq,H,D], out_k, lse) where
    out_k is the normalized output in kernel layout and lse [B*H,Lq,8]
    is the per-row log-sum-exp stripe the backward ring consumes."""
    from horovod_tpu.ops.flash_attention import flash_ring_step

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    perm = [(j, (j + 1) % n) for j in range(n)]

    # Transpose once; the ring circulates kernel-layout k/v shards.
    qk = _to_kernel(q, B, H)
    kk = _to_kernel(k, B, H)
    vk = _to_kernel(v, B, H)
    o0 = jnp.zeros((B * H, Lq, D), jnp.float32)
    m0 = jnp.full((B * H, Lq, 8), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B * H, Lq, 8), jnp.float32)

    q_off = _schedule_offsets(schedule, idx, n, Lq)

    def body(i, carry):
        o, m, l, k_blk, v_blk = carry
        src = (idx - i) % n

        def compute(o, m, l, k_blk, v_blk):
            return flash_ring_step(
                qk, k_blk, v_blk, o, m, l,
                q_offset=q_off,
                kv_offset=_schedule_offsets(schedule, src, n, Lk),
                causal=causal, scale=scale,
                interpret=_interpret_mode())

        if schedule == "zigzag":
            # Every step has at-or-below-diagonal work by construction
            # (rank r's high chunk sees every kv shard) — that balance
            # IS the point; no step-level skip exists to take.
            o, m, l = compute(o, m, l, k_blk, v_blk)
        else:
            o, m, l = _causal_skip_step(causal, src, idx, Lq, Lk,
                                        compute, o, m, l, k_blk, v_blk)
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return o, m, l, k_nxt, v_nxt

    o, m, l, _, _ = lax.fori_loop(0, n, body, (o0, m0, l0, kk, vk))
    l1 = jnp.where(l[:, :, :1] == 0.0, 1.0, l[:, :, :1])
    out_k = (o / l1).astype(q.dtype)
    # lse = m + log(l); untouched rows (m == -inf, l == 0) stay -inf.
    lse = jnp.broadcast_to(m[:, :, :1] + jnp.log(l1), m.shape)
    return _from_kernel(out_k, B, H), out_k, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_flash(q, k, v, axis_name, causal, scale,
                schedule="contiguous"):
    """Pallas ring attention, wrapped in a custom VJP because Pallas
    kernels are not auto-differentiable. The backward is a second ring
    pass (FlashAttention-2 style) over the saved per-row log-sum-exp —
    no forward recompute: dq accumulates locally while dk/dv travel
    around the ring with their k/v shard."""
    return _ring_flash_impl(q, k, v, axis_name, causal, scale,
                            schedule)[0]


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, schedule):
    out, out_k, lse = _ring_flash_impl(q, k, v, axis_name, causal,
                                       scale, schedule)
    return out, (q, k, v, out_k, lse)


def _ring_flash_bwd(axis_name, causal, scale, schedule, res, g):
    from horovod_tpu.ops.flash_attention import flash_ring_bwd_step

    q, k, v, out_k, lse = res
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    perm = [(j, (j + 1) % n) for j in range(n)]

    qk = _to_kernel(q, B, H)
    kk = _to_kernel(k, B, H)
    vk = _to_kernel(v, B, H)
    gk = _to_kernel(g, B, H)
    # delta = rowsum(dO * O): one fused XLA pass per shard, reused by
    # every ring step (both backward kernels stream it per q block).
    delta = jnp.broadcast_to(
        jnp.sum(gk.astype(jnp.float32) * out_k.astype(jnp.float32),
                axis=-1, keepdims=True), lse.shape)

    dq0 = jnp.zeros((B * H, Lq, D), jnp.float32)
    dk0 = jnp.zeros((B * H, Lk, D), jnp.float32)
    dv0 = jnp.zeros((B * H, Lk, D), jnp.float32)

    q_off = _schedule_offsets(schedule, idx, n, Lq)

    def body(i, carry):
        dq, k_blk, v_blk, dk, dv = carry
        src = (idx - i) % n

        def compute(dq, dk, dv, k_blk, v_blk):
            return flash_ring_bwd_step(
                qk, k_blk, v_blk, gk, lse, delta, dq, dk, dv,
                q_offset=q_off,
                kv_offset=_schedule_offsets(schedule, src, n, Lk),
                causal=causal, scale=scale,
                interpret=_interpret_mode())

        if schedule == "zigzag":
            dq, dk, dv = compute(dq, dk, dv, k_blk, v_blk)
        else:
            dq, dk, dv = _causal_skip_step(causal, src, idx, Lq, Lk,
                                           compute, dq, dk, dv, k_blk,
                                           v_blk)
        # dk/dv ride the ring with their k/v shard; after n steps each
        # shard's gradient arrives back on its home device.
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        dk_nxt = lax.ppermute(dk, axis_name, perm)
        dv_nxt = lax.ppermute(dv, axis_name, perm)
        return dq, k_nxt, v_nxt, dk_nxt, dv_nxt

    dq, _, _, dk, dv = lax.fori_loop(0, n, body, (dq0, kk, vk, dk0, dv0))
    return (_from_kernel(dq, B, H).astype(q.dtype),
            _from_kernel(dk, B, H).astype(k.dtype),
            _from_kernel(dv, B, H).astype(v.dtype))


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(q, k, v, axis_name, causal=True, scale=None,
                   schedule="contiguous"):
    """Exact multi-head attention over a sequence sharded on `axis_name`.

    Args: q, k, v of shape [B, L_local, H, D] (per-device shards, equal
    L_local on every device), inside shard_map over `axis_name`.
    Returns [B, L_local, H, D] in q.dtype.

    schedule:
      * "contiguous" (default): rank r holds tokens [r*L_local,
        (r+1)*L_local). Causal runs dispatch nothing for kv shards
        entirely in a rank's future (see `_causal_skip_step` for
        exactly what that saves — and what it does not: ring latency
        is set by the last rank, which computes at every step).
      * "zigzag": the global sequence is split into 2n chunks; rank r
        holds chunks (r, 2n-1-r) concatenated (`zigzag_shard` /
        `zigzag_unshard` convert layouts). Every rank then does the
        same amount of causal lower-triangle work at every ring step,
        halving the lockstep critical path at large n. Kernel path
        only (per-block offset arrays; L_local must be a multiple of
        256 so each chunk is 128-aligned).

    On TPU with 128-aligned shards the per-step local compute runs as a
    Pallas flash kernel with carried online-softmax state
    (`horovod_tpu.ops.flash_attention.flash_ring_step`), so per-step
    memory is O(block) instead of the O(Lq * Lk) score matrix; other
    backends/shapes use the blockwise jnp path. Gradients flow on both
    paths; the kernel path's backward is a second ring pass over the
    saved per-row log-sum-exp (FlashAttention-2 style — no forward
    recompute), with dk/dv accumulators riding the ring alongside
    their k/v shard.
    """
    if schedule not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown ring schedule: {schedule!r}")
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    if scale is None:
        scale = D ** -0.5
    if schedule == "zigzag":
        if not causal:
            # Non-causal work is already balanced; the zigzag layout
            # buys nothing and only complicates offsets.
            raise ValueError("schedule='zigzag' is a causal load-"
                             "balancing layout; use contiguous for "
                             "non-causal attention")
        if Lq % 256 or Lk % 256:
            raise ValueError(
                f"zigzag needs 256-multiple shard lengths (two "
                f"128-aligned chunks per rank); got Lq={Lq}, Lk={Lk}")
        if not _use_flash_ring(Lq, Lk, scale):
            raise ValueError(
                "schedule='zigzag' runs on the Pallas kernel ring "
                "only (TPU backend, or HVD_TPU_PALLAS_INTERPRET=1, "
                "static scale)")
        return _ring_flash(q, k, v, axis_name, causal, scale, "zigzag")
    if _use_flash_ring(Lq, Lk, scale):
        return _ring_flash(q, k, v, axis_name, causal, scale)
    return _ring_jnp(q, k, v, axis_name, causal, scale)


def zigzag_shard(x, n, axis=1):
    """Re-layout a GLOBAL sequence axis into zigzag device order:
    split into 2n chunks, device r's shard = concat(chunk r,
    chunk 2n-1-r). The result, sharded contiguously over n devices
    (e.g. shard_map in_specs P(axis_name) on `axis`), gives each
    device exactly the layout `ring_attention(schedule='zigzag')`
    expects. Inverse: `zigzag_unshard`."""
    ch = jnp.split(x, 2 * n, axis=axis)
    return jnp.concatenate(
        [jnp.concatenate([ch[r], ch[2 * n - 1 - r]], axis=axis)
         for r in range(n)], axis=axis)


def zigzag_unshard(x, n, axis=1):
    """Inverse of `zigzag_shard` (zigzag device order -> the natural
    global sequence order)."""
    pairs = jnp.split(x, 2 * n, axis=axis)  # [r0, r0', r1, r1', ...]
    out = [None] * (2 * n)
    for r in range(n):
        out[r] = pairs[2 * r]
        out[2 * n - 1 - r] = pairs[2 * r + 1]
    return jnp.concatenate(out, axis=axis)


def ulysses_attention(q, k, v, axis_name, causal=True, scale=None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style).

    Input [B, L_local, H, D] sequence-sharded; all_to_all turns it into
    [B, L_full, H/n, D] head-sharded, local dense attention runs on full
    sequence, and a second all_to_all restores sequence sharding. H must
    be divisible by the axis size.
    """
    n = lax.psum(1, axis_name)
    B, Ll, H, D = q.shape
    if scale is None:
        scale = D ** -0.5

    def seq_to_heads(x):
        # [B, Ll, H, D] -> concat seq, split heads -> [B, Ll*n, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # Local attention over the full sequence: flash_attention keeps it
    # O(L) memory on TPU (custom VJP covers the backward) and itself
    # falls back to the numerically-identical blockwise implementation
    # on other backends/unaligned shapes.
    from horovod_tpu.ops import flash_attention
    og = flash_attention(qg, kg, vg, causal=causal, scale=scale)
    return heads_to_seq(og)
