"""Parallelism strategies over JAX device meshes.

The reference framework is data-parallel only (SURVEY.md §2.5); data
parallelism here reproduces it natively (``make_train_step`` = the
DistributedOptimizer loop lowered onto an ICI mesh). Long-context sequence
parallelism (ring attention, Ulysses all-to-all) is a first-class TPU
extension layered on the same mesh machinery.

* :mod:`.mesh`  — topology discovery and Mesh construction (ICI within a
  slice, DCN across slices — the TPU analogue of the reference's
  local/cross communicator split, `common/mpi/mpi_context.cc:133-165`).
* :mod:`.train` — jitted, shard_map'd data-parallel train-step builder
  (the in-XLA equivalent of `_DistributedOptimizer.apply_gradients`,
  reference `horovod/tensorflow/__init__.py:231-258`), with
  ``accum_steps`` gradient accumulation (the flagship
  backward_passes_per_step), ``zero1`` optimizer-state sharding, and
  :func:`make_fsdp_train_step` — FSDP/ZeRO-3 through pure GSPMD
  shardings.
* :mod:`.ring`  — ring attention (blockwise flash attention with k/v
  blocks rotated over the ICI ring via ``ppermute``) and Ulysses-style
  all-to-all sequence parallelism (sp).
* :mod:`.tensor_parallel` — Megatron-style tp: full-size init,
  `tp_param_specs` placement, per-shard `cfg.local()` modules,
  `tp_grad_sync`.
* :mod:`.pipeline` — GPipe pp over stage-stacked blocks, with the
  pinned in-shard_map gradient contract and a ``remat`` option
  (1F1B-class activation memory).
* :mod:`.expert` — Switch/GShard MoE ep: top-1/top-2 routing with
  static capacity, expert-dim all_to_all, `ep_param_specs` /
  `ep_grad_sync`.

Pairwise compositions are test-pinned: tp x sp, sp x ep (ring AND
Ulysses), dp x pp, fsdp x tp, plus the dryrun's dp x {sp,tp,ep}
train steps.
"""

from horovod_tpu.compat import ensure_jax_compat as _ensure_jax_compat

_ensure_jax_compat()

from .mesh import (  # noqa: F401
    data_parallel_mesh,
    hybrid_mesh,
    mesh_axis_size,
    topology_summary,
)
from .expert import (  # noqa: F401
    MoeMlp, ep_grad_sync, ep_param_specs, moe_ffn, switch_dispatch)
from .pipeline import pipeline_apply, stack_block_params  # noqa: F401
from .ring import (ring_attention, ulysses_attention,  # noqa: F401
                   zigzag_shard, zigzag_unshard)
from .tensor_parallel import (  # noqa: F401
    tp_grad_sync, tp_param_specs)
from .train import make_fsdp_train_step, make_train_step  # noqa: F401
