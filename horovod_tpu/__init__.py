"""horovod_tpu — a TPU-native distributed training framework with the
capabilities of Horovod (allreduce-based data parallelism, coordinator
negotiation with tensor fusion / response cache / autotune, timeline, stall
inspection, a ``horovodrun``-style launcher) built on JAX/XLA for the TPU
data plane and a C++ host runtime for the control plane and host tensors.

Top level exposes the framework-agnostic (numpy) API; framework bindings
live in ``horovod_tpu.jax``, ``horovod_tpu.torch``, ``horovod_tpu.keras``,
``horovod_tpu.tensorflow``, ``horovod_tpu.mxnet``.
"""

import atexit as _atexit

from .common import (  # noqa: F401
    HorovodInternalError,
    allgather,
    allgather_async,
    allreduce,
    allreduce_async,
    broadcast,
    broadcast_async,
    get_basics,
    poll,
    reduce_scatter,
    reduce_scatter_async,
    shard_partition,
    synchronize,
)
from .groups import (  # noqa: F401
    WORLD,
    ProcessGroup,
    group_rank,
    group_size,
    new_group,
)

__version__ = "0.4.0"

_initialized_here = False
_world_env = None  # launcher-injected env saved before a rank-subset remap

# Callbacks invoked after every successful init() — including elastic
# re-inits. Framework bindings use this for per-generation state that must
# restart identically on every member (e.g. the jax binding's auto-name
# counter: a survivor of an elastic shrink/regrow and a freshly spawned
# worker must generate the same collective names).
_init_callbacks = []


def register_init_callback(fn):
    """Registers `fn()` to run after every successful init()."""
    _init_callbacks.append(fn)

_TOPOLOGY_KEYS = ("HVD_TPU_RANK", "HVD_TPU_SIZE", "HVD_TPU_LOCAL_RANK",
                  "HVD_TPU_LOCAL_SIZE", "HVD_TPU_CROSS_RANK",
                  "HVD_TPU_CROSS_SIZE", "HVD_TPU_ADDRS")


def _remap_subset_env(ranks):
    """Rewrites the HVD_TPU_* env so the native core rendezvouses over the
    `ranks` sub-communicator (members) or a size-1 self communicator
    (non-members). Reference analogue: ``hvd.init(comm=[...])``
    (`horovod/common/basics.py:29-60`, `common/mpi/mpi_context.cc:128-140`,
    where MPI_Group_incl builds the subset communicator); here the subset is
    realized by re-deriving rank/size/topology from the subset's addresses.
    Non-members become independent size-1 communicators (the reference
    falls back to MPI_COMM_WORLD with a warning, which leaves the two
    groups' collectives incompatible anyway)."""
    import os

    from .run.util import topology_env

    global _world_env
    if _world_env is None:
        _world_env = {k: os.environ.get(k) for k in _TOPOLOGY_KEYS}
    else:  # re-init with a different subset: start from the world view
        for k, v in _world_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    world_rank = int(os.environ.get("HVD_TPU_RANK", "0"))
    world_size = int(os.environ.get("HVD_TPU_SIZE", "1"))
    if len(set(ranks)) != len(ranks):
        raise ValueError("duplicate entries in ranks: %r" % (ranks,))
    for r in ranks:
        if not 0 <= r < world_size:
            raise ValueError("rank %d out of range for world size %d" %
                             (r, world_size))
    if world_rank not in ranks:
        for k in _TOPOLOGY_KEYS:
            os.environ.pop(k, None)
        os.environ["HVD_TPU_RANK"] = "0"
        os.environ["HVD_TPU_SIZE"] = "1"
        return
    addrs = (os.environ.get("HVD_TPU_ADDRS") or "").split(",")
    if len(addrs) != world_size:
        raise RuntimeError(
            "HVD_TPU_ADDRS does not cover the world; cannot form a "
            "rank-subset communicator")
    sub_addrs = [addrs[r] for r in ranks]
    os.environ.update(topology_env(list(ranks).index(world_rank), sub_addrs))


def _maybe_rendezvous():
    """Dynamic rendezvous: when the launcher supplied only
    ``HVD_TPU_RENDEZVOUS_ADDR`` (no pre-assigned ``HVD_TPU_ADDRS``), bind
    a port on this host, publish it, fetch the peer table and derive the
    topology env. Reference analogue: the Gloo HTTP rendezvous
    (`horovod/run/rendezvous/http_server.py:33-205`)."""
    import os

    if os.environ.get("HVD_TPU_ADDRS"):
        return
    rdv_addr = os.environ.get("HVD_TPU_RENDEZVOUS_ADDR")
    if not rdv_addr:
        return
    if os.environ.get("HVD_TPU_ELASTIC") == "1" and \
            "HVD_TPU_RANK" not in os.environ:
        # Elastic worker: rank/size/generation come from the driver-
        # published membership, not the spawn env (they change every
        # generation; see elastic/run.py).
        from .elastic.run import bootstrap_topology
        bootstrap_topology()
    size = int(os.environ.get("HVD_TPU_SIZE", "1"))
    if size <= 1:
        return
    if "HVD_TPU_RANK" not in os.environ:
        raise RuntimeError(
            "HVD_TPU_RENDEZVOUS_ADDR and HVD_TPU_SIZE are set but "
            "HVD_TPU_RANK is missing; the launcher must inject all three "
            "(check ssh env forwarding)")
    rank = int(os.environ["HVD_TPU_RANK"])
    timeout = float(os.environ.get("HVD_TPU_START_TIMEOUT", "60"))
    generation = int(os.environ.get("HVD_TPU_GENERATION", "0") or 0)
    from .run import rendezvous as _rdv
    os.environ.update(_rdv.resolve_topology(rank, size, rdv_addr, timeout,
                                            generation=generation))


# 2-D mesh state (docs/GROUPS.md): set by init(model_parallel=k) — this
# rank's (batch, model) groups plus the mesh shape. Re-formed on every
# (re-)init: the native group table clears per generation.
_mesh = None


def init(ranks=None, model_parallel=None):
    """Initializes the core runtime (rendezvous + background thread).

    Args:
      ranks: optional list of world ranks forming the communicator (the
        reference's ``hvd.init(comm=[0, 1])`` rank-subset form,
        ``horovod/common/basics.py:29-60``). Processes whose world rank is
        not listed initialize as independent size-1 communicators and sit
        out the subset's collectives.
      model_parallel: optional model-parallel width k (docs/GROUPS.md).
        The N ranks form a (N/k, k) (batch, model) mesh: rank r sits at
        batch row r//k and model column r%k; ``batch_group()`` is the
        rank's model-COLUMN (gradient reduction runs over it — N/k
        members) and ``model_group()`` its contiguous k-rank model row
        (tensor-parallel collectives ride it). Persists through elastic
        re-inits via ``HVD_TPU_MODEL_PARALLEL`` (the env form sets it
        job-wide without a code change).

    Reference analogue: ``hvd.init()`` -> ``horovod/common/basics.py:29-60``.
    """
    import os as _os

    global _initialized_here, _world_env, _mesh
    if not is_initialized():
        _maybe_rendezvous()
    if ranks is not None and len(ranks) > 0:
        _remap_subset_env(ranks)
    elif _world_env is not None:
        # A previous init(ranks=...) remapped the env; a plain init() must
        # see the original world topology again, not the stale subset.
        import os
        for k, v in _world_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        _world_env = None
    get_basics().init()
    # The native listener has bound; drop any rendezvous port
    # reservation held across init (see rendezvous.reserve_port).
    from .run.rendezvous import release_held_ports
    release_held_ports()
    for cb in _init_callbacks:
        cb()
    # Mesh formation AFTER the callbacks (groups are per-generation; the
    # native table was cleared by the (re-)init). The env is only
    # persisted AFTER validation against the live world size, so an
    # invalid model_parallel= raises without poisoning later init()
    # retries.
    _mesh = None
    mp = int(model_parallel) if model_parallel is not None else \
        int(_os.environ.get("HVD_TPU_MODEL_PARALLEL", "1") or "1")
    if mp > 1:
        _mesh = _form_mesh(mp, explicit=model_parallel is not None)
    if model_parallel is not None:
        # Persist so elastic re-inits (plain init() calls) re-form the
        # mesh for the new membership.
        _os.environ["HVD_TPU_MODEL_PARALLEL"] = str(mp)
    # Metrics endpoint (docs/METRICS.md): serve Prometheus at
    # HVD_TPU_METRICS_PORT + rank. After the callbacks (rank may have
    # changed across an elastic re-init; the server follows its slot).
    from . import _metrics
    _metrics.on_init()
    if not _initialized_here:
        _atexit.register(shutdown)
        _initialized_here = True


def _form_mesh(k, explicit=True):
    """Registers the (batch, model) mesh groups on THIS rank (every rank
    runs the identical sequence, so ids agree; docs/GROUPS.md).

    Megatron-style layout: model groups are k CONSECUTIVE ranks (the
    fastest-moving axis — on a TPU slice, launcher-ordered neighbors
    share ICI links), batch groups are the strided columns {j, j+k, ...}.
    Registration order: all k batch groups (column 0..k-1), then all N/k
    model groups (row 0..N/k-1).
    """
    n = size()
    if n % k != 0:
        if explicit:
            raise ValueError(
                "model_parallel=%d does not divide world size %d"
                % (k, n))
        # Env-driven re-form (an elastic re-init): the model is SHARDED
        # k ways, so a membership whose size k does not divide cannot
        # host it — name the resume constraint instead of a bare
        # divisibility error mid-recovery.
        raise RuntimeError(
            "elastic membership of size %d cannot resume the "
            "model_parallel=%d mesh (size must be a multiple of k — "
            "the model is sharded k ways); resize to a multiple of %d, "
            "or unset HVD_TPU_MODEL_PARALLEL for a fresh pure-DP job "
            "(docs/GROUPS.md)" % (n, k, k))
    batch_groups = [new_group(range(j, n, k)) for j in range(k)]
    model_groups = [new_group(range(i * k, (i + 1) * k))
                    for i in range(n // k)]
    r = rank()
    return {
        "k": k,
        "batch": batch_groups[r % k],
        "model": model_groups[r // k],
        "batch_groups": batch_groups,
        "model_groups": model_groups,
    }


def model_parallel_size():
    """The mesh's model-parallel width k (1 = pure data-parallel)."""
    return _mesh["k"] if _mesh is not None else 1


def batch_group():
    """This rank's batch-axis (data-parallel) group: the N/k ranks
    holding the same model shard. Gradient allreduces run over it —
    ``DistributedOptimizer`` defaults to it when the mesh is active.
    None without ``init(model_parallel=k)``."""
    return _mesh["batch"] if _mesh is not None else None


def model_group():
    """This rank's model-axis (tensor-parallel) group: the k ranks
    forming one model replica. ``parallel.tensor_parallel``'s host-plane
    f/g collectives ride it. None without ``init(model_parallel=k)``."""
    return _mesh["model"] if _mesh is not None else None


def mesh_groups():
    """(batch_group, model_group) for this rank, or (None, None)."""
    return (batch_group(), model_group())


def shutdown():
    """Coordinated shutdown of the core runtime."""
    get_basics().shutdown()
    from . import _metrics
    _metrics.stop_server()


def metrics():
    """This worker's live metrics registry (native/metrics.h) as a
    dict: monotonic counters (cycles, tensors/bytes executed, fusion,
    cache hit/miss, stall warnings, divergence errors), gauges (queue
    depth, generation), and fixed-bucket histograms (cycle duration,
    negotiation latency, tensors/bytes per cycle, fusion fill). See
    docs/METRICS.md for the catalog."""
    from . import _metrics
    return _metrics.metrics()


def job_metrics():
    """Rank 0 only: the job-wide view — every rank's piggybacked
    summary plus the per-rank announce-lag table (the straggler
    signal). Empty dict on other ranks."""
    from . import _metrics
    return _metrics.job_metrics()


def autotune():
    """Live closed-loop tuner state (docs/AUTOTUNE.md) as a dict:
    ``active``, ``rearm_epoch``/``rearms_total``, sample count, best
    score, the synchronized knob values under ``params`` (fusion_mb,
    cycle_time_ms, pipeline_chunk_kb, cache_enabled, the three
    hierarchical toggles), which knobs env pinned under ``fixed``, the
    observed workload ``profile``, and the converged drift ``baseline``.
    Callable any time from any thread."""
    import json as _json
    return _json.loads(get_basics().autotune_json())


def is_initialized():
    return get_basics().initialized()


def rank():
    return get_basics().rank()


def local_rank():
    return get_basics().local_rank()


def cross_rank():
    return get_basics().cross_rank()


def size():
    return get_basics().size()


def local_size():
    return get_basics().local_size()


def cross_size():
    return get_basics().cross_size()


def is_homogeneous():
    return get_basics().is_homogeneous()


def tcp_built():
    return get_basics().tcp_built()


def cpu_ops_built():
    return get_basics().cpu_ops_built()


# Reference-named capability probes (horovod/common/basics.py:117-191),
# for drop-in migration: the TCP controller fills the gloo role here;
# MPI/NCCL/DDL/MLSL backends do not exist in the TPU redesign (ICI
# collectives live inside XLA programs instead — see docs/DESIGN.md).

def mpi_threads_supported():
    return False


def mpi_enabled():
    return False


def mpi_built():
    return False


def gloo_enabled():
    """True: the TCP rendezvous/controller provides the gloo-role
    host data plane."""
    return tcp_built()


def gloo_built():
    return tcp_built()


def nccl_built():
    return False


def ddl_built():
    return False


def mlsl_built():
    return False
