"""horovod_tpu — a TPU-native distributed training framework with the
capabilities of Horovod (allreduce-based data parallelism, coordinator
negotiation with tensor fusion / response cache / autotune, timeline, stall
inspection, a ``horovodrun``-style launcher) built on JAX/XLA for the TPU
data plane and a C++ host runtime for the control plane and host tensors.

Top level exposes the framework-agnostic (numpy) API; framework bindings
live in ``horovod_tpu.jax``, ``horovod_tpu.torch``, ``horovod_tpu.keras``,
``horovod_tpu.tensorflow``, ``horovod_tpu.mxnet``.
"""

import atexit as _atexit

from .common import (  # noqa: F401
    HorovodInternalError,
    allgather,
    allgather_async,
    allreduce,
    allreduce_async,
    broadcast,
    broadcast_async,
    get_basics,
    poll,
    synchronize,
)

__version__ = "0.1.0"

_initialized_here = False


def init():
    """Initializes the core runtime (rendezvous + background thread).

    Reference analogue: ``hvd.init()`` -> ``horovod/common/basics.py:29-60``.
    """
    global _initialized_here
    get_basics().init()
    if not _initialized_here:
        _atexit.register(shutdown)
        _initialized_here = True


def shutdown():
    """Coordinated shutdown of the core runtime."""
    get_basics().shutdown()


def is_initialized():
    return get_basics().initialized()


def rank():
    return get_basics().rank()


def local_rank():
    return get_basics().local_rank()


def cross_rank():
    return get_basics().cross_rank()


def size():
    return get_basics().size()


def local_size():
    return get_basics().local_size()


def cross_size():
    return get_basics().cross_size()


def is_homogeneous():
    return get_basics().is_homogeneous()


def tcp_built():
    return get_basics().tcp_built()


def cpu_ops_built():
    return get_basics().cpu_ops_built()
