"""Keras callback implementations (reference:
``horovod/_keras/callbacks.py``): broadcast-on-start, metric averaging,
LR warmup/schedule with momentum correction."""

import horovod_tpu as hvd
from . import average_metrics, broadcast_model_weights


class BroadcastGlobalVariablesCallbackImpl:
    """Broadcasts initial model (and optimizer) state from root at train
    start so all ranks begin identical (reference: callbacks.py:20-43)."""

    def __init__(self, backend, root_rank, *args):
        super().__init__(*args)
        self.backend = backend
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_batch_begin(self, batch, logs=None):
        if self.broadcast_done:
            return
        broadcast_model_weights(self.model, self.root_rank)
        if hasattr(self.model, "optimizer") and \
                hasattr(self.model.optimizer, "variables"):
            import numpy as np
            for i, v in enumerate(self.model.optimizer.variables):
                try:
                    val = np.asarray(v)
                except Exception:
                    continue
                if val.dtype.kind in "fiu" and val.size:
                    out = np.asarray(hvd.broadcast(
                        np.ascontiguousarray(val), self.root_rank,
                        "keras_bc_opt.%d" % i)).reshape(val.shape)
                    v.assign(out)
        self.broadcast_done = True


class MetricAverageCallbackImpl:
    """Averages epoch-end metrics over ranks so rank-0 logging/checkpoint
    decisions see global values (reference: callbacks.py:46-84)."""

    def __init__(self, backend, *args):
        super().__init__(*args)
        self.backend = backend

    def on_epoch_end(self, epoch, logs=None):
        if logs:
            average_metrics(logs, prefix="metric.e%d" % epoch)


class LearningRateScheduleCallbackImpl:
    """Multiplies the initial LR by `multiplier` (callable or const) over
    [start_epoch, end_epoch) (reference: callbacks.py:87-145)."""

    def __init__(self, backend, multiplier, start_epoch=0, end_epoch=None,
                 staircase=True, momentum_correction=True, steps_per_epoch=None,
                 *args):
        super().__init__(*args)
        self.backend = backend
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.initial_lr = None
        self.restore_momentum = None
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        if not callable(multiplier):
            self.staircase = True
            self.multiplier = lambda epoch: multiplier
        else:
            self.multiplier = multiplier

    def _lr_var(self):
        return self.model.optimizer.learning_rate

    def _get_lr(self):
        lr = self._lr_var()
        return float(lr.numpy()) if hasattr(lr, "numpy") else float(lr)

    def _adjust(self, epoch):
        if self.initial_lr is None:
            self.initial_lr = self._get_lr()
        within = epoch >= self.start_epoch and \
            (self.end_epoch is None or epoch < self.end_epoch)
        if not within:
            return
        old_lr = self._get_lr()
        lr = self.initial_lr * self.multiplier(epoch)
        opt = self.model.optimizer
        if hasattr(opt.learning_rate, "assign"):
            opt.learning_rate.assign(lr)
        else:
            opt.learning_rate = lr
        # Momentum correction (Goyal et al.): when the LR changes, scale
        # SGD momentum by new_lr/old_lr for the next step, then restore
        # (reference: _keras/callbacks.py _adjust_learning_rate).
        if self.momentum_correction and old_lr > 0 and \
                hasattr(opt, "momentum") and isinstance(
                    getattr(opt, "momentum", None), (int, float)):
            if self.restore_momentum is None:
                self.restore_momentum = float(opt.momentum)
            opt.momentum = self.restore_momentum * lr / old_lr

    def _restore_momentum_if_needed(self):
        if self.restore_momentum is not None:
            self.model.optimizer.momentum = self.restore_momentum
            self.restore_momentum = None

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase:
            self._adjust(epoch)

    def on_batch_begin(self, batch, logs=None):
        if not self.staircase:
            if self.steps_per_epoch is None:
                # Keras populates params['steps'] once fit() starts.
                self.steps_per_epoch = (self.params or {}).get("steps")
            if self.steps_per_epoch:
                self._adjust(self.current_epoch +
                             float(batch) / self.steps_per_epoch)
            else:
                # No step count available: fall back to per-epoch
                # (staircase) adjustment rather than silently never
                # warming up.
                self._adjust(self.current_epoch)

    def on_batch_end(self, batch, logs=None):
        self._restore_momentum_if_needed()

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None and self.initial_lr is not None:
            lr = self.model.optimizer.learning_rate
            logs["lr"] = float(lr.numpy()) if hasattr(lr, "numpy") \
                else float(lr)


class LearningRateWarmupCallbackImpl(LearningRateScheduleCallbackImpl):
    """Gradual LR warmup from lr to lr*size over `warmup_epochs`
    (reference: callbacks.py:148-185 — the Goyal et al. recipe)."""

    def __init__(self, backend, warmup_epochs=5, momentum_correction=True,
                 steps_per_epoch=None, verbose=0, *args):
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose

        def multiplier(epoch):
            # epoch may be fractional (per-batch warmup).
            if epoch >= self.warmup_epochs:
                return hvd.size()
            return 1.0 + (hvd.size() - 1.0) * epoch / self.warmup_epochs

        super().__init__(backend, multiplier, start_epoch=0,
                         end_epoch=self.warmup_epochs + 1, staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch, *args)

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.warmup_epochs - 1 and self.verbose and \
                hvd.rank() == 0 and self.initial_lr is not None:
            print("\nEpoch %d: finished gradual learning rate warmup to "
                  "%g." % (epoch + 1, self.initial_lr * hvd.size()))
