"""Shared Keras implementation layer (reference:
``horovod/_keras/__init__.py`` — the common code behind both the
standalone-Keras and tf.keras public shells).

Keras-3 era: optimizers expose ``apply_gradients`` and models expose
numpy ``get_weights``/``set_weights``, so the collectives ride the
framework-agnostic numpy core directly.
"""

import numpy as np

import horovod_tpu as hvd


_distributed_class_cache = {}


def distributed_optimizer_class(base, compression=None, average=True,
                                group=None):
    """The dynamic `Distributed<Base>` optimizer CLASS — split from
    instance creation so load_model can hand these to keras
    deserialization as custom_objects (reference:
    _keras/__init__.py:107-123 load_model's custom-object wrapping).
    Cached per (base, compression, average, group) so repeated
    load_model calls reuse identical classes. `group` scopes the
    gradient averaging to a process group (docs/GROUPS.md); it defaults
    to this rank's batch group under hvd.init(model_parallel=k)."""
    key = (base, compression, average, group)
    cached = _distributed_class_cache.get(key)
    if cached is not None:
        return cached

    class _DistributedOptimizer(base):
        _HVD_WRAPPED = True

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            import tensorflow as tf
            from horovod_tpu import tensorflow as hvd_tf
            grp = group if group is not None else hvd.batch_group()
            grads_and_vars = list(grads_and_vars)
            reduced = []
            for i, (g, v) in enumerate(grads_and_vars):
                if g is not None:
                    comp = compression or hvd_tf.Compression.none
                    # Group-scoped allreduce needs dense tensors; the
                    # Keras surface has no sparse_as_dense knob, so
                    # densify sparse grads under a group — LOUDLY: a
                    # big embedding's IndexedSlices becomes a
                    # full-table dense allreduce per step.
                    sparse_dense = grp is not None
                    if sparse_dense and isinstance(g, tf.IndexedSlices):
                        import warnings
                        warnings.warn(
                            "group-scoped Keras optimizer densifies "
                            "IndexedSlices gradient %d (full-table "
                            "allreduce per step — docs/GROUPS.md); "
                            "consider a dense embedding or the jax "
                            "binding's sparse plane" % i,
                            stacklevel=2)
                    g = hvd_tf.allreduce(
                        g, average=average, name="keras_grad.%d" % i,
                        compression=comp,
                        sparse_as_dense=sparse_dense, group=grp)
                    g = tf.convert_to_tensor(g) if isinstance(
                        g, tf.IndexedSlices) else g
                reduced.append((g, v))
            return super().apply_gradients(reduced, *args, **kwargs)

    cls = type("Distributed%s" % base.__name__, (_DistributedOptimizer,),
               {})
    _distributed_class_cache[key] = cls
    return cls


def create_distributed_optimizer(keras, optimizer, name=None,
                                 compression=None, average=True,
                                 group=None):
    """Dynamically subclasses `optimizer` so apply_gradients first
    allreduces gradients (reference: _keras/__init__.py:20-80)."""
    cls = distributed_optimizer_class(optimizer.__class__,
                                      compression=compression,
                                      average=average, group=group)
    return cls.from_config(optimizer.get_config())


def broadcast_model_weights(model, root_rank=0):
    """Broadcasts model weights from root via the numpy core."""
    weights = model.get_weights()
    out = []
    for i, w in enumerate(weights):
        arr = np.ascontiguousarray(w)
        out.append(np.asarray(hvd.broadcast(
            arr, root_rank, "keras_bc.%d" % i)).reshape(w.shape))
    model.set_weights(out)


def average_metrics(logs, prefix="metric"):
    """Allreduce-averages every scalar in a Keras `logs` dict (reference:
    MetricAverageCallbackImpl, _keras/callbacks.py:46-84)."""
    if not logs:
        return logs
    for key in sorted(logs):
        value = logs[key]
        if isinstance(value, (int, float, np.floating, np.integer)):
            arr = np.asarray(float(value), dtype=np.float64)
            logs[key] = float(hvd.allreduce(
                arr, "%s.%s" % (prefix, key), average=True))
    return logs
