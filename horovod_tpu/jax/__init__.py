"""JAX binding — the TPU-native flagship API.

Two data planes, selected automatically:

* **In-jit (TPU path)**: inside ``jit``/``shard_map``/``pmap`` with a mapped
  axis, collectives lower to XLA ``AllReduce``/``AllGather``/
  ``CollectiveBroadcast`` over ICI — the TPU analogue of the reference's
  NCCL plane (/root/reference horovod/common/ops/nccl_operations.cc). XLA
  fuses and schedules them; no host round trip.
* **Host path**: on concrete arrays outside jit, tensors ride the C++ core
  (negotiation, fusion, response cache) exactly like the reference's CPU
  path (ops/mpi_operations.cc / gloo_operations.cc) — used for parameter
  broadcast, eager-style code, and cross-host DCN traffic.

API parity with the reference framework bindings
(``horovod/tensorflow/__init__.py``, ``horovod/torch/__init__.py``):
``init/rank/size/allreduce/allgather/broadcast``, ``DistributedOptimizer``
(optax), ``broadcast_parameters``, ``Compression``.
"""

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.compat import ensure_jax_compat as _ensure_jax_compat

_ensure_jax_compat()

import horovod_tpu as _hvd
from horovod_tpu import compression as _wire
from horovod_tpu import (  # noqa: F401
    init, shutdown, is_initialized, rank, local_rank, cross_rank, size,
    local_size, cross_size, is_homogeneous,
    mpi_threads_supported, mpi_enabled, mpi_built, gloo_enabled,
    gloo_built, nccl_built, ddl_built, mlsl_built,
)
# Elastic API: hvd.elastic.run / hvd.elastic.ElasticState (reference
# analogue: horovod.tensorflow.elastic).
from horovod_tpu import elastic  # noqa: F401
from horovod_tpu.common import ops as _ops

# Default mapped-axis name for the in-jit data plane.
AXIS_NAME = "hvd"

_name_counter = [0]


def _auto_name(prefix):
    _name_counter[0] += 1
    return "%s.%d" % (prefix, _name_counter[0])


def _reset_auto_names():
    """Generation reset: auto-generated collective names must restart
    from the same counter on every member after (re-)init. Without this,
    a survivor of an elastic shrink/regrow keeps its old count while a
    freshly spawned worker starts at zero — the two negotiate different
    names for the same call site and the job hangs (the divergence
    cross-check reports it; this removes the cause)."""
    _name_counter[0] = 0
    _assert_counter[0] = 0


_hvd.register_init_callback(_reset_auto_names)


def _is_traced(x):
    return isinstance(x, jax.core.Tracer)


def _axis_in_scope(axis_name):
    try:
        jax.lax.axis_index(axis_name)
        return True
    except NameError:
        return False
    except Exception:
        return False


def _multi_process():
    return _hvd.is_initialized() and _hvd.size() > 1


def _require_init_traced():
    """A collective traced in plain jit (no mapped axis) before ``init()``
    must fail loudly — silently degrading to identity would let a
    multi-process program train unsynchronized. (The in-jit mapped-axis
    plane needs no init: it is pure XLA.)"""
    if not _hvd.is_initialized():
        raise RuntimeError(
            "horovod_tpu collective used inside jit before hvd.init(); "
            "call init() first (single-process size-1 init is fine)")


def _host_callback(fn, tensor):
    """Routes a traced tensor through the host core from inside jit.

    ``ordered=True`` is required for deadlock freedom: every rank traces
    the same program, so ordered callbacks enqueue collectives in the same
    sequence on all ranks while each callback blocks on its completion.
    """
    from jax.experimental import io_callback
    out_shape = jax.ShapeDtypeStruct(tensor.shape, tensor.dtype)
    return io_callback(fn, out_shape, tensor, ordered=True)


class Compression:
    """Gradient compression codecs (reference: tensorflow/compression.py).

    Two families share this namespace:

    * legacy tensor codecs (``fp16``/``bf16`` classes below) cast the
      TENSOR before the collective and back after — reduction then
      accumulates in the narrow dtype;
    * wire modes (``wire_bf16``/``wire_int8``, =
      ``horovod_tpu.compression.Compression``) re-encode only the bytes
      each transport hop moves, keeping the f32 accumulator — the
      preferred, negotiated, cache-keyed path (docs/COMPRESSION.md).
      Strings ('bf16', 'int8') and ``HVD_TPU_COMPRESSION`` select these.
    """

    # Wire modes (docs/COMPRESSION.md): negotiated per tensor, f32
    # accumulation, selectable by string everywhere compression= is
    # accepted.
    wire_bf16 = _wire.Compression.bf16
    wire_int8 = _wire.Compression.int8

    class none:
        @staticmethod
        def compress(tensor):
            return tensor, None

        @staticmethod
        def decompress(tensor, ctx):
            return tensor

    class fp16:
        @staticmethod
        def compress(tensor):
            if tensor.dtype in (jnp.float32, jnp.float64):
                return tensor.astype(jnp.float16), tensor.dtype
            return tensor, None

        @staticmethod
        def decompress(tensor, ctx):
            return tensor.astype(ctx) if ctx is not None else tensor

    class bf16:
        """bfloat16 — the native TPU 16-bit format; preferred on TPU."""

        @staticmethod
        def compress(tensor):
            if tensor.dtype in (jnp.float32, jnp.float64):
                return tensor.astype(jnp.bfloat16), tensor.dtype
            return tensor, None

        @staticmethod
        def decompress(tensor, ctx):
            return tensor.astype(ctx) if ctx is not None else tensor


def allreduce(tensor, average=True, name=None, axis_name=AXIS_NAME,
              compression=None, prescale_factor=1.0,
              postscale_factor=1.0, group=None):
    """Allreduce across ranks (and, in-jit, across the mapped axis).

    ``group``: a ``hvd.ProcessGroup`` scoping the HOST-plane collective
    to a subgroup (docs/GROUPS.md) — e.g. ``hvd.batch_group()`` under
    ``init(model_parallel=k)``. The in-jit mapped-axis plane expresses
    subgroups through MESH AXES instead (psum over the batch or model
    axis of a 2-D mesh); ``group`` is ignored there.

    ``compression``: a wire mode ('none'/'bf16'/'int8', a
    ``horovod_tpu.compression`` mode, or None = HVD_TPU_COMPRESSION) —
    or a legacy tensor codec (``Compression.fp16``/``.bf16``), which
    keeps its historical cast-the-tensor semantics. Wire modes keep f32
    accumulation on both data planes: in-jit, bf16 and int8 run the
    EQuARX-style ``ring_allreduce`` with encode/decode fused into each
    hop (narrow bytes on the link, f32 dequant-add); on the host plane
    the mode rides the negotiation into the native ring
    (docs/COMPRESSION.md).
    """
    legacy = compression is not None and hasattr(compression, "compress")
    mode = _wire.Compression.none if legacy else _wire.resolve(compression)
    if _is_traced(tensor):
        if _axis_in_scope(axis_name):
            # XLA/ICI plane. none/legacy: psum over the mapped axis; XLA
            # emits an AllReduce that rides the TPU interconnect.
            compressed, ctx = (compression.compress(tensor) if legacy
                               else (tensor, None))
            if prescale_factor != 1.0:
                compressed = compressed * prescale_factor
            if mode.mode != _wire.NONE and \
                    compressed.dtype == jnp.float32:
                # Compressed modes ride the explicit ppermute ring: each
                # hop ships the narrow payload but dequantizes and ADDS
                # IN F32, preserving the f32-accumulation contract. (A
                # bf16-operand psum would NOT: XLA's AllReduce reduction
                # computation for a bf16 operand is add(bf16,bf16), so
                # every pairwise add rounds — error grows with world
                # size.)
                from horovod_tpu.parallel.ring import ring_allreduce
                summed = ring_allreduce(compressed, axis_name,
                                        compression=mode)
            else:
                summed = jax.lax.psum(compressed, axis_name)
            if average:
                summed = summed / jax.lax.psum(1, axis_name)
            if postscale_factor != 1.0:
                summed = summed * postscale_factor
            return compression.decompress(summed, ctx) if legacy \
                else summed.astype(tensor.dtype)
        if _multi_process():
            # Plain jit, no mapped axis: ride the host core via an ordered
            # callback (the reference's "CPU op inside the graph" shape).
            op_name = name or _auto_name("allreduce")

            def _cb(arr):
                return np.asarray(_ops.allreduce(
                    np.asarray(arr), op_name, average=average,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                    compression=mode, group=group)).astype(arr.dtype)

            compressed, ctx = (compression.compress(tensor) if legacy
                               else (tensor, None))
            reduced = _host_callback(_cb, compressed)
            return compression.decompress(reduced, ctx) if legacy \
                else reduced
        _require_init_traced()
        # Single process: allreduce is identity up to scaling.
        scale = prescale_factor * postscale_factor
        return tensor * scale if scale != 1.0 else tensor
    compressed, ctx = (compression.compress(tensor) if legacy
                       else (tensor, None))
    arr = np.asarray(compressed)
    out = _ops.allreduce(arr, name or _auto_name("allreduce"),
                         average=average, prescale_factor=prescale_factor,
                         postscale_factor=postscale_factor,
                         compression=mode, group=group)
    result = jnp.asarray(out)
    return compression.decompress(result, ctx) if legacy else result


def reduce_scatter(tensor, average=True, name=None, axis_name=AXIS_NAME,
                   compression=None, prescale_factor=1.0,
                   postscale_factor=1.0, group=None):
    """Reduce-scatter across ranks (docs/ZERO.md): the tensor is
    flattened, summed (or averaged) across ranks, and this rank keeps
    only its 1/N shard of the result — the gradient leg of the sharded
    weight update.

    In-jit over a mapped axis the flat tensor must divide evenly by the
    axis size (pad first; ``parallel.ring.ring_reduce_scatter`` handles
    padding and the compressed per-hop ring). On the host plane the
    shard partition is :func:`horovod_tpu.shard_partition` (uneven sizes
    allowed). Returns a 1-D array.
    """
    mode = _wire.resolve(compression)
    if _is_traced(tensor):
        if _axis_in_scope(axis_name):
            from horovod_tpu.parallel.ring import ring_reduce_scatter
            flat = tensor.reshape(-1)
            if prescale_factor != 1.0:
                flat = flat * prescale_factor
            shard = ring_reduce_scatter(flat, axis_name, compression=mode)
            if average:
                shard = shard / jax.lax.psum(1, axis_name)
            if postscale_factor != 1.0:
                shard = shard * postscale_factor
            return shard.astype(tensor.dtype)
        if _multi_process():
            from jax.experimental import io_callback

            from horovod_tpu import groups as _grp
            op_name = name or _auto_name("reduce_scatter")
            counts, _ = _ops.shard_partition(
                int(np.prod(tensor.shape, dtype=np.int64)),
                _grp.group_size(group))
            my_count = counts[_grp.group_rank(group)]

            def _cb(arr):
                return np.asarray(_ops.reduce_scatter(
                    np.asarray(arr), op_name, average=average,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                    compression=mode, group=group)).astype(arr.dtype)

            out_shape = jax.ShapeDtypeStruct((my_count,), tensor.dtype)
            return io_callback(_cb, out_shape, tensor, ordered=True)
        _require_init_traced()
        scale = prescale_factor * postscale_factor
        flat = tensor.reshape(-1)
        return flat * scale if scale != 1.0 else flat
    arr = np.asarray(tensor)
    out = _ops.reduce_scatter(arr, name or _auto_name("reduce_scatter"),
                              average=average,
                              prescale_factor=prescale_factor,
                              postscale_factor=postscale_factor,
                              compression=mode, group=group)
    return jnp.asarray(out)


def allgather(tensor, name=None, axis_name=AXIS_NAME, group=None):
    """Concatenates tensors from all ranks along dim 0.

    In plain jit without a mapped axis, all ranks must pass equal shapes
    (the host path outside jit supports unequal first dims, like the
    reference's allgatherv)."""
    if _is_traced(tensor):
        if _axis_in_scope(axis_name):
            return jax.lax.all_gather(tensor, axis_name, tiled=True)
        if _multi_process():
            from jax.experimental import io_callback

            from horovod_tpu import groups as _grp
            op_name = name or _auto_name("allgather")
            if tensor.ndim == 0:  # match the host path's 0-d -> (1,)
                tensor = tensor.reshape(1)

            def _cb(arr):
                return np.asarray(
                    _ops.allgather(np.asarray(arr), op_name, group=group))

            shape = (tensor.shape[0] * _grp.group_size(group),) + \
                tuple(tensor.shape[1:])
            out_shape = jax.ShapeDtypeStruct(shape, tensor.dtype)
            return io_callback(_cb, out_shape, tensor, ordered=True)
        _require_init_traced()
        return tensor
    arr = np.asarray(tensor)
    out = _ops.allgather(arr, name or _auto_name("allgather"), group=group)
    return jnp.asarray(out)


def broadcast(tensor, root_rank=0, name=None, axis_name=AXIS_NAME,
              group=None):
    """Broadcasts the root rank's tensor — or pytree of tensors,
    leaf-wise with order-stable names — to every rank (the group's
    members under ``group=``; ``root_rank`` stays a WORLD rank)."""
    leaves, treedef = jax.tree_util.tree_flatten(tensor)
    if len(leaves) != 1 or leaves[0] is not tensor:
        base = name or _auto_name("broadcast")
        out = [_broadcast_one(leaf, root_rank, "%s.%d" % (base, i),
                              axis_name, group)
               for i, leaf in enumerate(leaves)]
        return jax.tree_util.tree_unflatten(treedef, out)
    return _broadcast_one(tensor, root_rank, name, axis_name, group)


def _broadcast_one(tensor, root_rank, name, axis_name, group=None):
    if _is_traced(tensor):
        if _axis_in_scope(axis_name):
            # In-jit: mask every rank but the root to zero and psum — XLA
            # lowers this to a select+AllReduce with O(tensor) memory per
            # rank, vs. the N x tensor an all_gather would materialize.
            idx = jax.lax.axis_index(axis_name)
            masked = jnp.where(idx == root_rank, tensor,
                               jnp.zeros_like(tensor))
            # psum promotes bool to int32; cast back (no-op otherwise).
            return jax.lax.psum(masked, axis_name).astype(tensor.dtype)
        if _multi_process():
            op_name = name or _auto_name("broadcast")

            def _cb(arr):
                return np.asarray(_ops.broadcast(
                    np.asarray(arr), root_rank, op_name,
                    group=group)).astype(arr.dtype)

            return _host_callback(_cb, tensor)
        _require_init_traced()
        return tensor
    arr = np.asarray(tensor)
    out = _ops.broadcast(arr, root_rank, name or _auto_name("broadcast"),
                         group=group)
    return jnp.asarray(out)


def allreduce_gradients(grads, average=True, name_prefix="grad",
                        compression=None, axis_name=AXIS_NAME, group=None):
    """Allreduces a pytree of gradients (order-stable naming so all ranks
    negotiate the same tensors). ``compression`` as in :func:`allreduce`
    (wire modes negotiate per leaf; the core fuses same-mode leaves into
    one ring pass). ``group`` scopes the reduction — under a 2-D mesh
    this is the BATCH group: gradients average over the ranks sharing
    this model shard only (docs/GROUPS.md)."""
    legacy = compression is not None and hasattr(compression, "compress")
    mode = _wire.Compression.none if legacy else _wire.resolve(compression)
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if leaves and _is_traced(leaves[0]):
        reduced = [allreduce(g, average=average, axis_name=axis_name,
                             compression=compression, group=group)
                   for g in leaves]
        return jax.tree_util.tree_unflatten(treedef, reduced)
    # Host path: enqueue everything first so the core can fuse within a
    # cycle, then synchronize in order.
    from horovod_tpu import groups as _grp
    handles = []
    for i, g in enumerate(leaves):
        comp, ctx = compression.compress(g) if legacy else (g, None)
        arr = np.asarray(comp)
        postscale = 1.0 / _grp.group_size(group) if average else 1.0
        handles.append((_ops.allreduce_async(arr, "%s.%d" % (name_prefix, i),
                                             postscale_factor=postscale,
                                             compression=mode, group=group),
                        ctx))
    reduced = []
    for h, ctx in handles:
        out = jnp.asarray(_ops.synchronize(h))
        reduced.append(compression.decompress(out, ctx) if legacy else out)
    return jax.tree_util.tree_unflatten(treedef, reduced)


def broadcast_parameters(params, root_rank=0, name_prefix="param"):
    """Broadcasts a pytree of parameters from root (consistent init /
    checkpoint restore; reference: torch/__init__.py:255-284)."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    handles = []
    for i, p in enumerate(leaves):
        arr = np.asarray(p)
        handles.append(
            _ops.broadcast_async(arr, root_rank, "%s.%d" % (name_prefix, i)))
    out = [jnp.asarray(_ops.synchronize(h)) for h in handles]
    # Preserve original dtypes (e.g. bf16 params round-trip exactly).
    out = [o.astype(l.dtype) if hasattr(l, "dtype") else o
           for o, l in zip(out, leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


def broadcast_optimizer_state(opt_state, root_rank=0,
                              name_prefix="opt_state"):
    """Broadcasts an optax optimizer state pytree from root."""
    return broadcast_parameters(opt_state, root_rank=root_rank,
                                name_prefix=name_prefix)


def DistributedOptimizer(optimizer, compression=None,
                         average=True, name_prefix="grad",
                         axis_name=AXIS_NAME, sharded_update=None,
                         group=None, agc=None):
    """Wraps an optax GradientTransformation so every update first averages
    gradients across ranks (reference: _DistributedOptimizer,
    tensorflow/__init__.py:231-258).

    Works both inside a jitted+shard_map'd step (psum plane) and eagerly on
    host arrays (core plane). ``compression='bf16'``/``'int8'`` (or
    ``HVD_TPU_COMPRESSION``) shrinks the gradient bytes every hop moves
    — see :func:`allreduce` and docs/COMPRESSION.md, including when NOT
    to compress (integer/embedding gradients; hvd-lint flags those).

    ``sharded_update=True`` (job-wide: ``HVD_TPU_SHARDED_UPDATE=1``)
    switches the host plane to the ZeRO-style sharded weight update
    (docs/ZERO.md): gradients are flattened into ONE fused buffer and
    reduce-scattered (same wire bytes as the allreduce they replace —
    the ring's reduce-scatter leg runs either way), the optimizer
    applies only to this rank's 1/N shard — so momentum/Adam state
    shrinks N-fold — and updated parameter shards are allgathered back.
    Numerically identical to the replicated path for ELEMENTWISE
    transforms (sgd/momentum/adam/adamw...). Mixed sharded/replicated
    ranks are rejected at negotiation naming both ranks and modes. For
    the in-jit XLA plane use ``parallel.make_train_step(zero1=True)``
    instead. The optimizer state it returns is RANK-LOCAL — read it
    through :func:`sharded_state_full` (hvd-lint rule
    ``sharded-update-rank-local-param-read`` flags direct reads).

    ``group`` scopes the gradient reduction to a process group; under
    ``hvd.init(model_parallel=k)`` it DEFAULTS to this rank's batch
    group, so a mesh job's gradients average over the ranks sharing its
    model shard without any call-site change (docs/GROUPS.md).

    ``agc`` enables adaptive gradient clipping at the given clipping
    factor (e.g. 0.01 — ``ops/agc.py``, arxiv 2102.06171): each
    parameter's reduced gradient is unit-wise clipped against the
    parameter's own norm BEFORE the inner optimizer. This is what makes
    the norm-free zoo variants (``resnet50nf``/``resnet101nf`` — the
    measured-fastest conv route, PERF.md) trainable; it requires
    ``update(grads, state, params)`` and is rejected under
    ``sharded_update`` (1/N flat shards destroy the unit structure).
    """
    import optax

    if sharded_update is None:
        sharded_update = _ops.sharded_update_default()
    if sharded_update:
        if agc is not None:
            raise ValueError(
                "agc= does not compose with sharded_update: the sharded "
                "path updates 1/N flat shards, which destroys the "
                "per-unit (output-row) norm structure AGC clips against "
                "— every rank would clip a different slice of each "
                "filter. Use replicated updates with AGC, or chain "
                "optax.adaptive_grad_clip equivalents before a "
                "replicated optimizer")
        from horovod_tpu.groups import assert_sharded_update_world_scope
        assert_sharded_update_world_scope(group)
        return _sharded_distributed_optimizer(optimizer, compression,
                                              average, name_prefix)

    def init_fn(params):
        return optimizer.init(params)

    def update_fn(updates, state, params=None):
        # group=None resolves to the CURRENT batch group per update:
        # construction-time capture would go stale across elastic
        # re-inits (the mesh re-forms with fresh ids).
        grp = group if group is not None else _hvd.batch_group()
        updates = allreduce_gradients(updates, average=average,
                                      name_prefix=name_prefix,
                                      compression=compression,
                                      axis_name=axis_name, group=grp)
        if agc is not None:
            # Clip AFTER the reduction: the threshold applies to the
            # true global gradient, and every rank clips identically.
            from horovod_tpu.ops.agc import agc_clip
            if params is None:
                raise ValueError(
                    "agc= needs params: call update(grads, state, "
                    "params) — the clip threshold is relative to each "
                    "parameter's unit-wise norm")
            updates = agc_clip(updates, params, clipping=agc)
        return optimizer.update(updates, state, params)

    return optax.GradientTransformation(init_fn, update_fn)


def _flat_f32_concat(tree):
    """Flattens a pytree of arrays into one f32 vector (the Python-level
    fusion buffer: leaf offsets in flatten order ARE the shard
    boundaries' coordinate system)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return np.zeros(0, np.float32), leaves, treedef
    flat = np.concatenate(
        [np.ravel(np.asarray(l)).astype(np.float32) for l in leaves])
    return flat, leaves, treedef


def _report_opt_state_bytes(inner_state):
    """Reports this rank's optimizer-state bytes into the native
    opt_state_bytes gauge (docs/ZERO.md — the memory claim, observable
    in hvd-top and the bench A/B)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(inner_state):
        arr = np.asarray(leaf)
        total += arr.nbytes
    _hvd.get_basics().opt_state_metrics(total)


def _sharded_distributed_optimizer(optimizer, compression, average,
                                   name_prefix):
    """The sharded_update host-plane transformation (docs/ZERO.md).

    State layout: ``{"inner": <optimizer state over this rank's flat
    shard>, "total": <flat element count>, "world": <world size it was
    sharded for>, "rank": <owning rank>}``. The inner state's array
    leaves are SHARDS — 1/N of each momentum/Adam moment.
    """
    import optax

    mode = _wire.resolve_wire_arg(compression, Compression.none)

    def _my_shard(flat):
        counts, offsets = _ops.shard_partition(flat.size, _hvd.size())
        r = _hvd.rank()
        return flat[offsets[r]:offsets[r] + counts[r]]

    def init_fn(params):
        flat, _, _ = _flat_f32_concat(params)
        inner = optimizer.init(jnp.asarray(_my_shard(flat)))
        _report_opt_state_bytes(inner)
        return {"inner": inner, "total": int(flat.size),
                "world": _hvd.size(), "rank": _hvd.rank()}

    def update_fn(updates, state, params=None):
        # Re-checked per update: a mesh formed AFTER the optimizer was
        # built must fail here, not silently reduce-scatter the fused
        # buffer across model shards.
        from horovod_tpu.groups import assert_sharded_update_world_scope
        assert_sharded_update_world_scope()
        if params is None:
            raise ValueError(
                "sharded_update needs params: call update(grads, state, "
                "params) — the updated shard is params + update")
        if state["world"] != _hvd.size() or state["rank"] != _hvd.rank():
            raise RuntimeError(
                "sharded optimizer state was built for rank %d of %d but "
                "this process is rank %d of %d; after an elastic resize "
                "restore the last COMMITTED full-form state (the old "
                "membership's shards are gone) and re-shard it via "
                "sharded_state_shard() (docs/ZERO.md)"
                % (state["rank"], state["world"], _hvd.rank(), _hvd.size()))
        flat_g, _, _ = _flat_f32_concat(updates)
        if flat_g.size != state["total"]:
            raise ValueError("gradient tree has %d elements; state was "
                             "built for %d" % (flat_g.size, state["total"]))
        # ONE fused reduce-scatter over the flat gradient buffer. The
        # name deliberately matches the replicated path's first per-leaf
        # allreduce ("<prefix>.0") so a sharded rank meeting a replicated
        # peer collides at negotiation and is rejected naming both ranks
        # and modes (docs/ZERO.md) instead of hanging.
        g_shard = np.asarray(_ops.reduce_scatter(
            flat_g, "%s.0" % name_prefix, average=average,
            compression=mode))
        flat_p, p_leaves, treedef = _flat_f32_concat(params)
        p_shard = _my_shard(flat_p)
        u_shard, inner = optimizer.update(
            jnp.asarray(g_shard), state["inner"], jnp.asarray(p_shard))
        new_shard = p_shard + np.asarray(u_shard, np.float32)
        # Allgather of updated parameter shards: rank order == chunk
        # order, so the concatenation IS the full flat parameter vector.
        full_new = np.asarray(_ops.allgather(
            new_shard, "%s.param_ag" % name_prefix))
        _report_opt_state_bytes(inner)
        out_leaves = []
        off = 0
        for leaf in p_leaves:
            arr = np.asarray(leaf)
            seg = full_new[off:off + arr.size].reshape(arr.shape)
            off += arr.size
            out_leaves.append(jnp.asarray(
                (seg - arr.astype(np.float32)).astype(arr.dtype)))
        new_state = {"inner": inner, "total": state["total"],
                     "world": state["world"], "rank": state["rank"]}
        return jax.tree_util.tree_unflatten(treedef, out_leaves), new_state

    return optax.GradientTransformation(init_fn, update_fn)


def sharded_state_full(state, name_prefix="shard_state"):
    """Materializes a sharded optimizer state (from
    ``DistributedOptimizer(sharded_update=True)``) as its FULL,
    world-size-independent form: every shard-shaped array leaf of the
    inner state is allgathered into the full flat array; scalar leaves
    (Adam's step count) pass through. This is a COLLECTIVE — call it on
    every rank at the same point (a checkpoint/commit boundary).

    The result re-shards to ANY world size via
    :func:`sharded_state_shard`, which is how sharded state rides the
    durable checkpoint layer's re-shard-on-restore contract
    (docs/ZERO.md). Idempotent: a state already in full form is
    returned unchanged (no collective)."""
    if state["world"] == -1:
        return state
    if state["world"] != _hvd.size() or state["rank"] != _hvd.rank():
        # The old membership's shards no longer exist anywhere:
        # allgathering over the CURRENT ranks would reassemble a short
        # buffer and silently label it full. Only the membership that
        # built the shards can materialize them.
        raise RuntimeError(
            "sharded optimizer state was built for rank %d of %d but "
            "this process is rank %d of %d; the full form can only be "
            "materialized by the membership that built the shards — "
            "restore the last COMMITTED full-form state instead "
            "(docs/ZERO.md)"
            % (state["rank"], state["world"], _hvd.rank(), _hvd.size()))
    counts, _ = _ops.shard_partition(state["total"], state["world"])
    my_count = counts[state["rank"]]
    leaves, treedef = jax.tree_util.tree_flatten(state["inner"])
    out = []
    for i, leaf in enumerate(leaves):
        arr = np.asarray(leaf)
        if arr.ndim >= 1 and arr.shape[0] == my_count:
            arr = np.asarray(_ops.allgather(
                arr, "%s.%d" % (name_prefix, i)))
        out.append(arr)
    # world/rank -1 = "full form, not sharded for anyone" (not None:
    # the elastic state sync broadcasts every leaf through numpy).
    return {"inner": jax.tree_util.tree_unflatten(treedef, out),
            "total": state["total"], "world": -1, "rank": -1}


def sharded_state_shard(full_state):
    """Inverse of :func:`sharded_state_full` for the CURRENT rank/world:
    slices every full-length array leaf down to this rank's shard. Pure
    local slicing — no collective — so a restore path can re-shard a
    checkpointed full state at any world size. A state still sharded
    for THIS rank/world passes through unchanged; one sharded for a
    different (rank, world) cannot be re-sliced locally and is
    rejected (materialize the full form on the OLD membership first)."""
    if full_state["world"] != -1:
        if full_state["world"] == _hvd.size() and \
                full_state["rank"] == _hvd.rank():
            return full_state
        raise ValueError(
            "sharded_state_shard needs the full form (world=-1) or a "
            "state already sharded for this rank; got one sharded for "
            "rank %d of %d on rank %d of %d — call sharded_state_full() "
            "before the membership changes"
            % (full_state["rank"], full_state["world"], _hvd.rank(),
               _hvd.size()))
    total = full_state["total"]
    counts, offsets = _ops.shard_partition(total, _hvd.size())
    r = _hvd.rank()
    lo, hi = offsets[r], offsets[r] + counts[r]
    leaves, treedef = jax.tree_util.tree_flatten(full_state["inner"])
    out = []
    for leaf in leaves:
        arr = np.asarray(leaf)
        if arr.ndim >= 1 and arr.shape[0] == total:
            arr = arr[lo:hi]
        out.append(jnp.asarray(arr))
    return {"inner": jax.tree_util.tree_unflatten(treedef, out),
            "total": total, "world": _hvd.size(), "rank": r}


def init_distributed(local_device_ids=None):
    """Bootstraps ``jax.distributed`` from horovod_tpu's topology so jit
    programs span every host's chips (XLA collectives over ICI within a
    host/slice and DCN across hosts — the reference's multi-host NCCL
    role, SURVEY §2.6/§5.8).

    Call after ``init()``. Rank 0 reserves the coordinator port and
    broadcasts it through the host core, so no extra configuration is
    needed beyond the launcher's own rendezvous. No-op at size 1 or when
    jax.distributed is already initialized (idempotent: users following
    the standard JAX convention may have called
    ``jax.distributed.initialize`` themselves).
    """
    import os

    if not _hvd.is_initialized():
        raise RuntimeError("call hvd.init() before init_distributed()")
    if jax.distributed.is_initialized():
        return
    size = _hvd.size()
    if size <= 1:
        return
    from horovod_tpu.run.rendezvous import reserve_port

    port = reserve_port() if _hvd.rank() == 0 else 0
    port = int(np.asarray(_ops.broadcast(
        np.array([port], np.int64), 0, "jax_dist.coordinator_port"))[0])
    addrs = (os.environ.get("HVD_TPU_ADDRS") or "").split(",")
    if not addrs[0]:
        # Unreachable after a size>1 init (the core requires the addr
        # table); fail fast rather than pointing peers at loopback.
        raise RuntimeError(
            "HVD_TPU_ADDRS is not set; cannot derive the jax.distributed "
            "coordinator host")
    host = addrs[0].rsplit(":", 1)[0]
    jax.distributed.initialize(
        coordinator_address="%s:%d" % (host, port),
        num_processes=size, process_id=_hvd.rank(),
        local_device_ids=local_device_ids)


def sync_batch_norm_stats(stat_sum, stat_sumsq, count, group=None,
                          name="sync_bn", axis_name=AXIS_NAME):
    """Distributed-BN stats reduction (docs/GROUPS.md composition): sums
    per-replica (sum, sum-of-squares) partial statistics across ranks —
    ``group``-scoped on the host plane (e.g. ``hvd.batch_group()`` under
    a 2-D mesh so statistics stay within the batch group), psum when a
    mapped axis is in scope — and returns ``(mean, var, global_count)``.

    The standalone jax-wrapper surface for CUSTOM norm layers bringing
    their own one-pass statistics. The shipped modules
    (``ops.batch_norm.LeanBatchNorm(sync_group=...)`` /
    ``PallasBatchNorm(axis_name=...)``) do this same reduction inside
    their custom VJPs (``_lean_sync`` — the backward needs its own
    group-scoped pass, which a forward-only helper cannot provide).
    ``count`` is the PER-REPLICA element count behind the partial sums
    (a static int)."""
    from horovod_tpu import groups as _grp

    stacked = jnp.stack([jnp.asarray(stat_sum, jnp.float32),
                         jnp.asarray(stat_sumsq, jnp.float32)])
    if _is_traced(stacked) and _axis_in_scope(axis_name):
        total = jax.lax.psum(stacked, axis_name)
        n = jax.lax.psum(1, axis_name)
    else:
        total = allreduce(stacked, average=False, name=name, group=group)
        n = _grp.group_size(group)
    global_count = count * n
    mean = total[0] / global_count
    var = jnp.maximum(total[1] / global_count - mean * mean, 0.0)
    return mean, var, global_count


def metric_average(value, name=None):
    """Averages a scalar metric across ranks (reference:
    _keras/callbacks.py MetricAverageCallback semantics)."""
    arr = np.asarray(value, dtype=np.float64)
    return float(_ops.allreduce(arr, name or _auto_name("metric"),
                                average=True))


def collective_digest():
    """This rank's collective call fingerprint: ``(seq, digest)``.

    ``seq`` counts host-plane collectives enqueued since init; ``digest``
    is a rolling FNV-1a over each call's (op, dtype, shape-rank, name).
    Two ranks that executed identical call sequences report identical
    values. (In-jit psum/all_gather collectives ride XLA, not the host
    core, and are not counted — XLA already guarantees their cross-rank
    consistency by construction.)"""
    return _hvd.get_basics().call_digest()


class DivergenceError(RuntimeError):
    """Raised by :func:`assert_synchronized` when ranks' collective call
    sequences have diverged."""


_assert_counter = [0]


def assert_synchronized(name=None):
    """Runtime divergence assertion: verifies every rank has executed the
    same collective call sequence up to this point.

    Snapshots this rank's :func:`collective_digest`, allgathers the
    per-rank (rank, seq, digest) triples, and raises
    :class:`DivergenceError` naming the disagreeing ranks when they
    differ. Call it at natural barriers — after the initial
    ``broadcast_parameters``, at epoch ends, before checkpointing —
    wherever all ranks are structurally in the same place. Cost: one
    24-byte allgather.

    Every rank must call it the same number of times at the same points
    (it is itself a collective); a rank-conditional ``assert_synchronized``
    is exactly the bug it exists to catch — hvd-lint flags it like any
    other collective.
    """
    seq, digest = collective_digest()
    _assert_counter[0] += 1
    op_name = name or "hvd_assert_sync.%d" % _assert_counter[0]
    # int64 transport (the core's dtype table has no uint64); the digest
    # round-trips bit-exactly through the signed view.
    mine = np.array([[_hvd.rank(), seq, digest]],
                    dtype=np.uint64).view(np.int64)
    all_rows = np.asarray(_ops.allgather(mine, op_name)).view(np.uint64)
    rows = sorted((int(r[0]), int(r[1]), int(r[2])) for r in all_rows)
    if len({(s, d) for _, s, d in rows}) <= 1:
        return
    detail = "; ".join("rank %d: seq=%d digest=%016x" % row for row in rows)
    raise DivergenceError(
        "collective call sequences diverged across ranks (%s). Some rank "
        "executed extra, missing, or reordered collectives since init — "
        "typically a rank-conditional collective or unordered name "
        "iteration; run hvd-lint on the training script (docs/LINT.md)."
        % detail)
