"""Consistent checkpoint/restore for data-parallel jax training.

SURVEY §5.4 obligations: the reference has no checkpoint code of its own
— its pattern is "rank 0 saves, everyone restores (or rank 0 restores
and broadcasts)" (reference `examples/pytorch_imagenet_resnet50.py`
resume_from_epoch + `hvd.broadcast`). This module packages that pattern
over orbax for optax/flax pytrees:

* :func:`save` — the root rank (default 0) writes the pytree(s); other
  ranks no-op. A barrier (tiny allreduce) ensures no rank races ahead
  before the write is durable.
* :func:`restore` — the same root rank reads from disk, every rank
  receives the values via the core broadcast plane — so shared
  filesystems are NOT required (exactly the reference's
  broadcast-restore shape).
"""

import numpy as np

import horovod_tpu as _hvd
from horovod_tpu.common import ops as _ops

from . import broadcast_parameters


def _barrier(name):
    _ops.allreduce(np.zeros(1, np.float32), name)


def save(path, tree, step=None, root_rank=0):
    """Saves `tree` (any pytree of arrays) at `path` from `root_rank`
    (pass the same root to :func:`restore`).

    `step` appends a numbered subdirectory (path/<step>), the usual
    orbax layout for training runs. Returns the concrete directory
    written (on every rank, for logging)."""
    import os

    import orbax.checkpoint as ocp

    target = os.path.join(str(path), str(step)) if step is not None \
        else str(path)
    if _hvd.rank() == root_rank:
        with ocp.PyTreeCheckpointer() as ckpt:
            ckpt.save(os.path.abspath(target), tree, force=True)
    if _hvd.size() > 1:
        _barrier("ckpt_save.%s" % (step if step is not None else "x"))
    return target


def restore(path, template, step=None, root_rank=0):
    """Restores the pytree written by :func:`save`.

    `template` supplies the structure/dtypes (e.g. a freshly-initialized
    params/opt_state pytree). Only `root_rank` touches the filesystem;
    the values reach every other rank over the core broadcast plane, so
    workers without access to the checkpoint directory still restore
    consistently."""
    import os

    import orbax.checkpoint as ocp

    target = os.path.join(str(path), str(step)) if step is not None \
        else str(path)
    if _hvd.rank() == root_rank:
        # Restore WITH the template so orbax rebuilds the exact pytree
        # structure (namedtuples/custom nodes would otherwise come back
        # as dicts whose sorted-key leaf order can silently permute
        # same-shaped leaves).
        with ocp.PyTreeCheckpointer() as ckpt:
            tree = ckpt.restore(os.path.abspath(target), item=template)
        # Conform dtypes to the template BEFORE the broadcast: the saved
        # dtypes may differ (e.g. bf16 checkpoint, f32 template) and the
        # controller rejects mixed-dtype collectives across ranks.
        import jax
        import jax.numpy as jnp

        tree = jax.tree_util.tree_map(
            lambda r, t: jnp.asarray(r, dtype=t.dtype)
            if hasattr(t, "dtype") else r, tree, template)
    else:
        tree = template
    if _hvd.size() > 1:
        tree = broadcast_parameters(tree, root_rank=root_rank,
                                    name_prefix="ckpt_restore")
    return tree
