"""Consistent checkpoint/restore for data-parallel jax training.

SURVEY §5.4 obligations: the reference has no checkpoint code of its own
— its pattern is "rank 0 saves, everyone restores (or rank 0 restores
and broadcasts)" (reference `examples/pytorch_imagenet_resnet50.py`
resume_from_epoch + `hvd.broadcast`). This module packages that pattern
over orbax for optax/flax pytrees:

* :func:`save` — the root rank (default 0) writes the pytree(s); other
  ranks no-op. The root's success/failure is broadcast BEFORE any rank
  may proceed, so an orbax error on the root surfaces as a named
  :class:`CheckpointSaveError` on EVERY rank instead of the historical
  deadlock (non-root ranks waiting in the completion barrier for a root
  that already raised). The broadcast doubles as the completion barrier.
* :func:`restore` — the same root rank reads from disk, every rank
  receives the values via the core broadcast plane — so shared
  filesystems are NOT required (exactly the reference's
  broadcast-restore shape). A root-side read error raises
  :class:`CheckpointRestoreError` on every rank, same flag protocol.

Both functions contain collectives: every rank must call them. Guarding
them with ``if hvd.rank() == 0:`` deadlocks the job — hvd-lint's
``checkpoint-in-rank-guard`` rule flags that statically (docs/LINT.md).

For *durable, asynchronous, crash-surviving* checkpoints of elastic
training state, see ``hvd.elastic.ElasticState.enable_durable``
(docs/ELASTIC.md "Durability") — this module is the synchronous,
user-driven flavor.
"""

import numpy as np

import horovod_tpu as _hvd
from horovod_tpu.common import ops as _ops

from . import broadcast_parameters


class CheckpointError(RuntimeError):
    """Base for cross-rank checkpoint failures (named, raised on EVERY
    rank — never a hang)."""


class CheckpointSaveError(CheckpointError):
    """The root rank's checkpoint write failed; all ranks raise this
    (only the root carries the original exception as __cause__)."""


class CheckpointRestoreError(CheckpointError):
    """The root rank's checkpoint read failed; all ranks raise this
    (only the root carries the original exception as __cause__)."""


def _sync_root_ok(ok, root_rank, name):
    """Broadcasts the root's success flag; returns it on every rank.
    This is both the error channel and the completion barrier: a
    non-root rank returning from this broadcast proves the root got
    past its filesystem work."""
    flag = np.array([1.0 if ok else 0.0], np.float32)
    out = _ops.broadcast(flag, root_rank, name)
    return bool(np.asarray(out).reshape(-1)[0] >= 0.5)


def save(path, tree, step=None, root_rank=0):
    """Saves `tree` (any pytree of arrays) at `path` from `root_rank`
    (pass the same root to :func:`restore`).

    `step` appends a numbered subdirectory (path/<step>), the usual
    orbax layout for training runs. Returns the concrete directory
    written (on every rank, for logging). Raises
    :class:`CheckpointSaveError` on every rank when the root's write
    fails."""
    import os

    target = os.path.join(str(path), str(step)) if step is not None \
        else str(path)
    err = None
    if _hvd.rank() == root_rank:
        try:
            import orbax.checkpoint as ocp

            with ocp.PyTreeCheckpointer() as ckpt:
                ckpt.save(os.path.abspath(target), tree, force=True)
        except Exception as e:  # surfaced on every rank below
            err = e
    if _hvd.size() > 1:
        # Success flag FIRST (it doubles as the barrier): if the root
        # just raised, every rank must learn that and raise too — the
        # old bare barrier left non-root ranks blocked in an allreduce
        # the root never joined, until the stall timeout.
        ok = _sync_root_ok(err is None, root_rank,
                           "ckpt_save_ok.%s"
                           % (step if step is not None else "x"))
        if not ok:
            raise CheckpointSaveError(
                "checkpoint save to %r failed on root rank %d%s"
                % (target, root_rank,
                   ": %s" % err if err is not None else
                   " (see the root rank's log for the underlying "
                   "error)")) from err
    elif err is not None:
        raise CheckpointSaveError(
            "checkpoint save to %r failed: %s" % (target, err)) from err
    return target


def restore(path, template, step=None, root_rank=0):
    """Restores the pytree written by :func:`save`.

    `template` supplies the structure/dtypes (e.g. a freshly-initialized
    params/opt_state pytree). Only `root_rank` touches the filesystem;
    the values reach every other rank over the core broadcast plane, so
    workers without access to the checkpoint directory still restore
    consistently. Raises :class:`CheckpointRestoreError` on every rank
    when the root's read fails."""
    import os

    target = os.path.join(str(path), str(step)) if step is not None \
        else str(path)
    err = None
    tree = template
    if _hvd.rank() == root_rank:
        try:
            import orbax.checkpoint as ocp

            # Restore WITH the template so orbax rebuilds the exact
            # pytree structure (namedtuples/custom nodes would otherwise
            # come back as dicts whose sorted-key leaf order can
            # silently permute same-shaped leaves).
            with ocp.PyTreeCheckpointer() as ckpt:
                tree = ckpt.restore(os.path.abspath(target),
                                    item=template)
            # Conform dtypes to the template BEFORE the broadcast: the
            # saved dtypes may differ (e.g. bf16 checkpoint, f32
            # template) and the controller rejects mixed-dtype
            # collectives across ranks.
            import jax
            import jax.numpy as jnp

            tree = jax.tree_util.tree_map(
                lambda r, t: jnp.asarray(r, dtype=t.dtype)
                if hasattr(t, "dtype") else r, tree, template)
        except Exception as e:  # surfaced on every rank below
            err = e
    if _hvd.size() > 1:
        # Same flag-before-collectives protocol as save(): without it a
        # root-side read error (missing/corrupt checkpoint) left every
        # other rank hanging inside broadcast_parameters.
        ok = _sync_root_ok(err is None, root_rank,
                           "ckpt_restore_ok.%s"
                           % (step if step is not None else "x"))
        if not ok:
            raise CheckpointRestoreError(
                "checkpoint restore from %r failed on root rank %d%s"
                % (target, root_rank,
                   ": %s" % err if err is not None else
                   " (see the root rank's log for the underlying "
                   "error)")) from err
        tree = broadcast_parameters(tree, root_rank=root_rank,
                                    name_prefix="ckpt_restore")
    elif err is not None:
        raise CheckpointRestoreError(
            "checkpoint restore from %r failed: %s"
            % (target, err)) from err
    return tree
