"""Sparse (embedding-row) gradient collectives.

The reference allreduces tf.IndexedSlices by allgathering values+indices
instead of densifying (`horovod/tensorflow/__init__.py:65-76`) — O(rows
touched) traffic instead of O(vocab). JAX has no IndexedSlices; the
equivalent object is an explicit (indices, values) pair, which word2vec-
style models produce by taking grads w.r.t. the gathered rows only.
"""

import jax
import jax.numpy as jnp
import numpy as np

import horovod_tpu as _hvd
from . import allgather, AXIS_NAME


def allreduce_sparse(indices, values, name=None, average=True,
                     axis_name=AXIS_NAME):
    """Allreduces a sparse row-update set: returns (all_indices,
    all_values) gathered from every rank, values pre-divided by size when
    averaging. Rows repeated across ranks stay repeated — apply with a
    scatter-add so they sum, exactly like IndexedSlices application."""
    name = name or "sparse"
    all_indices = allgather(indices, name=name + ".i", axis_name=axis_name)
    all_values = allgather(values, name=name + ".v", axis_name=axis_name)
    if average:
        n = _hvd.size() if _hvd.is_initialized() else 1
        if isinstance(all_values, jax.core.Tracer):
            try:
                n = jax.lax.psum(1, axis_name)
            except NameError:
                pass
        all_values = all_values / n
    return all_indices, all_values


def apply_sparse(param, indices, values, scale=1.0):
    """Scatter-adds `scale * values` rows into `param` at `indices`
    (duplicate indices accumulate)."""
    return param.at[indices].add(scale * values)


def densify(indices, values, num_rows):
    """(indices, values) -> dense [num_rows, ...] accumulation — the
    `sparse_as_dense` escape hatch (reference tensorflow/__init__.py:
    _make_allreduce_grads_fn sparse_as_dense)."""
    dense_shape = (num_rows,) + tuple(np.shape(values))[1:]
    return jnp.zeros(dense_shape, values.dtype).at[indices].add(values)
