"""Flash attention as a Pallas TPU kernel.

Forward: a (batch*head, q-block, k-block) grid. The k dimension is the
innermost sequential axis: each step's k/v block is streamed HBM->VMEM by
the Pallas pipeline (double-buffered against the MXU work of the previous
block), while the online-softmax state (acc, running max, running sum)
lives in VMEM scratch that persists across the k steps of one q block —
the standard TPU flash recipe (128-aligned blocks, bf16 inputs, f32
accumulation). Causal masking skips the compute (not the fetch) of
k-blocks above the diagonal via `pl.when`.

Backward: custom VJP that recomputes attention blockwise over q in plain
JAX (O(BLOCK_Q * L) live memory) — XLA fuses it well, and it keeps the
kernel surface small. The softmax statistics are not saved; stability
comes from a fresh log-sum-exp per block.

On non-TPU backends the same kernel runs in Pallas interpret mode (tests)
or falls back to the blockwise JAX implementation.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
import jax.experimental.pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_Q = 128
BLOCK_K = 128


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                l_ref, *, scale, causal, num_kb):
    # q_ref: [BQ, D]; k_ref/v_ref: [BK, D]; o_ref: [BQ, D];
    # scratch: acc [BQ, D] f32, m/l [BQ, 128] f32 (state across k steps).
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    block_q, block_k = q_ref.shape[0], k_ref.shape[0]

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Causal: skip the compute (the fetch is pipelined regardless) of
    # k-blocks entirely above the diagonal.
    visible = (kj * block_k < (qi + 1) * block_q) if causal else kj >= 0

    @pl.when(visible)
    def _compute():
        # Matmuls take the inputs' native (bf16) dtype — the MXU's fast
        # path — and accumulate in f32; only softmax runs in f32.
        s = jax.lax.dot_general(
            q_ref[...], k_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [BQ, BK]
        if causal:
            # Mask only blocks straddling the diagonal; fully-visible
            # blocks (max col <= min row) skip the elementwise pass
            # entirely (the kernel is VPU-bound, every pass counts).
            def _mask(s):
                rows = qi * block_q + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                cols = kj * block_k + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                return jnp.where(rows >= cols, s, -jnp.inf)

            straddles = kj * block_k + (block_k - 1) > qi * block_q
            s = jax.lax.cond(straddles, _mask, lambda s: s, s)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[...] = jnp.broadcast_to(
            l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True),
            l_ref.shape)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == num_kb - 1)
    def _finalize():
        l = l_ref[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)  # rows with no visible keys
        o_ref[...] = (acc_ref[...] / l).astype(o_ref.dtype)
        # Log-sum-exp per row, saved for the backward recompute.
        lse_ref[...] = jnp.broadcast_to(m_ref[:, :1] + jnp.log(l),
                                        lse_ref.shape)


def _pick_block(L, preferred):
    for b in (preferred, 512, 256, 128):
        if b <= preferred and L % b == 0:
            return b
    return None


def _pallas_forward_lse(q, k, v, scale, causal, interpret,
                        block_q=None, block_k=None):
    """Returns (out [B,H,L,D], lse [B*H, L, 8] f32) — lse is the
    per-row log-sum-exp the backward kernels need (replicated over a
    8-wide trailing dim: keeps the block Mosaic-tileable and the DMA a
    contiguous stripe; 1-wide measured slower, 128-wide wastes 16x the
    memory)."""
    # q,k,v: [B, H, L, D]
    B, H, L, D = q.shape
    qf = q.reshape(B * H, L, D)
    kf = k.reshape(B * H, L, D)
    vf = v.reshape(B * H, L, D)

    # Bigger blocks amortize per-grid-step overhead (the MXU work per
    # step is tiny); bounded so s [BQ, BK] and the double-buffered k/v
    # blocks stay well inside VMEM. (256, 512) measured fastest on v5e
    # across the {128,256,512}^2 sweep.
    bq = block_q or _pick_block(L, 256)
    bk = block_k or _pick_block(L, 512)
    num_kb = L // bk
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               num_kb=num_kb)
    grid = (B * H, L // bq, num_kb)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bq, 8), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, L, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, L, 8), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, L, D), lse


def _pallas_forward(q, k, v, scale, causal, interpret,
                    block_q=None, block_k=None):
    return _pallas_forward_lse(q, k, v, scale, causal, interpret,
                               block_q, block_k)[0]


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *, scale, causal, num_kb):
    """dQ: grid (bh, q-block, k-block), k innermost sequential.
    Recomputes p = exp(s - lse) per block; dS = p * (dO.V^T - delta);
    dQ = sum_k dS.K * scale accumulated in VMEM scratch. lse and
    delta = rowsum(dO*O) are precomputed per row and streamed in."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    block_q, block_k = q_ref.shape[0], k_ref.shape[0]

    @pl.when(kj == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    visible = (kj * block_k < (qi + 1) * block_q) if causal else kj >= 0

    @pl.when(visible)
    def _compute():
        s = jax.lax.dot_general(
            q_ref[...], k_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse_ref[:, :1])
        if causal:
            def _mask(p):
                rows = qi * block_q + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                cols = kj * block_k + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                return jnp.where(rows >= cols, p, 0.0)

            straddles = kj * block_k + (block_k - 1) > qi * block_q
            p = jax.lax.cond(straddles, _mask, lambda p: p, p)
        dp = jax.lax.dot_general(
            do_ref[...], v_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[:, :1]) * scale
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == num_kb - 1)
    def _finalize():
        dq_ref[...] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    num_qb):
    """dK/dV: grid (bh, k-block, q-block), q innermost sequential.
    dV = sum_q P^T.dO; dK = sum_q dS^T.Q * scale."""
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    block_q, block_k = q_ref.shape[0], k_ref.shape[0]

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # Causal: q blocks entirely above this k block see none of it.
    visible = (qi * block_q + (block_q - 1) >= kj * block_k) if causal \
        else qi >= 0

    @pl.when(visible)
    def _compute():
        s = jax.lax.dot_general(
            q_ref[...], k_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse_ref[:, :1])
        if causal:
            def _mask(p):
                rows = qi * block_q + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                cols = kj * block_k + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                return jnp.where(rows >= cols, p, 0.0)

            straddles = kj * block_k + (block_k - 1) > qi * block_q
            p = jax.lax.cond(straddles, _mask, lambda p: p, p)
        p_lo = p.astype(do_ref.dtype)
        dv_acc[...] += jax.lax.dot_general(
            p_lo, do_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_ref[...], v_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[:, :1]) * scale).astype(q_ref.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds, q_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_qb - 1)
    def _finalize():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _pallas_backward(q, k, v, out, lse, g, scale, causal, interpret,
                     block_q=None, block_k=None):
    """Pallas backward: returns (dq, dk, dv) in the inputs' dtypes."""
    B, H, L, D = q.shape
    qf, kf, vf, gf = (x.reshape(B * H, L, D) for x in (q, k, v, g))
    # delta = rowsum(dO * O): one fused XLA pass, streamed into both
    # kernels per q block (recomputing it per grid step would redo the
    # reduction num_kb/num_qb times).
    delta = jnp.broadcast_to(
        jnp.sum(gf.astype(jnp.float32) *
                out.reshape(B * H, L, D).astype(jnp.float32), axis=-1,
                keepdims=True), (B * H, L, 8))
    bq = block_q or _pick_block(L, 256)
    bk = block_k or _pick_block(L, 512)
    num_kb, num_qb = L // bk, L // bq

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          num_kb=num_kb),
        grid=(B * H, L // bq, num_kb),
        in_specs=[
            pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bq, 8), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bq, 8), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, L, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          num_qb=num_qb),
        grid=(B * H, num_kb, num_qb),
        in_specs=[
            pl.BlockSpec((None, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, bq, 8), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, bq, 8), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, L, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, L, D), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    shape = (B, H, L, D)
    return (dq.reshape(shape), dk.reshape(shape), dv.reshape(shape))


def _blockwise_reference(q, k, v, scale, causal):
    """Blockwise JAX attention, O(BLOCK_Q * L) live memory; used for the
    backward recompute and as the non-TPU fallback."""
    B, H, L, D = q.shape
    block_q = min(BLOCK_Q, L)

    def per_qblock(start, size):
        qs = lax.slice_in_dim(q, start, start + size, axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", qs.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        if causal:
            rows = start + lax.broadcasted_iota(jnp.int32, (size, L), 0)
            cols = lax.broadcasted_iota(jnp.int32, (size, L), 1)
            s = jnp.where((rows >= cols)[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p,
                          v.astype(jnp.float32)).astype(q.dtype)

    # Ceil-divide over q so a sequence remainder (L % block_q != 0) gets
    # its own (smaller, still static-shaped) tail block.
    blocks = [per_qblock(start, min(block_q, L - start))
              for start in range(0, L, block_q)]
    return blocks[0] if len(blocks) == 1 else jnp.concatenate(blocks, axis=2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, scale, causal, interpret):
    if interpret is None:
        return _blockwise_reference(q, k, v, scale, causal)
    return _pallas_forward(q, k, v, scale, causal, interpret)


def _flash_fwd(q, k, v, scale, causal, interpret):
    if interpret is None:
        return _blockwise_reference(q, k, v, scale, causal), \
            (q, k, v, None, None)
    out, lse = _pallas_forward_lse(q, k, v, scale, causal, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, interpret, res, g):
    q, k, v, out, lse = res
    if interpret is None:
        # Non-kernel path: recompute-blockwise VJP in plain JAX.
        _, vjp = jax.vjp(
            lambda q, k, v: _blockwise_reference(q, k, v, scale, causal),
            q, k, v)
        return vjp(g)
    return _pallas_backward(q, k, v, out, lse, g, scale, causal, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=True, scale=None):
    """Flash attention over [B, L, H, D] inputs (same layout as
    `parallel.ring.ring_attention`); returns [B, L, H, D] in q.dtype.

    L must be a multiple of 128 to hit the Pallas kernel; other shapes
    (and non-TPU backends without interpret mode) use the blockwise JAX
    fallback, which is numerically identical.
    """
    B, L, H, D = q.shape
    if scale is None:
        scale = D ** -0.5
    # Kernel layout: [B, H, L, D].
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    on_tpu = jax.default_backend() == "tpu"
    if L % BLOCK_Q != 0 or not on_tpu:
        out = _flash(qt, kt, vt, scale, causal, None)
    else:
        out = _flash(qt, kt, vt, scale, causal, False)
    return out.transpose(0, 2, 1, 3)
