"""Flash attention as a Pallas TPU kernel.

Forward: a (batch*head, q-block, k-block) grid. The k dimension is the
innermost sequential axis: each step's k/v block is streamed HBM->VMEM by
the Pallas pipeline (double-buffered against the MXU work of the previous
block), while the online-softmax state (acc, running max, running sum)
lives in VMEM scratch that persists across the k steps of one q block —
the standard TPU flash recipe (128-aligned blocks, bf16 inputs, f32
accumulation). Causal masking skips both the compute (`pl.when`) and
the fetch (index maps clamp above-diagonal steps to the frontier
block; Pallas elides the DMA for a revisited block index) of k-blocks
above the diagonal — at long L this halves attention HBM traffic.

Backward: custom VJP that recomputes attention blockwise over q in plain
JAX (O(BLOCK_Q * L) live memory) — XLA fuses it well, and it keeps the
kernel surface small. The softmax statistics are not saved; stability
comes from a fresh log-sum-exp per block.

On non-TPU backends the same kernel runs in Pallas interpret mode (tests)
or falls back to the blockwise JAX implementation.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
import jax.experimental.pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_Q = 128
BLOCK_K = 128


def _masked_scores(q_ref, k_ref, scale, causal, q_off, kv_off, fill):
    """s = (q.k^T)*scale with causal masking by global row/col offsets.
    Only blocks straddling the diagonal pay the elementwise mask pass
    (the kernels are VPU-bound, every pass counts); `fill` is -inf for
    scores, 0 for probabilities."""
    block_q, block_k = q_ref.shape[0], k_ref.shape[0]
    s = jax.lax.dot_general(
        q_ref[...], k_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # [BQ, BK]
    if not causal:
        return s

    def _mask(s):
        rows = q_off + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = kv_off + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        return jnp.where(rows >= cols, s, fill)

    straddles = kv_off + (block_k - 1) > q_off
    return jax.lax.cond(straddles, _mask, lambda s: s, s)


def _online_softmax_update(s, v_ref, acc_ref, m_ref, l_ref, guard_empty):
    """One online-softmax block update of the (acc, m, l) state refs.
    `guard_empty` handles rows no block has touched yet (m == -inf, the
    ring-step case where visitation order is data-dependent); the plain
    forward's ascending k order makes the first visible block cover
    every row, so it skips the two extra passes."""
    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    if guard_empty:
        alpha = jnp.where(jnp.isneginf(m_new), 0.0, alpha)
        p = jnp.where(jnp.isneginf(m_new), 0.0, p)
    l_ref[...] = jnp.broadcast_to(
        l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape)
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                l_ref, *, scale, causal, num_kb):
    # q_ref: [BQ, D]; k_ref/v_ref: [BK, D]; o_ref: [BQ, D];
    # scratch: acc [BQ, D] f32, m/l [BQ, 128] f32 (state across k steps).
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    block_q, block_k = q_ref.shape[0], k_ref.shape[0]

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Causal: skip the compute (the fetch is pipelined regardless) of
    # k-blocks entirely above the diagonal.
    visible = (kj * block_k < (qi + 1) * block_q) if causal else kj >= 0

    @pl.when(visible)
    def _compute():
        # Matmuls take the inputs' native (bf16) dtype — the MXU's fast
        # path — and accumulate in f32; only softmax runs in f32.
        s = _masked_scores(q_ref, k_ref, scale, causal,
                           q_off=qi * block_q, kv_off=kj * block_k,
                           fill=-jnp.inf)
        _online_softmax_update(s, v_ref, acc_ref, m_ref, l_ref,
                               guard_empty=False)

    @pl.when(kj == num_kb - 1)
    def _finalize():
        l = l_ref[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)  # rows with no visible keys
        o_ref[...] = (acc_ref[...] / l).astype(o_ref.dtype)
        # Log-sum-exp per row, saved for the backward recompute.
        lse_ref[...] = jnp.broadcast_to(m_ref[:, :1] + jnp.log(l),
                                        lse_ref.shape)


def _pick_block(L, preferred):
    for b in (preferred, 512, 256, 128):
        if b <= preferred and L % b == 0:
            return b
    return None


def _default_blocks(D, L=None, backward=False):
    """Preferred (block_q, block_k) by head dim and sequence length,
    from v5e sweeps (examples/flash_block_sweep.py): (256, 512) at
    D=128; D<=64 leaves VMEM headroom for wider blocks — (256, 1024)
    forward / (512, 1024) backward at L=2048. Long sequences amortize
    still-bigger q blocks (L=8192 sweep: fwd (512,1024) 8.95 vs 10.39
    ms/layer, bwd (1024,1024) ~15.7 vs ~17.1): at L>=4096 the q block
    doubles. ONE definition for the plain and ring paths so a retune
    can't leave them inconsistent."""
    long_seq = L is not None and L >= 4096
    if D <= 64:
        if backward:
            return (1024, 1024) if long_seq else (512, 1024)
        return (512, 1024) if long_seq else (256, 1024)
    # D=128 at L=8192: fwd (512,512) 6.12 vs 8.24 ms/layer for the
    # L=2048-swept (256,512); bwd (512,1024) ~8.2 vs ~10.3.
    if long_seq:
        return (512, 1024) if backward else (512, 512)
    return (256, 512)


def _kv_index_map(bq, bk, causal):
    """k/v BlockSpec index map for grids with k innermost. Causal runs
    clamp the k-block index to the diagonal frontier: steps above the
    diagonal revisit the frontier block, and Pallas skips the DMA for a
    revisited index — halving k/v HBM traffic at long L (the compute is
    separately gated by `pl.when(visible)`)."""
    if not causal:
        return lambda b, i, j: (b, j, 0)
    return lambda b, i, j: (b, jnp.minimum(j, ((i + 1) * bq - 1) // bk), 0)


def _q_index_map(bq, bk, causal):
    """q-side BlockSpec index map for the dk/dv grid (q innermost).
    Causal runs clamp the q-block index UP to the first block at or
    below the diagonal (qi_min = (kj*bk)//bq): the leading invisible
    steps revisit that block, skipping their DMA."""
    if not causal:
        return lambda b, j, i: (b, i, 0)
    return lambda b, j, i: (b, jnp.maximum(i, (j * bk) // bq), 0)


def _require_block(L, preferred, what):
    b = _pick_block(L, preferred)
    if b is None:
        raise ValueError(
            f"{what}={L} must be a multiple of 128 for the Pallas ring "
            f"kernels (got {L} % 128 == {L % 128}); pad the sequence "
            "shard or use the jnp ring path")
    return b


def _pallas_forward_lse(q, k, v, scale, causal, interpret,
                        block_q=None, block_k=None):
    """Returns (out [B,H,L,D], lse [B*H, L, 8] f32) — lse is the
    per-row log-sum-exp the backward kernels need (replicated over a
    8-wide trailing dim: keeps the block Mosaic-tileable and the DMA a
    contiguous stripe; 1-wide measured slower, 128-wide wastes 16x the
    memory)."""
    # q,k,v: [B, H, L, D]
    B, H, L, D = q.shape
    qf = q.reshape(B * H, L, D)
    kf = k.reshape(B * H, L, D)
    vf = v.reshape(B * H, L, D)

    # Bigger blocks amortize per-grid-step overhead (the MXU work per
    # step is tiny); bounded so s [BQ, BK] and the double-buffered k/v
    # blocks stay well inside VMEM. Preferences are D-aware — see
    # _default_blocks.
    pq, pk = _default_blocks(D, L)
    bq = block_q or _pick_block(L, pq)
    bk = block_k or _pick_block(L, pk)
    num_kb = L // bk
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               num_kb=num_kb)
    grid = (B * H, L // bq, num_kb)
    kv_im = _kv_index_map(bq, bk, causal)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bk, D), kv_im),
            pl.BlockSpec((None, bk, D), kv_im),
        ],
        out_specs=[
            pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bq, 8), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, L, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, L, 8), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, L, D), lse


def _pallas_forward(q, k, v, scale, causal, interpret,
                    block_q=None, block_k=None):
    return _pallas_forward_lse(q, k, v, scale, causal, interpret,
                               block_q, block_k)[0]


def _ring_step_kernel(q_offs_ref, kv_offs_ref, q_ref, k_ref, v_ref,
                      oi_ref, mi_ref, li_ref, oo_ref, mo_ref, lo_ref,
                      acc_ref, m_ref, l_ref, *, scale, causal, num_kb):
    """One ring-attention step as a flash kernel with carried state.

    Same online-softmax update as `_fwd_kernel`, but the (acc, m, l)
    state is loaded from the previous ring step's outputs instead of
    initialized, and written back un-normalized (the caller divides by l
    after the last ring step). Causal masking uses *global* token
    offsets — PER-BLOCK arrays in SMEM (q_offs_ref[qi], kv_offs_ref[kj])
    rather than one scalar per shard, so a shard may hold discontiguous
    sequence chunks (the zigzag causal schedule) as long as chunk
    boundaries align with block boundaries. Block skipping is dynamic
    for the same reason.
    """
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    block_q, block_k = q_ref.shape[0], k_ref.shape[0]
    q_off = q_offs_ref[qi]
    kv_off = kv_offs_ref[kj]

    @pl.when(kj == 0)
    def _load_state():
        acc_ref[...] = oi_ref[...]
        m_ref[...] = jnp.broadcast_to(mi_ref[:, :1], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(li_ref[:, :1], l_ref.shape)

    # A k/v block entirely in this q block's future contributes nothing.
    visible = (kv_off <= q_off + block_q - 1) if causal else kj >= 0

    @pl.when(visible)
    def _compute():
        s = _masked_scores(q_ref, k_ref, scale, causal, q_off=q_off,
                           kv_off=kv_off, fill=-jnp.inf)
        _online_softmax_update(s, v_ref, acc_ref, m_ref, l_ref,
                               guard_empty=True)

    @pl.when(kj == num_kb - 1)
    def _store_state():
        oo_ref[...] = acc_ref[...]
        mo_ref[...] = jnp.broadcast_to(m_ref[:, :1], mo_ref.shape)
        lo_ref[...] = jnp.broadcast_to(l_ref[:, :1], lo_ref.shape)


def _chunk_len(L, offset, what):
    """Chunk length for a scalar shard offset (one chunk = the shard)
    or a 1-D array of per-chunk offsets (equal chunks)."""
    arr = jnp.asarray(offset)
    if arr.ndim == 0:
        return L
    if L % arr.shape[0]:
        raise ValueError(f"{what}: {arr.shape[0]} chunks must divide "
                         f"shard length {L}")
    return L // arr.shape[0]


def _block_offsets(offset, L, blk):
    """Per-block global offsets (L // blk,) int32 from a scalar shard
    offset or a 1-D array of per-chunk offsets (equal chunks whose
    length must be a multiple of blk — blocks may not straddle chunk
    boundaries)."""
    off = jnp.asarray(offset, jnp.int32)
    pos = jnp.arange(L // blk, dtype=jnp.int32) * blk
    if off.ndim == 0:
        return off + pos
    Lc = L // off.shape[0]
    if Lc % blk:
        # Reachable only via an explicit block_q/block_k override that
        # bypasses the _require_block(chunk_len, ...) pick: a block
        # spanning two discontiguous chunks would get one (wrong)
        # offset and silently mis-mask.
        raise ValueError(
            f"block size {blk} must divide the chunk length {Lc} "
            f"(chunked shards cannot have blocks straddling chunk "
            f"boundaries)")
    return off[pos // Lc] + pos % Lc


def flash_ring_step(q, k, v, o, m, l, q_offset, kv_offset, causal=True,
                    scale=None, interpret=False, block_q=None,
                    block_k=None):
    """One ring-attention local step over kernel-layout shards.

    Args: q [BH, Lq, D] (bf16/f32), k/v [BH, Lk, D], carried state
    o [BH, Lq, D] f32 (un-normalized accumulator), m/l [BH, Lq, 8] f32
    (running max / normalizer stripes), q_offset/kv_offset global token
    offsets — traced int32 scalars (contiguous shards), or 1-D arrays
    of per-chunk offsets for shards holding several equal discontiguous
    chunks (the zigzag causal schedule). Returns updated (o, m, l).
    """
    BH, Lq, D = q.shape
    Lk = k.shape[1]
    if scale is None:
        scale = D ** -0.5
    Lcq = _chunk_len(Lq, q_offset, "q_offset")
    Lck = _chunk_len(Lk, kv_offset, "kv_offset")
    pq, pk = _default_blocks(D, Lq)
    bq = block_q or _require_block(Lcq, pq, "q chunk length")
    bk = block_k or _require_block(Lck, pk, "k/v chunk length")
    num_kb = Lk // bk
    q_offs = _block_offsets(q_offset, Lq, bq)
    kv_offs = _block_offsets(kv_offset, Lk, bk)
    kernel = functools.partial(_ring_step_kernel, scale=scale,
                               causal=causal, num_kb=num_kb)
    grid = (BH, Lq // bq, num_kb)
    state_specs = [
        pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((None, bq, 8), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((None, bq, 8), lambda b, i, j: (b, i, 0)),
    ]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # per-q-block offs
            pl.BlockSpec(memory_space=pltpu.SMEM),  # per-kv-block offs
            pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, bk, D), lambda b, i, j: (b, j, 0)),
        ] + state_specs,
        out_specs=state_specs,
        out_shape=[
            jax.ShapeDtypeStruct((BH, Lq, D), jnp.float32),
            jax.ShapeDtypeStruct((BH, Lq, 8), jnp.float32),
            jax.ShapeDtypeStruct((BH, Lq, 8), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q_offs, kv_offs, q, k, v, o, m, l)


def _ring_bwd_dq_kernel(q_offs_ref, kv_offs_ref, q_ref, k_ref, v_ref,
                        do_ref, lse_ref, delta_ref, dqi_ref, dqo_ref,
                        dq_acc, *, scale, causal, num_kb):
    """dQ contribution of one backward ring step (FlashAttention-2
    math, global offsets like `_ring_step_kernel`). The dq accumulator
    is carried *across ring steps* (dqi -> dqo, f32): each arriving k/v
    shard adds its `sum_k dS.K` term; no forward recompute — p comes
    from the saved per-row lse."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    block_q, block_k = q_ref.shape[0], k_ref.shape[0]
    q_off = q_offs_ref[qi]
    kv_off = kv_offs_ref[kj]

    @pl.when(kj == 0)
    def _load():
        dq_acc[...] = dqi_ref[...]

    visible = (kv_off <= q_off + block_q - 1) if causal else kj >= 0

    @pl.when(visible)
    def _compute():
        s = _masked_scores(q_ref, k_ref, scale, causal, q_off=q_off,
                           kv_off=kv_off, fill=-jnp.inf)
        p = jnp.exp(s - lse_ref[:, :1])  # masked entries: exp(-inf) = 0
        dp = jax.lax.dot_general(
            do_ref[...], v_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[:, :1]) * scale)
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == num_kb - 1)
    def _store():
        dqo_ref[...] = dq_acc[...]


def _ring_bwd_dkv_kernel(q_offs_ref, kv_offs_ref, q_ref, k_ref, v_ref,
                         do_ref, lse_ref, delta_ref, dki_ref, dvi_ref,
                         dko_ref, dvo_ref, dk_acc, dv_acc, *, scale,
                         causal, num_qb):
    """dK/dV contribution of one backward ring step. The dk/dv
    accumulators travel around the ring with their k/v shard (the
    caller ppermutes them together), so after n steps each shard
    arrives home with its full gradient. Grid (bh, k-block, q-block),
    q innermost sequential."""
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    block_q, block_k = q_ref.shape[0], k_ref.shape[0]
    q_off = q_offs_ref[qi]
    kv_off = kv_offs_ref[kj]

    @pl.when(qi == 0)
    def _load():
        dk_acc[...] = dki_ref[...]
        dv_acc[...] = dvi_ref[...]

    visible = (q_off + block_q - 1 >= kv_off) if causal else qi >= 0

    @pl.when(visible)
    def _compute():
        s = _masked_scores(q_ref, k_ref, scale, causal, q_off=q_off,
                           kv_off=kv_off, fill=-jnp.inf)
        p = jnp.exp(s - lse_ref[:, :1])  # masked entries: exp(-inf) = 0
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_ref[...], v_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[:, :1]) * scale).astype(q_ref.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds, q_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_qb - 1)
    def _store():
        dko_ref[...] = dk_acc[...]
        dvo_ref[...] = dv_acc[...]


def flash_ring_bwd_step(q, k, v, do, lse, delta, dq, dk, dv, q_offset,
                        kv_offset, causal=True, scale=None,
                        interpret=False, block_q=None, block_k=None):
    """One backward ring step over kernel-layout shards.

    Args: q/do [BH, Lq, D], k/v [BH, Lk, D], lse/delta [BH, Lq, 8] f32
    (per-row log-sum-exp from the forward; delta = rowsum(dO*O)),
    dq [BH, Lq, D] f32 (local accumulator), dk/dv [BH, Lk, D] f32
    (accumulators traveling with the k/v shard), q_offset/kv_offset
    global token offsets. Returns updated (dq, dk, dv).
    """
    BH, Lq, D = q.shape
    Lk = k.shape[1]
    if scale is None:
        scale = D ** -0.5
    Lcq = _chunk_len(Lq, q_offset, "q_offset")
    Lck = _chunk_len(Lk, kv_offset, "kv_offset")
    pq, pk = _default_blocks(D, Lq, backward=True)
    bq = block_q or _require_block(Lcq, pq, "q chunk length")
    bk = block_k or _require_block(Lck, pk, "k/v chunk length")
    num_kb, num_qb = Lk // bk, Lq // bq
    q_offs = _block_offsets(q_offset, Lq, bq)
    kv_offs = _block_offsets(kv_offset, Lk, bk)

    q_spec = lambda b, i, j: (b, i, 0)      # noqa: E731
    stripe_spec = lambda b, i, j: (b, i, 0)  # noqa: E731

    dq = pl.pallas_call(
        functools.partial(_ring_bwd_dq_kernel, scale=scale, causal=causal,
                          num_kb=num_kb),
        grid=(BH, num_qb, num_kb),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((None, bq, D), q_spec),
            pl.BlockSpec((None, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, bq, D), q_spec),
            pl.BlockSpec((None, bq, 8), stripe_spec),
            pl.BlockSpec((None, bq, 8), stripe_spec),
            pl.BlockSpec((None, bq, D), q_spec),
        ],
        out_specs=pl.BlockSpec((None, bq, D), q_spec),
        out_shape=jax.ShapeDtypeStruct((BH, Lq, D), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q_offs, kv_offs, q, k, v, do, lse, delta, dq)

    k_spec = lambda b, j, i: (b, j, 0)  # noqa: E731
    dk, dv = pl.pallas_call(
        functools.partial(_ring_bwd_dkv_kernel, scale=scale,
                          causal=causal, num_qb=num_qb),
        grid=(BH, num_kb, num_qb),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((None, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, bk, D), k_spec),
            pl.BlockSpec((None, bk, D), k_spec),
            pl.BlockSpec((None, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, bq, 8), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, bq, 8), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, bk, D), k_spec),
            pl.BlockSpec((None, bk, D), k_spec),
        ],
        out_specs=[
            pl.BlockSpec((None, bk, D), k_spec),
            pl.BlockSpec((None, bk, D), k_spec),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Lk, D), jnp.float32),
            jax.ShapeDtypeStruct((BH, Lk, D), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q_offs, kv_offs, q, k, v, do, lse, delta, dk, dv)
    return dq, dk, dv


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *, scale, causal, num_kb):
    """dQ: grid (bh, q-block, k-block), k innermost sequential.
    Recomputes p = exp(s - lse) per block; dS = p * (dO.V^T - delta);
    dQ = sum_k dS.K * scale accumulated in VMEM scratch. lse and
    delta = rowsum(dO*O) are precomputed per row and streamed in."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    block_q, block_k = q_ref.shape[0], k_ref.shape[0]

    @pl.when(kj == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    visible = (kj * block_k < (qi + 1) * block_q) if causal else kj >= 0

    @pl.when(visible)
    def _compute():
        s = _masked_scores(q_ref, k_ref, scale, causal,
                           q_off=qi * block_q, kv_off=kj * block_k,
                           fill=-jnp.inf)
        p = jnp.exp(s - lse_ref[:, :1])  # masked entries: exp(-inf) = 0
        dp = jax.lax.dot_general(
            do_ref[...], v_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[:, :1]) * scale
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == num_kb - 1)
    def _finalize():
        dq_ref[...] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    num_qb):
    """dK/dV: grid (bh, k-block, q-block), q innermost sequential.
    dV = sum_q P^T.dO; dK = sum_q dS^T.Q * scale."""
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    block_q, block_k = q_ref.shape[0], k_ref.shape[0]

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # Causal: q blocks entirely above this k block see none of it.
    visible = (qi * block_q + (block_q - 1) >= kj * block_k) if causal \
        else qi >= 0

    @pl.when(visible)
    def _compute():
        s = _masked_scores(q_ref, k_ref, scale, causal,
                           q_off=qi * block_q, kv_off=kj * block_k,
                           fill=-jnp.inf)
        p = jnp.exp(s - lse_ref[:, :1])  # masked entries: exp(-inf) = 0
        p_lo = p.astype(do_ref.dtype)
        dv_acc[...] += jax.lax.dot_general(
            p_lo, do_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_ref[...], v_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[:, :1]) * scale).astype(q_ref.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds, q_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_qb - 1)
    def _finalize():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _pallas_backward(q, k, v, out, lse, g, scale, causal, interpret,
                     block_q=None, block_k=None):
    """Pallas backward: returns (dq, dk, dv) in the inputs' dtypes."""
    B, H, L, D = q.shape
    qf, kf, vf, gf = (x.reshape(B * H, L, D) for x in (q, k, v, g))
    # delta = rowsum(dO * O): one fused XLA pass, streamed into both
    # kernels per q block (recomputing it per grid step would redo the
    # reduction num_kb/num_qb times).
    delta = jnp.broadcast_to(
        jnp.sum(gf.astype(jnp.float32) *
                out.reshape(B * H, L, D).astype(jnp.float32), axis=-1,
                keepdims=True), (B * H, L, 8))
    # Backward blocks are independent of the forward's (lse/delta
    # stripes are block-agnostic); see _default_blocks for the swept
    # preferences.
    pq, pk = _default_blocks(D, L, backward=True)
    bq = block_q or _pick_block(L, pq)
    bk = block_k or _pick_block(L, pk)
    num_kb, num_qb = L // bk, L // bq

    kv_im = _kv_index_map(bq, bk, causal)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          num_kb=num_kb),
        grid=(B * H, L // bq, num_kb),
        in_specs=[
            pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bk, D), kv_im),
            pl.BlockSpec((None, bk, D), kv_im),
            pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bq, 8), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bq, 8), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, L, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    q_im = _q_index_map(bq, bk, causal)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          num_qb=num_qb),
        grid=(B * H, num_kb, num_qb),
        in_specs=[
            pl.BlockSpec((None, bq, D), q_im),
            pl.BlockSpec((None, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, bq, D), q_im),
            pl.BlockSpec((None, bq, 8), q_im),
            pl.BlockSpec((None, bq, 8), q_im),
        ],
        out_specs=[
            pl.BlockSpec((None, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, L, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, L, D), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    shape = (B, H, L, D)
    return (dq.reshape(shape), dk.reshape(shape), dv.reshape(shape))


def _blockwise_reference(q, k, v, scale, causal):
    """Blockwise JAX attention, O(BLOCK_Q * L) live memory; used for the
    backward recompute and as the non-TPU fallback."""
    B, H, L, D = q.shape
    block_q = min(BLOCK_Q, L)

    def per_qblock(start, size):
        qs = lax.slice_in_dim(q, start, start + size, axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", qs.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        if causal:
            rows = start + lax.broadcasted_iota(jnp.int32, (size, L), 0)
            cols = lax.broadcasted_iota(jnp.int32, (size, L), 1)
            s = jnp.where((rows >= cols)[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p,
                          v.astype(jnp.float32)).astype(q.dtype)

    # Ceil-divide over q so a sequence remainder (L % block_q != 0) gets
    # its own (smaller, still static-shaped) tail block.
    blocks = [per_qblock(start, min(block_q, L - start))
              for start in range(0, L, block_q)]
    return blocks[0] if len(blocks) == 1 else jnp.concatenate(blocks, axis=2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, scale, causal, interpret):
    if interpret is None:
        return _blockwise_reference(q, k, v, scale, causal)
    return _pallas_forward(q, k, v, scale, causal, interpret)


def _flash_fwd(q, k, v, scale, causal, interpret):
    if interpret is None:
        return _blockwise_reference(q, k, v, scale, causal), \
            (q, k, v, None, None)
    out, lse = _pallas_forward_lse(q, k, v, scale, causal, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, interpret, res, g):
    q, k, v, out, lse = res
    if interpret is None:
        # Non-kernel path: recompute-blockwise VJP in plain JAX.
        _, vjp = jax.vjp(
            lambda q, k, v: _blockwise_reference(q, k, v, scale, causal),
            q, k, v)
        return vjp(g)
    return _pallas_backward(q, k, v, out, lse, g, scale, causal, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def analytic_attention_flops(B, H, L, D, causal=True, training=False):
    """FLOPs the Pallas attention kernels execute per call — XLA's
    compiled-cost analysis reports custom calls as ZERO flops, so
    benchmarks add this analytic count to keep MFU honest. Forward runs
    2 matmuls per (q,k) block pair (QK^T, PV); the backward kernels run
    7 matmul-equivalents (s and dp are recomputed in both the dQ and
    dK/dV kernels, plus the dQ/dK/dV products). ``training=True``
    therefore returns the FULL forward+backward step count (2 + 7 = 9
    per block pair) — callers must NOT add a separate forward term.
    Causal halves the visited block pairs."""
    per_matmul = 2.0 * B * H * L * L * D
    if causal:
        per_matmul /= 2.0
    return (9.0 if training else 2.0) * per_matmul


def flash_attention(q, k, v, causal=True, scale=None):
    """Flash attention over [B, L, H, D] inputs (same layout as
    `parallel.ring.ring_attention`); returns [B, L, H, D] in q.dtype.

    L must be a multiple of 128 to hit the Pallas kernel; other shapes
    (and non-TPU backends without interpret mode) use the blockwise JAX
    fallback, which is numerically identical.
    """
    B, L, H, D = q.shape
    if scale is None:
        scale = D ** -0.5
    # Kernel layout: [B, H, L, D].
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    on_tpu = jax.default_backend() == "tpu"
    if L % BLOCK_Q != 0 or not on_tpu:
        out = _flash(qt, kt, vt, scale, causal, None)
    else:
        out = _flash(qt, kt, vt, scale, causal, False)
    return out.transpose(0, 2, 1, 3)
