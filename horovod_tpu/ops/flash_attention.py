"""Flash attention as a Pallas TPU kernel.

Forward: a (batch*kv_head, q-block, k-block) grid. The k dimension is the
innermost sequential axis: each step's k/v block is streamed HBM->VMEM by
the Pallas pipeline (double-buffered against the MXU work of the previous
block), while the online-softmax state (acc, running max, running sum)
lives in VMEM scratch that persists across the k steps of one q block —
the standard TPU flash recipe (128-aligned blocks, bf16 inputs, f32
accumulation). Causal masking skips both the compute (`pl.when`) and
the fetch (index maps clamp above-diagonal steps to the frontier
block; Pallas elides the DMA for a revisited block index) of k-blocks
above the diagonal — at long L this halves attention HBM traffic.

GQA/MQA (num_kv_heads < num_heads) uses a grouped-rows layout: the
`group = H / G` query heads sharing one kv head are interleaved into the
q rows (row r of kv-head g's [L*group, D] slab is position r//group,
head g*group + r%group). One kv block then serves the whole group per
fetch, k/v is never materialized at H heads (the HBM win that motivates
GQA), and dK/dV accumulate the group reduction inside the kernel instead
of a [B,H,L,D] gradient plus a post-hoc sum. The only kernel change is
that row positions are `row // group` — masks, frontier clamps and
block-skip predicates all run in position units.

Rotary embedding can be fused into the kernels (`rotary_base`), which
removes the HBM round trip of writing rotated q/k outside the kernel.
The cos/sin terms are NOT computed in-kernel: transcendentals plus the
half-pair shuffle on every block visit serialize the VPU ahead of each
MXU step and measured ~2x whole-kernel cost at L=8192. Instead the
caller builds full-width (C, S) tables once per call (f32, sign folded
into S; XLA CSEs them across layers) and the kernels stream table
blocks through the same index maps as q/k — per-visit work drops to
one lane-roll + 2 mul + 1 add (`_rot_apply`), and rotated q is cached
in VMEM scratch for the whole k sweep. Rotation is linear-orthogonal
per row, so the backward kernels rotate q/k the same way to recompute
scores and counter-rotate finished dQ/dK blocks (the S sign flips —
see `_rot_apply(neg=True)`) at finalize. The ring-step kernels instead
accumulate gradients in rotated space across ring steps; the caller
counter-rotates once after the last step (`apply_rotary(neg=True)`).

Backward: custom VJP over saved per-row log-sum-exp (FlashAttention-2
style). On non-TPU backends the same kernels run in Pallas interpret
mode (tests) or fall back to the blockwise JAX implementation.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
import jax.experimental.pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_Q = 128
BLOCK_K = 128


def apply_rotary(x, positions, base=10000.0, neg=False):
    """Rotary embedding outside the kernels (jnp fallbacks, ring
    gradient counter-rotation). ``positions`` must be broadcastable to
    ``x.shape[:-1]``; pairs are (d, d + D/2) — the same convention as
    the in-kernel table path and `models.transformer._rotary`.
    ``neg=True`` applies the transpose rotation R(-pos) (the gradient
    counter-rotation)."""
    D = x.shape[-1]
    half = D // 2
    inv = base ** (-jnp.arange(half, dtype=jnp.float32) * 2.0 / D)
    ang = positions[..., None].astype(jnp.float32) * inv
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    if neg:
        sin = -sin
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


def _rope_tables(positions, D, base):
    """Full-width rotary tables for the kernels: (C, S) [R, D] f32 with
    C[r, j] = cos(pos_r * inv_freq[j mod D/2]) and the application sign
    baked into S (= [-sin | +sin]), so the in-kernel work is
    x * C + roll(x, D/2) * S — no transcendentals, no half-pair
    slicing. Built once per call; XLA CSEs identical tables across
    layers."""
    half = D // 2
    inv = base ** (-jnp.arange(half, dtype=jnp.float32) * 2.0 / D)
    ang = positions[:, None].astype(jnp.float32) * inv  # [R, half]
    c = jnp.cos(ang)
    s = jnp.sin(ang)
    return (jnp.concatenate([c, c], axis=-1),
            jnp.concatenate([-s, s], axis=-1))


def _rot_apply(x, cos_ref, sin_ref, neg=False):
    """Rotate a [R, D] block by streamed tables: each row's pair
    partner sits half a lane-width away, fetched with one lane-roll.
    ``neg=True`` is the transpose rotation (gradient counter-rotation;
    for the baked-sign tables that is exactly an S sign flip)."""
    xf = x.astype(jnp.float32)
    partner = pltpu.roll(xf, x.shape[-1] // 2, 1)
    ps = partner * sin_ref[...]
    out = xf * cos_ref[...] + (-ps if neg else ps)
    return out.astype(x.dtype)


def _to_rows(x, group):
    """[B, H, L, D] (H = G*group) -> grouped kernel layout
    [B*G, L*group, D], row = pos*group + u for head g*group + u."""
    B, H, L, D = x.shape
    G = H // group
    return (x.reshape(B, G, group, L, D).transpose(0, 1, 3, 2, 4)
            .reshape(B * G, L * group, D))


def _from_rows(x, B, group):
    """Inverse of `_to_rows`: [B*G, L*group, D] -> [B, G*group, L, D]."""
    BG, R, D = x.shape
    G = BG // B
    L = R // group
    return (x.reshape(B, G, L, group, D).transpose(0, 1, 3, 2, 4)
            .reshape(B, G * group, L, D))


def _masked_scores(q, k, scale, causal, q_off, kv_off, fill, group=1):
    """s = (q.k^T)*scale with causal masking by global positions: q row
    r is position q_off + r//group (grouped GQA layout; group=1 is the
    plain layout). Only blocks straddling the diagonal pay the
    elementwise mask pass (the kernels are VPU-bound, every pass
    counts); `fill` is -inf for scores, 0 for probabilities."""
    block_q, block_k = q.shape[0], k.shape[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # [BQ, BK]
    if not causal:
        return s

    def _mask(s):
        riota = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        rows = q_off + (riota // group if group > 1 else riota)
        cols = kv_off + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        return jnp.where(rows >= cols, s, fill)

    # q_off is the POSITION of the block's first row.
    straddles = kv_off + (block_k - 1) > q_off
    return jax.lax.cond(straddles, _mask, lambda s: s, s)


def _online_softmax_update(s, v_ref, acc_ref, m_ref, l_ref, guard_empty):
    """One online-softmax block update of the (acc, m, l) state refs.
    `guard_empty` handles rows no block has touched yet (m == -inf, the
    ring-step case where visitation order is data-dependent); the plain
    forward's ascending k order makes the first visible block cover
    every row, so it skips the two extra passes."""
    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    if guard_empty:
        alpha = jnp.where(jnp.isneginf(m_new), 0.0, alpha)
        p = jnp.where(jnp.isneginf(m_new), 0.0, p)
    l_ref[...] = jnp.broadcast_to(
        l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape)
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _fwd_kernel(*refs, scale, causal, num_kb, bqp, group, rotary):
    # q_ref: [BQ, D]; k_ref/v_ref: [BK, D]; o_ref: [BQ, D];
    # scratch: acc [BQ, D] f32, m/l [BQ, 128] f32 (state across k steps)
    # + qrot [BQ, D] under fused rotary (q rotated ONCE per q block at
    # kj==0). bqp = BQ // group: positions per q block (grouped GQA).
    if rotary:
        (q_ref, k_ref, v_ref, qc_ref, qs_ref, kc_ref, ks_ref, o_ref,
         lse_ref, acc_ref, m_ref, l_ref, qrot_ref) = refs
    else:
        (q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
         l_ref) = refs
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    block_k = k_ref.shape[0]

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        if rotary:
            qrot_ref[...] = _rot_apply(q_ref[...], qc_ref, qs_ref)

    # Causal: skip the compute (the fetch is pipelined regardless) of
    # k-blocks entirely above the diagonal. Position units.
    visible = (kj * block_k < (qi + 1) * bqp) if causal else kj >= 0

    @pl.when(visible)
    def _compute():
        # Matmuls take the inputs' native (bf16) dtype — the MXU's fast
        # path — and accumulate in f32; only softmax runs in f32.
        if rotary:
            q = qrot_ref[...]
            k = _rot_apply(k_ref[...], kc_ref, ks_ref)
        else:
            q = q_ref[...]
            k = k_ref[...]
        s = _masked_scores(q, k, scale, causal,
                           q_off=qi * bqp, kv_off=kj * block_k,
                           fill=-jnp.inf, group=group)
        _online_softmax_update(s, v_ref, acc_ref, m_ref, l_ref,
                               guard_empty=False)

    @pl.when(kj == num_kb - 1)
    def _finalize():
        l = l_ref[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)  # rows with no visible keys
        o_ref[...] = (acc_ref[...] / l).astype(o_ref.dtype)
        # Log-sum-exp per row, saved for the backward recompute.
        lse_ref[...] = jnp.broadcast_to(m_ref[:, :1] + jnp.log(l),
                                        lse_ref.shape)


def _pick_block(L, preferred):
    for b in (preferred, 512, 256, 128):
        if b <= preferred and L % b == 0:
            return b
    return None


def _pick_rows_block(L, preferred, group):
    """Row-block size. group=1: the plain picker. Grouped GQA layouts
    pick `bqp` positions * `group` interleaved head rows with bqp | L
    and total rows at most the preference (for grouped layouts that is
    `_grouped_blocks`' row cap, swept separately from the plain row
    budgets); bqp >= 8 keeps the resulting rows a sublane multiple for
    any group."""
    if group == 1:
        return _pick_block(L, preferred)
    for bqp in (512, 256, 128, 64, 32, 16, 8):
        if bqp * group <= preferred and L % bqp == 0:
            return bqp * group
    return None


def _grouped_blocks(D, L, group, backward=False):
    """(rows_cap, block_k) for grouped-GQA layouts. v5e sweeps
    (examples/flash_block_sweep.py --G N) at L=8192: grouped blocks
    want MORE rows and a NARROWER k block than the plain policy —
    D=128 group=3: fwd 1536/512 beats the plain-cap 384/512 by 10%
    AND plain MHA itself by 1.4%; bwd 1536/512 is 22% under the
    plain-cap pick and 19% under plain MHA (the in-kernel dK/dV group
    reduction writes G instead of H heads). D=64 group=4: 2048/512
    beats the plain-cap 512/1024 by 6% fwd / 10% fwd+bwd (2048/1024
    overflows VMEM — s alone is 8 MB f32). Shapes without sweep data
    (short L) keep the conservative plain-preference cap.

    Interpolation caveat for UNSWEPT group sizes: the caps above were
    measured at group=4 (D<=64 -> 2048 rows) and group=3 (D>64 -> 1536
    rows) only, and are applied to every group>1 at long L. For other
    groups the power-of-two bqp search in _pick_rows_block then lands
    on smaller row blocks than the cap suggests (e.g. group=2, D=64:
    bqp=512 -> 1024 rows, not 2048) — a performance-only divergence
    from a hypothetical per-group optimum, never a correctness issue
    (_check_blocks still enforces exact tiling). Extend the sweep
    (examples/flash_block_sweep.py --G N) before trusting these caps
    for a new production group size."""
    pq, pk = _default_blocks(D, L, backward)
    long_seq = L is not None and L >= 4096
    if group > 1 and long_seq:
        cap = 1536 if D > 64 else 2048
        return cap, (512 if L % 512 == 0 else pk)
    return pq, pk


def _default_blocks(D, L=None, backward=False):
    """Preferred (block_q, block_k) by head dim and sequence length,
    from v5e sweeps (examples/flash_block_sweep.py): (256, 512) at
    D=128; D<=64 leaves VMEM headroom for wider blocks — (256, 1024)
    forward / (512, 1024) backward at L=2048. Long sequences amortize
    still-bigger q blocks (L=8192 sweep: fwd (512,1024) 8.95 vs 10.39
    ms/layer, bwd (1024,1024) ~15.7 vs ~17.1): at L>=4096 the q block
    doubles. ONE definition for the plain and ring paths so a retune
    can't leave them inconsistent."""
    long_seq = L is not None and L >= 4096
    if D <= 64:
        if backward:
            return (1024, 1024) if long_seq else (512, 1024)
        return (512, 1024) if long_seq else (256, 1024)
    # D=128 at L=8192: fwd (512,512) 6.12 vs 8.24 ms/layer for the
    # L=2048-swept (256,512); bwd (512,1024) ~8.2 vs ~10.3.
    if long_seq:
        return (512, 1024) if backward else (512, 512)
    return (256, 512)


def _kv_index_map(bqp, bk, causal, rank2=False):
    """k/v BlockSpec index map for grids with k innermost (position
    units: bqp = positions per q block). Causal runs clamp the k-block
    index to the diagonal frontier: steps above the diagonal revisit
    the frontier block, and Pallas skips the DMA for a revisited index
    — halving k/v HBM traffic at long L (the compute is separately
    gated by `pl.when(visible)`). ``rank2`` drops the batch coordinate
    (the rotary tables have no batch dim)."""
    if not causal:
        if rank2:
            return lambda b, i, j: (j, 0)
        return lambda b, i, j: (b, j, 0)
    if rank2:
        return lambda b, i, j: (jnp.minimum(j, ((i + 1) * bqp - 1) // bk), 0)
    return lambda b, i, j: (b, jnp.minimum(j, ((i + 1) * bqp - 1) // bk), 0)


def _q_index_map(bqp, bk, causal, rank2=False):
    """q-side BlockSpec index map for the dk/dv grid (q innermost).
    Causal runs clamp the q-block index UP to the first block at or
    below the diagonal (qi_min = (kj*bk)//bqp, position units): the
    leading invisible steps revisit that block, skipping their DMA."""
    if not causal:
        if rank2:
            return lambda b, j, i: (i, 0)
        return lambda b, j, i: (b, i, 0)
    if rank2:
        return lambda b, j, i: (jnp.maximum(i, (j * bk) // bqp), 0)
    return lambda b, j, i: (b, jnp.maximum(i, (j * bk) // bqp), 0)


def _require_rows_block(L, preferred, group, what):
    b = _pick_rows_block(L, preferred, group)
    if b is None:
        raise ValueError(
            f"{what}={L} must be a multiple of 128 (or of 8*group for "
            f"grouped kv heads, group={group}) for the Pallas ring "
            f"kernels; pad the sequence shard or use the jnp ring path")
    return b


def _check_blocks(rows, L, bq, bk, group):
    """Fail loudly on block sizes that do not tile the arrays: a
    Pallas grid of rows//bq steps silently TRUNCATES coverage when bq
    does not divide the row count (observed in a block sweep — wrong
    results that look fast)."""
    if not bq or not bk or rows % bq or L % bk or bq % group:
        raise ValueError(
            f"invalid flash blocks: block_q={bq} must divide "
            f"rows={rows} and be a multiple of group={group}; "
            f"block_k={bk} must divide the kv length {L}")


def _row_positions(L, group):
    """Positions of the grouped-rows layout's rows for a full sequence
    starting at 0: row r = pos*group + u -> position r//group."""
    return jnp.repeat(jnp.arange(L, dtype=jnp.int32), group)


def _pallas_forward_lse(q, k, v, scale, causal, interpret,
                        block_q=None, block_k=None, rotary_base=None):
    """q [B, H, L, D], k/v [B, G, L, D] with G | H. Returns
    (out [B,H,L,D], lse [B*G, L*group, 8] f32) — lse is the per-row
    log-sum-exp the backward kernels need, in the grouped-rows layout
    (replicated over an 8-wide trailing dim: keeps the block
    Mosaic-tileable and the DMA a contiguous stripe; 1-wide measured
    slower, 128-wide wastes 16x the memory)."""
    B, H, L, D = q.shape
    G = k.shape[1]
    group = H // G
    qf = _to_rows(q, group)
    kf = k.reshape(B * G, L, D)
    vf = v.reshape(B * G, L, D)

    # Bigger blocks amortize per-grid-step overhead (the MXU work per
    # step is tiny); bounded so s [BQ, BK] and the double-buffered k/v
    # blocks stay well inside VMEM. Preferences are D-aware — see
    # _default_blocks.
    pq, pk = _grouped_blocks(D, L, group)
    bq = block_q or _pick_rows_block(L, pq, group)
    bk = block_k or _pick_block(L, pk)
    rows = L * group
    _check_blocks(rows, L, bq, bk, group)
    bqp = bq // group
    num_kb = L // bk
    rotary = rotary_base is not None
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               num_kb=num_kb, bqp=bqp, group=group,
                               rotary=rotary)
    grid = (B * G, rows // bq, num_kb)
    kv_im = _kv_index_map(bqp, bk, causal)
    q_spec = pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0))
    in_specs = [q_spec,
                pl.BlockSpec((None, bk, D), kv_im),
                pl.BlockSpec((None, bk, D), kv_im)]
    inputs = [qf, kf, vf]
    if rotary:
        qc, qs = _rope_tables(_row_positions(L, group), D, rotary_base)
        kc, ks = _rope_tables(jnp.arange(L, dtype=jnp.int32), D,
                              rotary_base)
        tq_spec = pl.BlockSpec((bq, D), lambda b, i, j: (i, 0))
        tk_spec = pl.BlockSpec((bk, D),
                               _kv_index_map(bqp, bk, causal, rank2=True))
        in_specs += [tq_spec, tq_spec, tk_spec, tk_spec]
        inputs += [qc, qs, kc, ks]
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            q_spec,
            pl.BlockSpec((None, bq, 8), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * G, rows, D), q.dtype),
            jax.ShapeDtypeStruct((B * G, rows, 8), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ] + ([pltpu.VMEM((bq, D), q.dtype)] if rotary else []),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*inputs)
    return _from_rows(out, B, group), lse


def _pallas_forward(q, k, v, scale, causal, interpret,
                    block_q=None, block_k=None, rotary_base=None):
    return _pallas_forward_lse(q, k, v, scale, causal, interpret,
                               block_q, block_k, rotary_base)[0]


def _ring_step_kernel(*refs, scale, causal, num_kb, bqp, group, rotary):
    """One ring-attention step as a flash kernel with carried state.

    Same online-softmax update as `_fwd_kernel`, but the (acc, m, l)
    state is loaded from the previous ring step's outputs instead of
    initialized, and written back un-normalized (the caller divides by l
    after the last ring step). Causal masking uses *global* token
    offsets — PER-BLOCK arrays in SMEM (q_offs_ref[qi], kv_offs_ref[kj],
    position units) rather than one scalar per shard, so a shard may
    hold discontiguous sequence chunks (the zigzag causal schedule) as
    long as chunk boundaries align with block boundaries. Block skipping
    is dynamic for the same reason. Fused rotary streams shard-global
    (C, S) tables built by the caller from the same offsets.
    """
    if rotary:
        (q_offs_ref, kv_offs_ref, q_ref, k_ref, v_ref, qc_ref, qs_ref,
         kc_ref, ks_ref, oi_ref, mi_ref, li_ref, oo_ref, mo_ref, lo_ref,
         acc_ref, m_ref, l_ref, qrot_ref) = refs
    else:
        (q_offs_ref, kv_offs_ref, q_ref, k_ref, v_ref, oi_ref, mi_ref,
         li_ref, oo_ref, mo_ref, lo_ref, acc_ref, m_ref, l_ref) = refs
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    q_off = q_offs_ref[qi]
    kv_off = kv_offs_ref[kj]

    @pl.when(kj == 0)
    def _load_state():
        acc_ref[...] = oi_ref[...]
        m_ref[...] = jnp.broadcast_to(mi_ref[:, :1], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(li_ref[:, :1], l_ref.shape)
        if rotary:
            qrot_ref[...] = _rot_apply(q_ref[...], qc_ref, qs_ref)

    # A k/v block entirely in this q block's future contributes nothing.
    visible = (kv_off <= q_off + bqp - 1) if causal else kj >= 0

    @pl.when(visible)
    def _compute():
        if rotary:
            q = qrot_ref[...]
            k = _rot_apply(k_ref[...], kc_ref, ks_ref)
        else:
            q = q_ref[...]
            k = k_ref[...]
        s = _masked_scores(q, k, scale, causal, q_off=q_off,
                           kv_off=kv_off, fill=-jnp.inf, group=group)
        _online_softmax_update(s, v_ref, acc_ref, m_ref, l_ref,
                               guard_empty=True)

    @pl.when(kj == num_kb - 1)
    def _store_state():
        oo_ref[...] = acc_ref[...]
        mo_ref[...] = jnp.broadcast_to(m_ref[:, :1], mo_ref.shape)
        lo_ref[...] = jnp.broadcast_to(l_ref[:, :1], lo_ref.shape)


def _chunk_len(L, offset, what):
    """Chunk length for a scalar shard offset (one chunk = the shard)
    or a 1-D array of per-chunk offsets (equal chunks)."""
    arr = jnp.asarray(offset)
    if arr.ndim == 0:
        return L
    if L % arr.shape[0]:
        raise ValueError(f"{what}: {arr.shape[0]} chunks must divide "
                         f"shard length {L}")
    return L // arr.shape[0]


def _block_offsets(offset, L, blk):
    """Per-block global offsets (L // blk,) int32 from a scalar shard
    offset or a 1-D array of per-chunk offsets (equal chunks whose
    length must be a multiple of blk — blocks may not straddle chunk
    boundaries). Position units throughout."""
    off = jnp.asarray(offset, jnp.int32)
    pos = jnp.arange(L // blk, dtype=jnp.int32) * blk
    if off.ndim == 0:
        return off + pos
    Lc = L // off.shape[0]
    if Lc % blk:
        # Reachable only via an explicit block_q/block_k override that
        # bypasses the _require_rows_block(chunk_len, ...) pick: a block
        # spanning two discontiguous chunks would get one (wrong)
        # offset and silently mis-mask.
        raise ValueError(
            f"block size {blk} must divide the chunk length {Lc} "
            f"(chunked shards cannot have blocks straddling chunk "
            f"boundaries)")
    return off[pos // Lc] + pos % Lc


def shard_positions(offset, L):
    """Global positions [L] of a shard described by a scalar offset or
    a 1-D array of per-chunk offsets (the `_block_offsets` convention);
    used for the ring path's rotary tables and post-loop
    counter-rotation."""
    off = jnp.asarray(offset, jnp.int32)
    if off.ndim == 0:
        return off + jnp.arange(L, dtype=jnp.int32)
    Lc = L // off.shape[0]
    return (off[:, None] +
            jnp.arange(Lc, dtype=jnp.int32)[None]).reshape(-1)


def _ring_tables(q_offset, kv_offset, Lq, Lk, D, group, rotary_base):
    """(qc, qs, kc, ks) rotary tables for one ring step, from the
    shard/chunk offsets (shard-global positions; q in grouped-rows
    order)."""
    qpos = jnp.repeat(shard_positions(q_offset, Lq), group)
    kpos = shard_positions(kv_offset, Lk)
    qc, qs = _rope_tables(qpos, D, rotary_base)
    kc, ks = _rope_tables(kpos, D, rotary_base)
    return qc, qs, kc, ks


def flash_ring_step(q, k, v, o, m, l, q_offset, kv_offset, causal=True,
                    scale=None, interpret=False, block_q=None,
                    block_k=None, group=1, rotary_base=None):
    """One ring-attention local step over kernel-layout shards.

    Args: q [BG, Lq*group, D] grouped-rows layout (bf16/f32; group=1 is
    the plain [B*H, Lq, D] layout), k/v [BG, Lk, D], carried state
    o [BG, Lq*group, D] f32 (un-normalized accumulator), m/l
    [BG, Lq*group, 8] f32 (running max / normalizer stripes),
    q_offset/kv_offset global token POSITION offsets — traced int32
    scalars (contiguous shards), or 1-D arrays of per-chunk offsets for
    shards holding several equal discontiguous chunks (the zigzag
    causal schedule). Returns updated (o, m, l)."""
    BG, rows, D = q.shape
    Lq = rows // group
    Lk = k.shape[1]
    if scale is None:
        scale = D ** -0.5
    Lcq = _chunk_len(Lq, q_offset, "q_offset")
    Lck = _chunk_len(Lk, kv_offset, "kv_offset")
    pq, pk = _grouped_blocks(D, Lq, group)
    bq = block_q or _require_rows_block(Lcq, pq, group, "q chunk length")
    bk = block_k or _require_rows_block(Lck, pk, 1, "k/v chunk length")
    _check_blocks(rows, Lk, bq, bk, group)
    bqp = bq // group
    num_kb = Lk // bk
    q_offs = _block_offsets(q_offset, Lq, bqp)
    kv_offs = _block_offsets(kv_offset, Lk, bk)
    rotary = rotary_base is not None
    kernel = functools.partial(_ring_step_kernel, scale=scale,
                               causal=causal, num_kb=num_kb, bqp=bqp,
                               group=group, rotary=rotary)
    grid = (BG, rows // bq, num_kb)
    q_spec = pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0))
    kv_spec = pl.BlockSpec((None, bk, D), lambda b, i, j: (b, j, 0))
    state_specs = [
        q_spec,
        pl.BlockSpec((None, bq, 8), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((None, bq, 8), lambda b, i, j: (b, i, 0)),
    ]
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # per-q-block offs
        pl.BlockSpec(memory_space=pltpu.SMEM),  # per-kv-block offs
        q_spec, kv_spec, kv_spec,
    ]
    inputs = [q_offs, kv_offs, q, k, v]
    if rotary:
        qc, qs, kc, ks = _ring_tables(q_offset, kv_offset, Lq, Lk, D,
                                      group, rotary_base)
        tq = pl.BlockSpec((bq, D), lambda b, i, j: (i, 0))
        tk = pl.BlockSpec((bk, D), lambda b, i, j: (j, 0))
        in_specs += [tq, tq, tk, tk]
        inputs += [qc, qs, kc, ks]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs + state_specs,
        out_specs=state_specs,
        out_shape=[
            jax.ShapeDtypeStruct((BG, rows, D), jnp.float32),
            jax.ShapeDtypeStruct((BG, rows, 8), jnp.float32),
            jax.ShapeDtypeStruct((BG, rows, 8), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ] + ([pltpu.VMEM((bq, D), q.dtype)] if rotary else []),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*(inputs + [o, m, l]))


def _ring_bwd_dq_kernel(*refs, scale, causal, num_kb, bqp, group,
                        rotary):
    """dQ contribution of one backward ring step (FlashAttention-2
    math, global offsets like `_ring_step_kernel`). The dq accumulator
    is carried *across ring steps* (dqi -> dqo, f32): each arriving k/v
    shard adds its `sum_k dS.K` term; no forward recompute — p comes
    from the saved per-row lse. With fused rotary the accumulator stays
    in ROTATED space across steps; the caller counter-rotates once
    after the last ring step."""
    if rotary:
        (q_offs_ref, kv_offs_ref, q_ref, k_ref, v_ref, qc_ref, qs_ref,
         kc_ref, ks_ref, do_ref, lse_ref, delta_ref, dqi_ref, dqo_ref,
         dq_acc, qrot_ref) = refs
    else:
        (q_offs_ref, kv_offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
         delta_ref, dqi_ref, dqo_ref, dq_acc) = refs
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    q_off = q_offs_ref[qi]
    kv_off = kv_offs_ref[kj]

    @pl.when(kj == 0)
    def _load():
        dq_acc[...] = dqi_ref[...]
        if rotary:
            qrot_ref[...] = _rot_apply(q_ref[...], qc_ref, qs_ref)

    visible = (kv_off <= q_off + bqp - 1) if causal else kj >= 0

    @pl.when(visible)
    def _compute():
        if rotary:
            q = qrot_ref[...]
            k = _rot_apply(k_ref[...], kc_ref, ks_ref)
        else:
            q = q_ref[...]
            k = k_ref[...]
        s = _masked_scores(q, k, scale, causal, q_off=q_off,
                           kv_off=kv_off, fill=-jnp.inf, group=group)
        p = jnp.exp(s - lse_ref[:, :1])  # masked entries: exp(-inf) = 0
        dp = jax.lax.dot_general(
            do_ref[...], v_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[:, :1]) * scale)
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == num_kb - 1)
    def _store():
        dqo_ref[...] = dq_acc[...]


def _ring_bwd_dkv_kernel(*refs, scale, causal, num_qb, bqp, group,
                         rotary):
    """dK/dV contribution of one backward ring step. The dk/dv
    accumulators travel around the ring with their k/v shard (the
    caller ppermutes them together), so after n steps each shard
    arrives home with its full gradient (dk in rotated space under
    fused rotary — counter-rotated at home after the loop). Grid
    (bg, k-block, q-block), q innermost sequential."""
    if rotary:
        (q_offs_ref, kv_offs_ref, q_ref, k_ref, v_ref, qc_ref, qs_ref,
         kc_ref, ks_ref, do_ref, lse_ref, delta_ref, dki_ref, dvi_ref,
         dko_ref, dvo_ref, dk_acc, dv_acc, krot_ref) = refs
    else:
        (q_offs_ref, kv_offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
         delta_ref, dki_ref, dvi_ref, dko_ref, dvo_ref, dk_acc,
         dv_acc) = refs
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    q_off = q_offs_ref[qi]
    kv_off = kv_offs_ref[kj]

    @pl.when(qi == 0)
    def _load():
        dk_acc[...] = dki_ref[...]
        dv_acc[...] = dvi_ref[...]
        if rotary:
            krot_ref[...] = _rot_apply(k_ref[...], kc_ref, ks_ref)

    visible = (q_off + bqp - 1 >= kv_off) if causal else qi >= 0

    @pl.when(visible)
    def _compute():
        if rotary:
            q = _rot_apply(q_ref[...], qc_ref, qs_ref)
            k = krot_ref[...]
        else:
            q = q_ref[...]
            k = k_ref[...]
        s = _masked_scores(q, k, scale, causal, q_off=q_off,
                           kv_off=kv_off, fill=-jnp.inf, group=group)
        p = jnp.exp(s - lse_ref[:, :1])  # masked entries: exp(-inf) = 0
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_ref[...], v_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[:, :1]) * scale).astype(q.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_qb - 1)
    def _store():
        dko_ref[...] = dk_acc[...]
        dvo_ref[...] = dv_acc[...]


def flash_ring_bwd_step(q, k, v, do, lse, delta, dq, dk, dv, q_offset,
                        kv_offset, causal=True, scale=None,
                        interpret=False, block_q=None, block_k=None,
                        group=1, rotary_base=None):
    """One backward ring step over kernel-layout shards.

    Args: q/do [BG, Lq*group, D] grouped-rows layout, k/v [BG, Lk, D],
    lse/delta [BG, Lq*group, 8] f32 (per-row log-sum-exp from the
    forward; delta = rowsum(dO*O)), dq [BG, Lq*group, D] f32 (local
    accumulator), dk/dv [BG, Lk, D] f32 (accumulators traveling with
    the k/v shard), q_offset/kv_offset global token position offsets.
    Returns updated (dq, dk, dv). Under fused rotary, dq and dk stay
    in rotated space — counter-rotate after the last ring step with
    `apply_rotary(..., neg=True)`."""
    BG, rows, D = q.shape
    Lq = rows // group
    Lk = k.shape[1]
    if scale is None:
        scale = D ** -0.5
    Lcq = _chunk_len(Lq, q_offset, "q_offset")
    Lck = _chunk_len(Lk, kv_offset, "kv_offset")
    pq, pk = _grouped_blocks(D, Lq, group, backward=True)
    bq = block_q or _require_rows_block(Lcq, pq, group, "q chunk length")
    bk = block_k or _require_rows_block(Lck, pk, 1, "k/v chunk length")
    _check_blocks(rows, Lk, bq, bk, group)
    bqp = bq // group
    num_kb, num_qb = Lk // bk, rows // bq
    q_offs = _block_offsets(q_offset, Lq, bqp)
    kv_offs = _block_offsets(kv_offset, Lk, bk)
    rotary = rotary_base is not None
    if rotary:
        tables = list(_ring_tables(q_offset, kv_offset, Lq, Lk, D,
                                   group, rotary_base))
    else:
        tables = []

    q_spec = lambda b, i, j: (b, i, 0)      # noqa: E731
    stripe_spec = lambda b, i, j: (b, i, 0)  # noqa: E731
    table_specs_ki = ([pl.BlockSpec((bq, D), lambda b, i, j: (i, 0))] * 2
                      + [pl.BlockSpec((bk, D),
                                      lambda b, i, j: (j, 0))] * 2
                      if rotary else [])

    dq = pl.pallas_call(
        functools.partial(_ring_bwd_dq_kernel, scale=scale, causal=causal,
                          num_kb=num_kb, bqp=bqp, group=group,
                          rotary=rotary),
        grid=(BG, num_qb, num_kb),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((None, bq, D), q_spec),
            pl.BlockSpec((None, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, bk, D), lambda b, i, j: (b, j, 0)),
        ] + table_specs_ki + [
            pl.BlockSpec((None, bq, D), q_spec),
            pl.BlockSpec((None, bq, 8), stripe_spec),
            pl.BlockSpec((None, bq, 8), stripe_spec),
            pl.BlockSpec((None, bq, D), q_spec),
        ],
        out_specs=pl.BlockSpec((None, bq, D), q_spec),
        out_shape=jax.ShapeDtypeStruct((BG, rows, D), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)] + (
            [pltpu.VMEM((bq, D), q.dtype)] if rotary else []),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q_offs, kv_offs, q, k, v, *tables, do, lse, delta, dq)

    k_spec = lambda b, j, i: (b, j, 0)  # noqa: E731
    table_specs_qi = ([pl.BlockSpec((bq, D), lambda b, j, i: (i, 0))] * 2
                      + [pl.BlockSpec((bk, D),
                                      lambda b, j, i: (j, 0))] * 2
                      if rotary else [])
    dk, dv = pl.pallas_call(
        functools.partial(_ring_bwd_dkv_kernel, scale=scale,
                          causal=causal, num_qb=num_qb, bqp=bqp,
                          group=group, rotary=rotary),
        grid=(BG, num_kb, num_qb),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((None, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, bk, D), k_spec),
            pl.BlockSpec((None, bk, D), k_spec),
        ] + table_specs_qi + [
            pl.BlockSpec((None, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, bq, 8), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, bq, 8), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, bk, D), k_spec),
            pl.BlockSpec((None, bk, D), k_spec),
        ],
        out_specs=[
            pl.BlockSpec((None, bk, D), k_spec),
            pl.BlockSpec((None, bk, D), k_spec),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BG, Lk, D), jnp.float32),
            jax.ShapeDtypeStruct((BG, Lk, D), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)] + (
            [pltpu.VMEM((bk, D), k.dtype)] if rotary else []),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q_offs, kv_offs, q, k, v, *tables, do, lse, delta, dk, dv)
    return dq, dk, dv


def _bwd_dq_kernel(*refs, scale, causal, num_kb, bqp, group, rotary):
    """dQ: grid (bg, q-block, k-block), k innermost sequential.
    Recomputes p = exp(s - lse) per block; dS = p * (dO.V^T - delta);
    dQ = sum_k dS.K * scale accumulated in VMEM scratch. lse and
    delta = rowsum(dO*O) are precomputed per row and streamed in.
    Fused rotary: q rotated once per q block into scratch (kj==0);
    accumulate in rotated space, counter-rotate the finished block at
    finalize."""
    if rotary:
        (q_ref, k_ref, v_ref, qc_ref, qs_ref, kc_ref, ks_ref, do_ref,
         lse_ref, delta_ref, dq_ref, dq_acc, qrot_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
         dq_acc) = refs
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    block_k = k_ref.shape[0]

    @pl.when(kj == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)
        if rotary:
            qrot_ref[...] = _rot_apply(q_ref[...], qc_ref, qs_ref)

    visible = (kj * block_k < (qi + 1) * bqp) if causal else kj >= 0

    @pl.when(visible)
    def _compute():
        if rotary:
            q = qrot_ref[...]
            k = _rot_apply(k_ref[...], kc_ref, ks_ref)
        else:
            q = q_ref[...]
            k = k_ref[...]
        s = _masked_scores(q, k, scale, causal,
                           q_off=qi * bqp, kv_off=kj * block_k,
                           fill=-jnp.inf, group=group)
        p = jnp.exp(s - lse_ref[:, :1])  # masked entries: exp(-inf) = 0
        dp = jax.lax.dot_general(
            do_ref[...], v_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[:, :1]) * scale
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == num_kb - 1)
    def _finalize():
        dq = dq_acc[...]
        if rotary:
            dq = _rot_apply(dq, qc_ref, qs_ref, neg=True)
        dq_ref[...] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal, num_qb, bqp, group, rotary):
    """dK/dV: grid (bg, k-block, q-block), q innermost sequential.
    dV = sum_q P^T.dO; dK = sum_q dS^T.Q * scale. In the grouped GQA
    layout the q rows interleave the whole head group, so the group
    reduction of dK/dV happens in these same accumulators. Fused
    rotary: k rotated once per OUTER k block into scratch (qi==0, the
    block is fixed across the inner q sweep); q rotated per visit (a
    fresh DMA each step anyway); dK counter-rotated at finalize (dV is
    rotation-free)."""
    if rotary:
        (q_ref, k_ref, v_ref, qc_ref, qs_ref, kc_ref, ks_ref, do_ref,
         lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
         krot_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
         dv_ref, dk_acc, dv_acc) = refs
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    block_k = k_ref.shape[0]

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)
        if rotary:
            krot_ref[...] = _rot_apply(k_ref[...], kc_ref, ks_ref)

    # Causal: q blocks entirely above this k block see none of it.
    visible = (qi * bqp + (bqp - 1) >= kj * block_k) if causal \
        else qi >= 0

    @pl.when(visible)
    def _compute():
        if rotary:
            q = _rot_apply(q_ref[...], qc_ref, qs_ref)
            k = krot_ref[...]
        else:
            q = q_ref[...]
            k = k_ref[...]
        s = _masked_scores(q, k, scale, causal,
                           q_off=qi * bqp, kv_off=kj * block_k,
                           fill=-jnp.inf, group=group)
        p = jnp.exp(s - lse_ref[:, :1])  # masked entries: exp(-inf) = 0
        p_lo = p.astype(do_ref.dtype)
        dv_acc[...] += jax.lax.dot_general(
            p_lo, do_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_ref[...], v_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[:, :1]) * scale).astype(q.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_qb - 1)
    def _finalize():
        dk = dk_acc[...]
        if rotary:
            dk = _rot_apply(dk, kc_ref, ks_ref, neg=True)
        dk_ref[...] = dk.astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _pallas_backward(q, k, v, out, lse, g, scale, causal, interpret,
                     block_q=None, block_k=None, rotary_base=None):
    """Pallas backward: q/out/g [B,H,L,D], k/v [B,G,L,D], lse in the
    grouped-rows layout. Returns (dq [B,H,L,D], dk/dv [B,G,L,D]) in the
    inputs' dtypes."""
    B, H, L, D = q.shape
    G = k.shape[1]
    group = H // G
    qf, gf, outf = (_to_rows(x, group) for x in (q, g, out))
    kf = k.reshape(B * G, L, D)
    vf = v.reshape(B * G, L, D)
    # delta = rowsum(dO * O): one fused XLA pass, streamed into both
    # kernels per q block (recomputing it per grid step would redo the
    # reduction num_kb/num_qb times).
    delta = jnp.broadcast_to(
        jnp.sum(gf.astype(jnp.float32) * outf.astype(jnp.float32),
                axis=-1, keepdims=True), lse.shape)
    # Backward blocks are independent of the forward's (lse/delta
    # stripes are block-agnostic); see _default_blocks for the swept
    # preferences.
    pq, pk = _grouped_blocks(D, L, group, backward=True)
    bq = block_q or _pick_rows_block(L, pq, group)
    bk = block_k or _pick_block(L, pk)
    rows = L * group
    _check_blocks(rows, L, bq, bk, group)
    bqp = bq // group
    num_kb, num_qb = L // bk, rows // bq
    rotary = rotary_base is not None
    if rotary:
        qc, qs = _rope_tables(_row_positions(L, group), D, rotary_base)
        kc, ks = _rope_tables(jnp.arange(L, dtype=jnp.int32), D,
                              rotary_base)
        tables = [qc, qs, kc, ks]
    else:
        tables = []

    kv_im = _kv_index_map(bqp, bk, causal)
    tq_spec = pl.BlockSpec((bq, D), lambda b, i, j: (i, 0))
    tk_spec = pl.BlockSpec((bk, D),
                           _kv_index_map(bqp, bk, causal, rank2=True))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          num_kb=num_kb, bqp=bqp, group=group,
                          rotary=rotary),
        grid=(B * G, rows // bq, num_kb),
        in_specs=[
            pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bk, D), kv_im),
            pl.BlockSpec((None, bk, D), kv_im),
        ] + ([tq_spec, tq_spec, tk_spec, tk_spec] if rotary else []) + [
            pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bq, 8), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bq, 8), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * G, rows, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)] + (
            [pltpu.VMEM((bq, D), q.dtype)] if rotary else []),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, *tables, gf, lse, delta)

    q_im = _q_index_map(bqp, bk, causal)
    tq2_spec = pl.BlockSpec((bq, D), _q_index_map(bqp, bk, causal,
                                                  rank2=True))
    tk2_spec = pl.BlockSpec((bk, D), lambda b, j, i: (j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          num_qb=num_qb, bqp=bqp, group=group,
                          rotary=rotary),
        grid=(B * G, num_kb, num_qb),
        in_specs=[
            pl.BlockSpec((None, bq, D), q_im),
            pl.BlockSpec((None, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, bk, D), lambda b, j, i: (b, j, 0)),
        ] + ([tq2_spec, tq2_spec, tk2_spec, tk2_spec]
             if rotary else []) + [
            pl.BlockSpec((None, bq, D), q_im),
            pl.BlockSpec((None, bq, 8), q_im),
            pl.BlockSpec((None, bq, 8), q_im),
        ],
        out_specs=[
            pl.BlockSpec((None, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * G, L, D), k.dtype),
            jax.ShapeDtypeStruct((B * G, L, D), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)] + (
            [pltpu.VMEM((bk, D), k.dtype)] if rotary else []),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, *tables, gf, lse, delta)

    return (_from_rows(dq, B, group), dk.reshape(B, G, L, D),
            dv.reshape(B, G, L, D))


def _blockwise_reference(q, k, v, scale, causal, rotary_base=None):
    """Blockwise JAX attention, O(BLOCK_Q * L) live memory; used for the
    backward recompute and as the non-TPU fallback. q [B,H,L,D], k/v
    [B,G,L,D] — GQA repeats kv across each head group here (the kernel
    path never materializes that)."""
    B, H, L, D = q.shape
    G = k.shape[1]
    group = H // G
    if rotary_base is not None:
        pos = jnp.arange(L, dtype=jnp.int32)
        q = apply_rotary(q, pos, rotary_base)
        k = apply_rotary(k, pos, rotary_base)
    if group > 1:
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
    block_q = min(BLOCK_Q, L)

    def per_qblock(start, size):
        qs = lax.slice_in_dim(q, start, start + size, axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", qs.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        if causal:
            rows = start + lax.broadcasted_iota(jnp.int32, (size, L), 0)
            cols = lax.broadcasted_iota(jnp.int32, (size, L), 1)
            s = jnp.where((rows >= cols)[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p,
                          v.astype(jnp.float32)).astype(q.dtype)

    # Ceil-divide over q so a sequence remainder (L % block_q != 0) gets
    # its own (smaller, still static-shaped) tail block.
    blocks = [per_qblock(start, min(block_q, L - start))
              for start in range(0, L, block_q)]
    return blocks[0] if len(blocks) == 1 else jnp.concatenate(blocks, axis=2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, interpret, rotary_base=None):
    if interpret is None:
        return _blockwise_reference(q, k, v, scale, causal, rotary_base)
    return _pallas_forward(q, k, v, scale, causal, interpret,
                           rotary_base=rotary_base)


def _flash_fwd(q, k, v, scale, causal, interpret, rotary_base=None):
    if interpret is None:
        return _blockwise_reference(q, k, v, scale, causal,
                                    rotary_base), (q, k, v, None, None)
    out, lse = _pallas_forward_lse(q, k, v, scale, causal, interpret,
                                   rotary_base=rotary_base)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, interpret, rotary_base, res, g):
    q, k, v, out, lse = res
    if interpret is None:
        # Non-kernel path: recompute-blockwise VJP in plain JAX.
        _, vjp = jax.vjp(
            lambda q, k, v: _blockwise_reference(q, k, v, scale, causal,
                                                 rotary_base),
            q, k, v)
        return vjp(g)
    return _pallas_backward(q, k, v, out, lse, g, scale, causal,
                            interpret, rotary_base=rotary_base)


_flash.defvjp(_flash_fwd, _flash_bwd)


def analytic_attention_flops(B, H, L, D, causal=True, training=False):
    """FLOPs the Pallas attention kernels execute per call — XLA's
    compiled-cost analysis reports custom calls as ZERO flops, so
    benchmarks add this analytic count to keep MFU honest. Forward runs
    2 matmuls per (q,k) block pair (QK^T, PV); the backward kernels run
    7 matmul-equivalents (s and dp are recomputed in both the dQ and
    dK/dV kernels, plus the dQ/dK/dV products). ``training=True``
    therefore returns the FULL forward+backward step count (2 + 7 = 9
    per block pair) — callers must NOT add a separate forward term.
    Causal halves the visited block pairs. H is the number of QUERY
    heads — GQA/MQA change kv memory traffic, not attention FLOPs."""
    per_matmul = 2.0 * B * H * L * L * D
    if causal:
        per_matmul /= 2.0
    return (9.0 if training else 2.0) * per_matmul


def flash_attention(q, k, v, causal=True, scale=None, rotary_base=None):
    """Flash attention over [B, L, H, D] inputs (same layout as
    `parallel.ring.ring_attention`); returns [B, L, H, D] in q.dtype.

    GQA/MQA: pass k/v with fewer heads, [B, L, G, D] with G dividing H
    — query head h attends through kv head h // (H // G) (consecutive
    query heads share a kv head, the llama convention). ``rotary_base``
    fuses rotary position embedding (positions 0..L-1) into the
    kernels' q/k load path — do not also rotate outside.

    L must be a multiple of 128 to hit the Pallas kernel; other shapes
    (and non-TPU backends without interpret mode) use the blockwise JAX
    fallback, which is numerically identical.
    """
    B, L, H, D = q.shape
    G = k.shape[2]
    if H % G:
        raise ValueError(
            f"num_heads={H} must be a multiple of num_kv_heads={G}")
    group = H // G
    if scale is None:
        scale = D ** -0.5
    # Kernel layout: [B, H, L, D] / [B, G, L, D].
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    on_tpu = jax.default_backend() == "tpu"
    kernel_ok = (
        on_tpu and L % BLOCK_Q == 0 and
        _pick_rows_block(L, _grouped_blocks(D, L, group)[0], group)
        is not None and _pick_rows_block(
            L, _grouped_blocks(D, L, group, backward=True)[0], group)
        is not None)
    out = _flash(qt, kt, vt, scale, causal, False if kernel_ok else None,
                 rotary_base)
    return out.transpose(0, 2, 1, 3)
