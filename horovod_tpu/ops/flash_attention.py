"""Flash attention as a Pallas TPU kernel.

Forward: one grid program per (batch*head, q-block). The q block and the
full k/v for that head live in VMEM; the kernel streams k/v in BLOCK_K
slices with an online-softmax accumulator, so HBM traffic is O(L*D) and
VMEM is O(BLOCK*D) — the standard flash recipe, tiled to the MXU
(128-aligned blocks, bf16 inputs, f32 accumulation). Causal masking skips
whole k-blocks above the diagonal (the fori_loop bound is the q-block
index), not just elements.

Backward: custom VJP that recomputes attention blockwise over q in plain
JAX (O(BLOCK_Q * L) live memory) — XLA fuses it well, and it keeps the
kernel surface small. The softmax statistics are not saved; stability
comes from a fresh log-sum-exp per block.

On non-TPU backends the same kernel runs in Pallas interpret mode (tests)
or falls back to the blockwise JAX implementation.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
import jax.experimental.pallas as pl

BLOCK_Q = 128
BLOCK_K = 128


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_k):
    # q_ref: [BLOCK_Q, D]; k_ref/v_ref: [L, D]; o_ref: [BLOCK_Q, D]
    qi = pl.program_id(1)
    block_q = q_ref.shape[0]
    seq_len = k_ref.shape[0]
    num_kb = seq_len // block_k

    q = q_ref[:].astype(jnp.float32) * scale

    acc = jnp.zeros((block_q, q_ref.shape[1]), jnp.float32)
    m = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)

    # Causal: k-blocks strictly above the diagonal contribute nothing —
    # bound the loop instead of masking them.
    kb_bound = jnp.minimum(qi + 1, num_kb) if causal else num_kb

    def body(j, carry):
        acc, m, l = carry
        k_blk = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [BQ, BK]
        if causal:
            rows = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc, m, l = lax.fori_loop(0, kb_bound, body, (acc, m, l))
    l = jnp.where(l == 0.0, 1.0, l)  # rows with no visible keys
    o_ref[:] = (acc / l).astype(o_ref.dtype)


def _pallas_forward(q, k, v, scale, causal, interpret):
    # q,k,v: [B, H, L, D]
    B, H, L, D = q.shape
    qf = q.reshape(B * H, L, D)
    kf = k.reshape(B * H, L, D)
    vf = v.reshape(B * H, L, D)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=BLOCK_K)
    grid = (B * H, L // BLOCK_Q)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, BLOCK_Q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, L, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, L, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, BLOCK_Q, D),
                               lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, L, D), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, L, D)


def _blockwise_reference(q, k, v, scale, causal):
    """Blockwise JAX attention, O(BLOCK_Q * L) live memory; used for the
    backward recompute and as the non-TPU fallback."""
    B, H, L, D = q.shape
    block_q = min(BLOCK_Q, L)
    num_qb = L // block_q

    def per_qblock(i):
        qs = lax.dynamic_slice_in_dim(q, i * block_q, block_q, axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", qs.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        if causal:
            rows = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, L), 0)
            cols = lax.broadcasted_iota(jnp.int32, (block_q, L), 1)
            s = jnp.where((rows >= cols)[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p,
                          v.astype(jnp.float32)).astype(q.dtype)

    blocks = [per_qblock(i) for i in range(num_qb)]
    return jnp.concatenate(blocks, axis=2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, scale, causal, interpret):
    if interpret is None:
        return _blockwise_reference(q, k, v, scale, causal)
    return _pallas_forward(q, k, v, scale, causal, interpret)


def _flash_fwd(q, k, v, scale, causal, interpret):
    return _flash(q, k, v, scale, causal, interpret), (q, k, v)


def _flash_bwd(scale, causal, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: _blockwise_reference(q, k, v, scale, causal),
        q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=True, scale=None):
    """Flash attention over [B, L, H, D] inputs (same layout as
    `parallel.ring.ring_attention`); returns [B, L, H, D] in q.dtype.

    L must be a multiple of 128 to hit the Pallas kernel; other shapes
    (and non-TPU backends without interpret mode) use the blockwise JAX
    fallback, which is numerically identical.
    """
    B, L, H, D = q.shape
    if scale is None:
        scale = D ** -0.5
    # Kernel layout: [B, H, L, D].
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    on_tpu = jax.default_backend() == "tpu"
    if L % BLOCK_Q != 0 or not on_tpu:
        out = _flash(qt, kt, vt, scale, causal, None)
    else:
        out = _flash(qt, kt, vt, scale, causal, False)
    return out.transpose(0, 2, 1, 3)
