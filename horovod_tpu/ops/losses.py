"""Memory-lean LM losses.

`chunked_softmax_cross_entropy` computes causal-LM cross entropy
without ever materializing the full [B, L, vocab] logits tensor in
f32: it scans over sequence chunks, projecting each chunk to the
vocabulary, reducing it to logsumexp + target-logit immediately, and
rematerializing the chunk projection in the backward
(``jax.checkpoint``) — peak live memory is O(B * chunk * vocab)
instead of O(B * L * vocab). At GPT-2-small shapes (V=32k) the dense
f32 logits + softmax of a [8, 2048] batch is ~4 GB of HBM traffic per
pass; at L=8192 the dense form does not fit a single v5e at all, the
chunked form does.

No reference analogue (the reference never sees model internals); this
is part of the long-context extension the flash kernels anchor.
"""

import jax
import jax.numpy as jnp
from jax import lax


def chunked_softmax_cross_entropy(hidden, kernel, targets, chunk=512):
    """Mean token cross entropy over chunked vocab projections.

    Args:
      hidden: [B, L, D] final hidden states (any float dtype; the
        projection runs in the kernel's compute dtype and reduces in
        f32).
      kernel: [D, V] lm-head kernel (no bias, the standard LM head).
      targets: [B, L] int target token ids.
      chunk: sequence chunk length; L must be divisible by it (pass
        chunk=L for one-shot).

    Returns the scalar mean loss = mean(logsumexp(logits) -
    logits[target]) — identical math to log_softmax + gather.
    """
    B, L, D = hidden.shape
    if L % chunk != 0:
        raise ValueError("L=%d not divisible by chunk=%d" % (L, chunk))
    n = L // chunk
    h = hidden.reshape(B, n, chunk, D).transpose(1, 0, 2, 3)
    t = targets.reshape(B, n, chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def chunk_loss(h_c, t_c):
        logits = (h_c @ kernel.astype(h_c.dtype)).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, t_c[..., None],
                                  axis=-1)[..., 0]
        return jnp.sum(lse - tgt)

    def body(acc, xs):
        h_c, t_c = xs
        return acc + chunk_loss(h_c, t_c), None

    total, _ = lax.scan(body, jnp.float32(0.0), (h, t))
    return total / (B * L)
