"""Fused Pallas BatchNorm statistics for TPU.

Built to attack the PERF.md profile's biggest non-conv line
(`convert_reduce_fusion`, ~29 ms/step on ResNet-50 batch 256).
MEASURED OUTCOME (v5e, PERF.md "negative result" section): the stats
kernels beat XLA's reductions (~17.6 vs 29 ms/step) but the 53 Pallas
islands per direction cost ~80 ms/step in fusion-boundary copies/
reshapes/unfused masks — stock XLA BN wins for deep conv nets. Use
`PallasBatchNorm` where norm layers are few and wide; it is also the
package's sync-BN implementation (`axis_name`). Both reductions the
op needs —

* forward: per-channel sum and sum-of-squares of the activation, and
* backward: per-channel sum(dy) and sum(dy * x_hat)

— are computed by ONE Pallas kernel each: a single bf16 read of the
activation block, f32 accumulation in registers, both reductions of the
pair emitted together (XLA's lowering builds convert+reduce fusions per
reduction). The normalize / dx elementwise math stays in XLA on purpose:
there it fuses into neighboring producers/consumers (residual adds, ReLU
masks — the `multiply_add_fusion` lines), which a Pallas island cannot.

The reference delegates BN to cuDNN (no analogue source); this is the
TPU-native equivalent of its fused-BN dependence. Correctness is pinned
against `flax.linen.BatchNorm` in tests (interpret mode on CPU); v5e
measurement via `bench.py --model resnet50pbn`.

Layout contract: activations reshaped to (M, C), stats over axis 0.
M must be divisible by the block size (the caller picks the largest
power-of-two divisor within a VMEM byte budget; if that is < 8 rows the
plain XLA path is used — tiny inputs don't carry the bottleneck).
Narrow-channel layers (C <= 64, i.e. k*C stays within the 128-lane
register) are lane-packed: k rows fold into the lane dimension so every
VPU lane is live, with a (k, C) sum after the kernel. 64 < C < 128
cannot pack a whole row and keeps C lanes live.
"""

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


# ~16 MB VMEM/core; blocks are double-buffered (and the grad kernel
# reads two operands), so stay well under: 4 MB for the one-input
# stats pass, 2 MB per input for the two-input grad pass.
_STATS_BLOCK_BYTES = 4 * 1024 * 1024
_GRAD_BLOCK_BYTES = 2 * 1024 * 1024


def _pick_bm(M, C, itemsize, cap_bytes):
    """Largest power-of-two divisor of M whose (bm, C) block fits the
    byte budget. Blocks must be BIG: a 1024-row cap put the ResNet-50
    stem (M=3.2M) at ~3.1k sequential grid steps, and per-step overhead
    across 53 BN layers fwd+bwd cost more than the fused read saved
    (measured 189 vs 110 ms/step on v5e). At 4 MB the stem is 98
    steps."""
    # VMEM pads the lane dim to the next 128 multiple (C=64 -> 128,
    # C=288 -> 384), so budget by the padded width.
    padded_c = ((C + 127) // 128) * 128
    cap_rows = max(8, cap_bytes // (padded_c * itemsize))
    bm = 1
    while bm * 2 <= cap_rows and M % (bm * 2) == 0:
        bm *= 2
    return bm


def _pack_factor(M, C, itemsize, cap_bytes):
    """Lane packing: view (M, C) as (M/k, k*C) so narrow-channel layers
    (ResNet stem C=64) fill the VPU's 128 lanes; channel c lives at
    lanes c, C+c, ..., folded by a cheap (2, k, C) sum after the call.
    Only pack when the packed shape still yields a >=8-row block."""
    k = 1
    while C * (k * 2) <= 128 and M % (k * 2) == 0:
        k *= 2
    while k > 1 and _pick_bm(M // k, k * C, itemsize, cap_bytes) < 8:
        k //= 2
    return k


def _plan(shape, dtype, block_m, cap_bytes):
    """(k, Mp, Cp, bm) for a (M, C) reduction: pack factor, packed
    shape, block rows. An explicit block_m disables packing (tests pin
    block-size semantics on the unpacked layout)."""
    M, C = shape
    itemsize = jnp.dtype(dtype).itemsize
    k = 1 if block_m else _pack_factor(M, C, itemsize, cap_bytes)
    Mp, Cp = M // k, k * C
    bm = block_m or _pick_bm(Mp, Cp, itemsize, cap_bytes)
    return k, Mp, Cp, bm


def _fold(out, k, C):
    """Undo lane packing on a (2, k*C) kernel output."""
    return out.reshape(2, k, C).sum(axis=1) if k > 1 else out


def _stats_kernel(x_ref, out_ref):
    i = pl.program_id(0)
    xb = x_ref[...].astype(jnp.float32)
    blk = jnp.stack([jnp.sum(xb, axis=0), jnp.sum(xb * xb, axis=0)])

    @pl.when(i == 0)
    def _():
        out_ref[...] = blk

    @pl.when(i > 0)
    def _():
        out_ref[...] = out_ref[...] + blk


def batch_norm_stats(x2d, interpret=False, block_m=None):
    """Per-channel (sum, sum_of_squares) of a (M, C) array in one
    bf16-read f32-accumulate pass. Returns two (C,) f32 arrays."""
    M, C = x2d.shape
    k, Mp, Cp, bm = _plan(x2d.shape, x2d.dtype, block_m,
                          _STATS_BLOCK_BYTES)
    xp = x2d.reshape(Mp, Cp) if k > 1 else x2d
    out = pl.pallas_call(
        _stats_kernel,
        grid=(Mp // bm,),
        in_specs=[pl.BlockSpec((bm, Cp), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((2, Cp), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((2, Cp), jnp.float32),
        interpret=interpret,
    )(xp)
    out = _fold(out, k, C)
    return out[0], out[1]


def _grad_stats_kernel(dy_ref, x_ref, mean_ref, rstd_ref, out_ref):
    i = pl.program_id(0)
    dy = dy_ref[...].astype(jnp.float32)
    xb = x_ref[...].astype(jnp.float32)
    xhat = (xb - mean_ref[...]) * rstd_ref[...]
    blk = jnp.stack([jnp.sum(dy, axis=0), jnp.sum(dy * xhat, axis=0)])

    @pl.when(i == 0)
    def _():
        out_ref[...] = blk

    @pl.when(i > 0)
    def _():
        out_ref[...] = out_ref[...] + blk


def batch_norm_grad_stats(dy2d, x2d, mean, rstd, interpret=False,
                          block_m=None):
    """Per-channel (sum(dy), sum(dy * x_hat)) — i.e. (d_beta, d_gamma)
    — in one fused read of dy and x. mean/rstd are (C,) f32."""
    M, C = x2d.shape
    # Budget by the wider operand: the public API allows f32 dy with
    # bf16 x, and the dy block must fit the per-input budget too.
    wider = max((dy2d.dtype, x2d.dtype), key=lambda d: jnp.dtype(d).itemsize)
    k, Mp, Cp, bm = _plan(x2d.shape, wider, block_m, _GRAD_BLOCK_BYTES)
    dyp = dy2d.reshape(Mp, Cp) if k > 1 else dy2d
    xp = x2d.reshape(Mp, Cp) if k > 1 else x2d
    # Packed lane l holds channel l % C, so tile the per-channel stats.
    meanp = jnp.tile(mean, k) if k > 1 else mean
    rstdp = jnp.tile(rstd, k) if k > 1 else rstd
    out = pl.pallas_call(
        _grad_stats_kernel,
        grid=(Mp // bm,),
        in_specs=[
            pl.BlockSpec((bm, Cp), lambda i: (i, 0)),
            pl.BlockSpec((bm, Cp), lambda i: (i, 0)),
            pl.BlockSpec((1, Cp), lambda i: (0, 0)),
            pl.BlockSpec((1, Cp), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((2, Cp), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((2, Cp), jnp.float32),
        interpret=interpret,
    )(dyp, xp, meanp.reshape(1, Cp), rstdp.reshape(1, Cp))
    out = _fold(out, k, C)
    return out[0], out[1]


def _use_kernel(M):
    # The max(8, ...) floor in _pick_bm means the kernel-usable test
    # reduces to "M has a power-of-two divisor >= 8".
    return M % 8 == 0


def _stats(x2d, interpret):
    M, C = x2d.shape
    if interpret is not None and _use_kernel(M):
        s, ss = batch_norm_stats(x2d, interpret)
    else:
        xf = x2d.astype(jnp.float32)
        s, ss = jnp.sum(xf, axis=0), jnp.sum(xf * xf, axis=0)
    return s, ss


def _bn_train_fwd(x2d, gamma, beta, eps, interpret, axis_name=None):
    M, C = x2d.shape
    s, ss = _stats(x2d, interpret)
    if axis_name is not None:
        # Cross-replica (sync) BN: the kernels produce per-device
        # partial sums; one packed psum over the data axis makes the
        # statistics global. M_g = M * group size (equal shards).
        s, ss = jax.lax.psum((s, ss), axis_name)
        M = M * jax.lax.psum(1, axis_name)
    mean = s / M
    var = jnp.maximum(ss / M - mean * mean, 0.0)
    rstd = jax.lax.rsqrt(var + eps)
    a = gamma * rstd
    b = beta - mean * a
    # Normalize stays in XLA: it fuses with neighbors (residual/ReLU).
    y = (x2d.astype(jnp.float32) * a + b).astype(x2d.dtype)
    return (y, mean, var), (x2d, gamma, mean, rstd)


def _bn_train_bwd(eps, interpret, axis_name, res, cotangents):
    gy, gmean, gvar = cotangents
    x2d, gamma, mean, rstd = res
    M, C = x2d.shape
    gyf = gy.astype(jnp.float32) if gy.dtype != jnp.float32 else gy
    xf = x2d.astype(jnp.float32)
    xhat = (xf - mean) * rstd
    if interpret is not None and _use_kernel(M):
        dbeta, dgamma = batch_norm_grad_stats(gy, x2d, mean, rstd,
                                              interpret)
    else:
        dbeta = jnp.sum(gyf, axis=0)
        dgamma = jnp.sum(gyf * xhat, axis=0)
    if axis_name is not None:
        # dx needs the GLOBAL reductions over the sync group; the
        # returned dgamma/dbeta stay local — the training loop's
        # gradient allreduce completes them (matching what autodiff
        # of a psum-of-stats formulation yields).
        dbeta_g, dgamma_g = jax.lax.psum((dbeta, dgamma), axis_name)
        Mg = M * jax.lax.psum(1, axis_name)
    else:
        dbeta_g, dgamma_g, Mg = dbeta, dgamma, M
    dx = (gamma * rstd) * (gyf - dbeta_g / Mg - xhat * (dgamma_g / Mg))
    # Direct mean/var cotangent terms (zero in training use — running
    # stats aren't differentiated — and XLA folds the add-zeros away;
    # kept exact so jax.grad through mean/var is still correct).
    dx = dx + gmean / Mg + gvar * (2.0 / Mg) * (xf - mean)
    return dx.astype(x2d.dtype), dgamma, dbeta


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_batch_norm_train(x2d, gamma, beta, eps=1e-5, interpret=False,
                           axis_name=None):
    """Training-mode BN over (M, C): returns (y, mean, var) with the
    Pallas stats kernels on both the forward and the VJP path. mean /
    var are f32 batch statistics for the caller's running-stats
    update. `axis_name` enables cross-replica (sync) BN: statistics
    are psummed over that mesh axis (kernels stay per-device; one
    packed psum each way rides the ICI)."""
    return _bn_train_fwd(x2d, gamma, beta, eps, interpret, axis_name)[0]


def _bn_train_vjp_fwd(x2d, gamma, beta, eps, interpret, axis_name):
    return _bn_train_fwd(x2d, gamma, beta, eps, interpret, axis_name)


fused_batch_norm_train.defvjp(_bn_train_vjp_fwd, _bn_train_bwd)


try:
    import flax.linen as nn

    class PallasBatchNorm(nn.Module):
        """Drop-in for `nn.BatchNorm` (the subset ResNet uses) with the
        fused Pallas statistics path in training mode. Eval mode (
        `use_running_average=True`) is pure elementwise math and stays
        in XLA entirely."""
        use_running_average: bool = False
        momentum: float = 0.9
        epsilon: float = 1e-5
        dtype: Any = None
        param_dtype: Any = jnp.float32
        scale_init: Callable = nn.initializers.ones
        bias_init: Callable = nn.initializers.zeros
        axis_name: str = None  # sync BN: psum stats over this mesh axis
        interpret: bool = False

        @nn.compact
        def __call__(self, x):
            C = x.shape[-1]
            scale = self.param("scale", self.scale_init, (C,),
                               self.param_dtype)
            bias = self.param("bias", self.bias_init, (C,),
                              self.param_dtype)
            ra_mean = self.variable("batch_stats", "mean",
                                    lambda: jnp.zeros(C, jnp.float32))
            ra_var = self.variable("batch_stats", "var",
                                   lambda: jnp.ones(C, jnp.float32))
            if self.use_running_average:
                a = scale * jax.lax.rsqrt(ra_var.value + self.epsilon)
                b = bias - ra_mean.value * a
                return (x.astype(jnp.float32) * a + b).astype(
                    self.dtype or x.dtype)
            x2d = x.reshape(-1, C)
            interpret = self.interpret
            if jax.default_backend() != "tpu" and not interpret:
                interpret = None  # plain-XLA fallback off-TPU
            y, mean, var = fused_batch_norm_train(
                x2d, scale, bias, self.epsilon, interpret,
                self.axis_name)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var
            return y.reshape(x.shape).astype(self.dtype or x.dtype)
except ImportError:  # pragma: no cover - flax is baked into this env
    PallasBatchNorm = None
