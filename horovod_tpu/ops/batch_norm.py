"""Fused Pallas BatchNorm statistics for TPU.

Built to attack the PERF.md profile's biggest non-conv line
(`convert_reduce_fusion`, ~29 ms/step on ResNet-50 batch 256).
MEASURED OUTCOME (v5e, PERF.md "negative result" section): the stats
kernels beat XLA's reductions (~17.6 vs 29 ms/step) but the 53 Pallas
islands per direction cost ~80 ms/step in fusion-boundary copies/
reshapes/unfused masks — stock XLA BN wins for deep conv nets. Use
`PallasBatchNorm` where norm layers are few and wide; it is also the
package's sync-BN implementation (`axis_name`). Both reductions the
op needs —

* forward: per-channel sum and sum-of-squares of the activation, and
* backward: per-channel sum(dy) and sum(dy * x_hat)

— are computed by ONE Pallas kernel each: a single bf16 read of the
activation block, f32 accumulation in registers, both reductions of the
pair emitted together (XLA's lowering builds convert+reduce fusions per
reduction). The normalize / dx elementwise math stays in XLA on purpose:
there it fuses into neighboring producers/consumers (residual adds, ReLU
masks — the `multiply_add_fusion` lines), which a Pallas island cannot.

The reference delegates BN to cuDNN (no analogue source); this is the
TPU-native equivalent of its fused-BN dependence. Correctness is pinned
against `flax.linen.BatchNorm` in tests (interpret mode on CPU); v5e
measurement via `bench.py --model resnet50pbn`.

Layout contract: activations reshaped to (M, C), stats over axis 0.
M must be divisible by the block size (the caller picks the largest
power-of-two divisor within a VMEM byte budget; if that is < 8 rows the
plain XLA path is used — tiny inputs don't carry the bottleneck).
Narrow-channel layers (C <= 64, i.e. k*C stays within the 128-lane
register) are lane-packed: k rows fold into the lane dimension so every
VPU lane is live, with a (k, C) sum after the kernel. 64 < C < 128
cannot pack a whole row and keeps C lanes live.
"""

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


# ~16 MB VMEM/core; blocks are double-buffered (and the grad kernel
# reads two operands), so stay well under: 4 MB for the one-input
# stats pass, 2 MB per input for the two-input grad pass.
_STATS_BLOCK_BYTES = 4 * 1024 * 1024
_GRAD_BLOCK_BYTES = 2 * 1024 * 1024


def _pick_bm(M, C, itemsize, cap_bytes):
    """Largest power-of-two divisor of M whose (bm, C) block fits the
    byte budget. Blocks must be BIG: a 1024-row cap put the ResNet-50
    stem (M=3.2M) at ~3.1k sequential grid steps, and per-step overhead
    across 53 BN layers fwd+bwd cost more than the fused read saved
    (measured 189 vs 110 ms/step on v5e). At 4 MB the stem is 98
    steps."""
    # VMEM pads the lane dim to the next 128 multiple (C=64 -> 128,
    # C=288 -> 384), so budget by the padded width.
    padded_c = ((C + 127) // 128) * 128
    cap_rows = max(8, cap_bytes // (padded_c * itemsize))
    bm = 1
    while bm * 2 <= cap_rows and M % (bm * 2) == 0:
        bm *= 2
    return bm


def _pack_factor(M, C, itemsize, cap_bytes):
    """Lane packing: view (M, C) as (M/k, k*C) so narrow-channel layers
    (ResNet stem C=64) fill the VPU's 128 lanes; channel c lives at
    lanes c, C+c, ..., folded by a cheap (2, k, C) sum after the call.
    Only pack when the packed shape still yields a >=8-row block."""
    k = 1
    while C * (k * 2) <= 128 and M % (k * 2) == 0:
        k *= 2
    while k > 1 and _pick_bm(M // k, k * C, itemsize, cap_bytes) < 8:
        k //= 2
    return k


def _plan(shape, dtype, block_m, cap_bytes):
    """(k, Mp, Cp, bm) for a (M, C) reduction: pack factor, packed
    shape, block rows. An explicit block_m disables packing (tests pin
    block-size semantics on the unpacked layout)."""
    M, C = shape
    itemsize = jnp.dtype(dtype).itemsize
    k = 1 if block_m else _pack_factor(M, C, itemsize, cap_bytes)
    Mp, Cp = M // k, k * C
    bm = block_m or _pick_bm(Mp, Cp, itemsize, cap_bytes)
    return k, Mp, Cp, bm


def _fold(out, k, C):
    """Undo lane packing on a (2, k*C) kernel output."""
    return out.reshape(2, k, C).sum(axis=1) if k > 1 else out


def _stats_kernel(x_ref, out_ref):
    i = pl.program_id(0)
    xb = x_ref[...].astype(jnp.float32)
    blk = jnp.stack([jnp.sum(xb, axis=0), jnp.sum(xb * xb, axis=0)])

    @pl.when(i == 0)
    def _():
        out_ref[...] = blk

    @pl.when(i > 0)
    def _():
        out_ref[...] = out_ref[...] + blk


def batch_norm_stats(x2d, interpret=False, block_m=None):
    """Per-channel (sum, sum_of_squares) of a (M, C) array in one
    bf16-read f32-accumulate pass. Returns two (C,) f32 arrays."""
    M, C = x2d.shape
    k, Mp, Cp, bm = _plan(x2d.shape, x2d.dtype, block_m,
                          _STATS_BLOCK_BYTES)
    xp = x2d.reshape(Mp, Cp) if k > 1 else x2d
    out = pl.pallas_call(
        _stats_kernel,
        grid=(Mp // bm,),
        in_specs=[pl.BlockSpec((bm, Cp), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((2, Cp), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((2, Cp), jnp.float32),
        interpret=interpret,
    )(xp)
    out = _fold(out, k, C)
    return out[0], out[1]


def _grad_stats_kernel(dy_ref, x_ref, mean_ref, rstd_ref, out_ref):
    i = pl.program_id(0)
    dy = dy_ref[...].astype(jnp.float32)
    xb = x_ref[...].astype(jnp.float32)
    xhat = (xb - mean_ref[...]) * rstd_ref[...]
    blk = jnp.stack([jnp.sum(dy, axis=0), jnp.sum(dy * xhat, axis=0)])

    @pl.when(i == 0)
    def _():
        out_ref[...] = blk

    @pl.when(i > 0)
    def _():
        out_ref[...] = out_ref[...] + blk


def batch_norm_grad_stats(dy2d, x2d, mean, rstd, interpret=False,
                          block_m=None):
    """Per-channel (sum(dy), sum(dy * x_hat)) — i.e. (d_beta, d_gamma)
    — in one fused read of dy and x. mean/rstd are (C,) f32."""
    M, C = x2d.shape
    # Budget by the wider operand: the public API allows f32 dy with
    # bf16 x, and the dy block must fit the per-input budget too.
    wider = max((dy2d.dtype, x2d.dtype), key=lambda d: jnp.dtype(d).itemsize)
    k, Mp, Cp, bm = _plan(x2d.shape, wider, block_m, _GRAD_BLOCK_BYTES)
    dyp = dy2d.reshape(Mp, Cp) if k > 1 else dy2d
    xp = x2d.reshape(Mp, Cp) if k > 1 else x2d
    # Packed lane l holds channel l % C, so tile the per-channel stats.
    meanp = jnp.tile(mean, k) if k > 1 else mean
    rstdp = jnp.tile(rstd, k) if k > 1 else rstd
    out = pl.pallas_call(
        _grad_stats_kernel,
        grid=(Mp // bm,),
        in_specs=[
            pl.BlockSpec((bm, Cp), lambda i: (i, 0)),
            pl.BlockSpec((bm, Cp), lambda i: (i, 0)),
            pl.BlockSpec((1, Cp), lambda i: (0, 0)),
            pl.BlockSpec((1, Cp), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((2, Cp), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((2, Cp), jnp.float32),
        interpret=interpret,
    )(dyp, xp, meanp.reshape(1, Cp), rstdp.reshape(1, Cp))
    out = _fold(out, k, C)
    return out[0], out[1]


def _use_kernel(M):
    # The max(8, ...) floor in _pick_bm means the kernel-usable test
    # reduces to "M has a power-of-two divisor >= 8".
    return M % 8 == 0


def _stats(x2d, interpret):
    M, C = x2d.shape
    if interpret is not None and _use_kernel(M):
        s, ss = batch_norm_stats(x2d, interpret)
    else:
        xf = x2d.astype(jnp.float32)
        s, ss = jnp.sum(xf, axis=0), jnp.sum(xf * xf, axis=0)
    return s, ss


def _bn_train_fwd(x2d, gamma, beta, eps, interpret, axis_name=None):
    M, C = x2d.shape
    s, ss = _stats(x2d, interpret)
    if axis_name is not None:
        # Cross-replica (sync) BN: the kernels produce per-device
        # partial sums; one packed psum over the data axis makes the
        # statistics global. M_g = M * group size (equal shards).
        s, ss = jax.lax.psum((s, ss), axis_name)
        M = M * jax.lax.psum(1, axis_name)
    mean = s / M
    var = jnp.maximum(ss / M - mean * mean, 0.0)
    rstd = jax.lax.rsqrt(var + eps)
    a = gamma * rstd
    b = beta - mean * a
    # Normalize stays in XLA: it fuses with neighbors (residual/ReLU).
    y = (x2d.astype(jnp.float32) * a + b).astype(x2d.dtype)
    return (y, mean, var), (x2d, gamma, mean, rstd)


def _bn_train_bwd(eps, interpret, axis_name, res, cotangents):
    gy, gmean, gvar = cotangents
    x2d, gamma, mean, rstd = res
    M, C = x2d.shape
    gyf = gy.astype(jnp.float32) if gy.dtype != jnp.float32 else gy
    xf = x2d.astype(jnp.float32)
    xhat = (xf - mean) * rstd
    if interpret is not None and _use_kernel(M):
        dbeta, dgamma = batch_norm_grad_stats(gy, x2d, mean, rstd,
                                              interpret)
    else:
        dbeta = jnp.sum(gyf, axis=0)
        dgamma = jnp.sum(gyf * xhat, axis=0)
    if axis_name is not None:
        # dx needs the GLOBAL reductions over the sync group; the
        # returned dgamma/dbeta stay local — the training loop's
        # gradient allreduce completes them (matching what autodiff
        # of a psum-of-stats formulation yields).
        dbeta_g, dgamma_g = jax.lax.psum((dbeta, dgamma), axis_name)
        Mg = M * jax.lax.psum(1, axis_name)
    else:
        dbeta_g, dgamma_g, Mg = dbeta, dgamma, M
    dx = (gamma * rstd) * (gyf - dbeta_g / Mg - xhat * (dgamma_g / Mg))
    # Direct mean/var cotangent terms (zero in training use — running
    # stats aren't differentiated — and XLA folds the add-zeros away;
    # kept exact so jax.grad through mean/var is still correct).
    dx = dx + gmean / Mg + gvar * (2.0 / Mg) * (xf - mean)
    return dx.astype(x2d.dtype), dgamma, dbeta


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_batch_norm_train(x2d, gamma, beta, eps=1e-5, interpret=False,
                           axis_name=None):
    """Training-mode BN over (M, C): returns (y, mean, var) with the
    Pallas stats kernels on both the forward and the VJP path. mean /
    var are f32 batch statistics for the caller's running-stats
    update. `axis_name` enables cross-replica (sync) BN: statistics
    are psummed over that mesh axis (kernels stay per-device; one
    packed psum each way rides the ICI)."""
    return _bn_train_fwd(x2d, gamma, beta, eps, interpret, axis_name)[0]


def _bn_train_vjp_fwd(x2d, gamma, beta, eps, interpret, axis_name):
    return _bn_train_fwd(x2d, gamma, beta, eps, interpret, axis_name)


fused_batch_norm_train.defvjp(_bn_train_vjp_fwd, _bn_train_bwd)


# ---------------------------------------------------------------------------
# Traffic-lean BatchNorm (round 10): the graph-level answer to the round-4
# island tax. PERF.md's round-4 measurement proved Pallas stats kernels the
# wrong lever for deep conv nets on TPU (the ~11 ms stats win lost ~80 ms to
# fusion-boundary copies), so this path never leaves XLA's fusion graph and
# instead makes each activation pass TOUCH FEWER BYTES:
#
# * one-pass statistics: a single VARIADIC reduce emits (sum, sum-of-squares)
#   forward and (sum(dy), sum(dy*x_hat)) backward from ONE read of the
#   activation (XLA fuses the x*x / dy*x_hat producers into the reduce), vs
#   the per-quantity convert+reduce fusions the stock lowering builds;
# * a custom_vjp that saves only (x, mean, rstd) — x is the producing conv's
#   output and already live for ITS backward — and recomputes x_hat in the
#   backward, eliminating the stored-normalized-intermediate round trip
#   autodiff of the closed-form BN expression materializes (an extra f32
#   M x C residual per layer in a bf16 model);
# * optional fused ReLU (`relu=True`): y = max(bn(x), 0) in one epilogue,
#   with the backward MASK recomputed from the pre-activation sign
#   (x_hat * gamma + beta > 0) instead of saved.
#
# The same formulation carries the distributed plane: `axis_name=` psums the
# per-device partial sums over a mesh axis (in-jit sync BN), `group=` rides
# the HOST collectives with process-group scoping (docs/GROUPS.md — sync BN
# over the batch group of a 2-D mesh), and `groups=` splits the batch into
# ghost-BN virtual batches (arxiv 1705.08741; the large-per-chip-batch
# regularizer) — all through one (G, C)-shaped stats pipeline.
# ---------------------------------------------------------------------------


def onepass_stats(a, b, axis=0):
    """(sum(a), sum(b)) over `axis` as a pair of sibling reduce fusions,
    each a SINGLE fused read of its operand chain (the cast and the
    x*x / dy*x_hat producers fuse into the reduce), f32 accumulation.

    Measured pitfall, kept as the design note: a variadic tuple
    `lax.reduce((a, b), ...)` looks like "one pass" but XLA does NOT
    fuse elementwise producers into variadic reduces — the squared
    operand MATERIALIZED as a full f32 activation buffer (2R + 1W extra
    per stats pass, verified via per-instruction `cost_analysis`).
    Sibling single-operand reduces each take a fused producer chain, so
    the pair costs two reads and zero intermediate writes."""
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    return (jnp.sum(a.astype(jnp.float32), axis=axes),
            jnp.sum(b.astype(jnp.float32), axis=axes))


def _lean_sync(pair, axis_name, group, name):
    """Cross-rank reduction of a (stats_a, stats_b) pair: psum over the
    in-jit mesh axis, or one host-plane allreduce (group-scoped, stable
    name) when `group` is set. Returns (pair, replica_count)."""
    a, b = pair
    n = 1
    if axis_name is not None:
        a, b = jax.lax.psum((a, b), axis_name)
        n = jax.lax.psum(1, axis_name)
    elif group is not None:
        import horovod_tpu.jax as hvd_jax
        from horovod_tpu import groups as _grp
        grp = None if group == "world" else group
        stacked = hvd_jax.allreduce(jnp.stack([a, b]), average=False,
                                    name=name, group=grp)
        a, b = stacked[0], stacked[1]
        n = _grp.group_size(grp)
    return (a, b), n


def _ghost_view(x, groups):
    """(x reshaped for ghost groups, reduce axes, per-channel-stat
    shape for broadcasting). The leading batch axis splits into
    (groups, N//groups); the reshape is a leading-dim split — a
    bitcast, never a layout change (collapsing to (M, C) measured as a
    REGRESSION: the 2-D view through the custom-VJP boundary forced
    layout copies into the neighboring conv backward fusions)."""
    if groups == 1:
        return x, tuple(range(x.ndim - 1)), (x.shape[-1],)
    xg = x.reshape((groups, x.shape[0] // groups) + x.shape[1:])
    return xg, tuple(range(1, xg.ndim - 1)), \
        (groups,) + (1,) * (x.ndim - 1) + (x.shape[-1],)


def _lean_fwd(x, gamma, beta, eps, relu, groups, axis_name, group,
              sync_name):
    C = x.shape[-1]
    dt = x.dtype
    xg, axes, bshape = _ghost_view(x, groups)
    count_local = xg.size // (groups * C)
    # f32 cast + square fuse into the reduce producer: ONE read of the
    # (possibly bf16) activation, f32 accumulation, BOTH reductions.
    xf = xg.astype(jnp.float32)
    s, ss = onepass_stats(xf, xf * xf, axis=axes)   # (C,) or (G, C)
    (s, ss), n = _lean_sync((s, ss), axis_name, group, sync_name)
    count = count_local * n
    mean = s / count
    var = jnp.maximum(ss / count - mean * mean, 0.0)
    rstd = jax.lax.rsqrt(var + eps)
    a = gamma * rstd                                 # f32, stat-shaped
    b = beta - mean * a
    # Normalize in the COMPUTE dtype (flax's convention: stats in f32,
    # apply in dtype) — a bf16 model's activation passes stay 2-byte.
    y = xg * a.reshape(bshape).astype(dt) + b.reshape(bshape).astype(dt)
    if relu:
        y = jnp.maximum(y, jnp.zeros((), dt))
    return (y.reshape(x.shape), mean, var), (x, gamma, beta, mean, rstd)


def _lean_bwd(eps, relu, groups, axis_name, group, sync_name, res, ct):
    gy, gmean, gvar = ct
    x, gamma, beta, mean, rstd = res
    C = x.shape[-1]
    dt = x.dtype
    xg, axes, bshape = _ghost_view(x, groups)
    count_local = xg.size // (groups * C)
    gyg = gy.reshape(xg.shape)
    mean_b = mean.reshape(bshape)
    rstd_b = rstd.reshape(bshape)
    # x_hat recomputed (never stored), in the compute dtype for the
    # elementwise chain; the f32 casts below fuse into the reduce.
    xhat = (xg - mean_b.astype(dt)) * rstd_b.astype(dt)
    if relu:
        # The forward's ReLU mask, recomputed from the pre-activation
        # sign (y_pre = x_hat * gamma + beta) — never stored.
        pre = xhat * gamma.astype(dt) + beta.astype(dt)
        gyg = jnp.where(pre > 0, gyg, jnp.zeros((), dt))
    # Both backward reductions from one fused read of (gy, x), f32
    # accumulation.
    gyf = gyg.astype(jnp.float32)
    dbeta, dgamma = onepass_stats(gyf, gyf * xhat.astype(jnp.float32),
                                  axis=axes)
    # dx needs the reductions over the FULL sync scope; the returned
    # dgamma/dbeta stay local — the training loop's gradient allreduce
    # completes them (matching autodiff of a psum-of-stats formulation).
    (dbeta_g, dgamma_g), n = _lean_sync(
        (dbeta, dgamma), axis_name, group,
        sync_name + ".bwd" if sync_name else sync_name)
    count = count_local * n
    a_b = (gamma * rstd_b).astype(dt)
    dx = a_b * (gyg - (dbeta_g.reshape(bshape) / count).astype(dt) -
                xhat * (dgamma_g.reshape(bshape) / count).astype(dt))
    # Direct mean/var cotangents (zero in training use — running stats
    # are not differentiated — and XLA folds the mul-by-zero-constant
    # away; kept exact so jax.grad through the returned stats is still
    # correct).
    gmean_b = jnp.asarray(gmean, jnp.float32).reshape(bshape)
    gvar_b = jnp.asarray(gvar, jnp.float32).reshape(bshape)
    dx = dx + (gmean_b / count).astype(dt) + \
        (gvar_b * (2.0 / count)).astype(dt) * (xg - mean_b.astype(dt))
    if groups > 1:
        dgamma = dgamma.sum(axis=0)
        dbeta = dbeta.sum(axis=0)
    return (dx.reshape(x.shape), dgamma, dbeta)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def lean_batch_norm_train(x, gamma, beta, eps=1e-5, relu=False,
                          groups=1, axis_name=None, group=None,
                          sync_name="lean_bn"):
    """Training-mode traffic-lean BN over a channels-last activation of
    any rank (stats over every leading axis): returns (y, mean, var)
    with batch statistics in f32 for the caller's running-stats update.

    Pure XLA on both passes (no kernel islands — the round-4 lesson)
    and no layout-changing views (x keeps its native NHWC shape through
    the custom-VJP boundary): one-pass variadic-reduce statistics,
    residuals limited to (x, mean, rstd), x_hat (and the ``relu=True``
    mask, from the pre-activation sign) recomputed in the backward.

    ``groups`` > 1 is ghost BN: the leading batch axis splits into
    `groups` virtual batches normalized independently (mean/var come
    back as (G, C)). ``axis_name`` syncs statistics over an in-jit mesh
    axis; ``group`` syncs through the HOST collectives scoped to a
    process group (docs/GROUPS.md; pass the string "world" for
    whole-world sync) under the stable collective name ``sync_name`` —
    both make the statistics global over the participating replicas
    (sync BN).
    """
    return _lean_fwd(x, gamma, beta, eps, relu, groups, axis_name,
                     group, sync_name)[0]


lean_batch_norm_train.defvjp(_lean_fwd, _lean_bwd)


def bn_remat_policy():
    """Checkpoint policy for BN-scoped rematerialization: saves every
    residual EXCEPT the normalize-pass outputs (tagged
    ``hvd_bn_norm`` by :class:`LeanBatchNorm`), so the normalized
    activations are recomputed in the backward instead of stored —
    ``nn.remat(Block, policy=bn_remat_policy())`` or
    ``ResNet(..., bn_remat=True)``."""
    return jax.checkpoint_policies.save_anything_except_these_names(
        "hvd_bn_norm")


try:
    import flax.linen as nn

    class PallasBatchNorm(nn.Module):
        """Drop-in for `nn.BatchNorm` (the subset ResNet uses) with the
        fused Pallas statistics path in training mode. Eval mode (
        `use_running_average=True`) is pure elementwise math and stays
        in XLA entirely."""
        use_running_average: bool = False
        momentum: float = 0.9
        epsilon: float = 1e-5
        dtype: Any = None
        param_dtype: Any = jnp.float32
        scale_init: Callable = nn.initializers.ones
        bias_init: Callable = nn.initializers.zeros
        axis_name: str = None  # sync BN: psum stats over this mesh axis
        # Ghost BN (virtual batches normalized independently): routed
        # through the graph-level lean path — per-group stats would
        # multiply the kernel islands, the exact round-4 failure mode.
        virtual_batch_size: int = None
        interpret: bool = False

        @nn.compact
        def __call__(self, x):
            C = x.shape[-1]
            scale = self.param("scale", self.scale_init, (C,),
                               self.param_dtype)
            bias = self.param("bias", self.bias_init, (C,),
                              self.param_dtype)
            ra_mean = self.variable("batch_stats", "mean",
                                    lambda: jnp.zeros(C, jnp.float32))
            ra_var = self.variable("batch_stats", "var",
                                   lambda: jnp.ones(C, jnp.float32))
            if self.use_running_average:
                a = scale * jax.lax.rsqrt(ra_var.value + self.epsilon)
                b = bias - ra_mean.value * a
                return (x.astype(jnp.float32) * a + b).astype(
                    self.dtype or x.dtype)
            x2d = x.reshape(-1, C)
            if self.virtual_batch_size:
                N = x.shape[0]
                if N % self.virtual_batch_size:
                    raise ValueError(
                        "virtual_batch_size=%d does not divide the "
                        "batch %d" % (self.virtual_batch_size, N))
                groups = N // self.virtual_batch_size
                y, mean, var = lean_batch_norm_train(
                    x2d, scale, bias, self.epsilon, False,
                    groups, self.axis_name,
                    None, "lean_bn/%s" % "/".join(self.scope.path))
                if groups > 1:  # (G, C) group stats -> (C,) running
                    mean, var = mean.mean(axis=0), var.mean(axis=0)
            else:
                interpret = self.interpret
                if jax.default_backend() != "tpu" and not interpret:
                    interpret = None  # plain-XLA fallback off-TPU
                y, mean, var = fused_batch_norm_train(
                    x2d, scale, bias, self.epsilon, interpret,
                    self.axis_name)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var
            return y.reshape(x.shape).astype(self.dtype or x.dtype)

    class LeanBatchNorm(nn.Module):
        """Drop-in for ``nn.BatchNorm`` (the subset the conv zoo uses)
        on the traffic-lean graph-level path: one-pass variadic-reduce
        statistics, custom-VJP residuals limited to (x, mean, rstd),
        x_hat (and the ``fuse_relu`` mask) recomputed in the backward —
        never leaving XLA's fusion graph (the round-4 island-tax
        lesson, PERF.md).

        ``virtual_batch_size`` enables ghost BN: the leading batch dim
        splits into ``N // virtual_batch_size`` groups normalized
        independently (running stats average the group statistics).
        ``axis_name`` is in-jit cross-replica sync BN (psum over the
        mesh axis); ``sync_group`` syncs through the HOST collectives
        scoped to a process group — e.g. ``hvd.batch_group()`` under a
        2-D mesh (docs/GROUPS.md), or the string "world". The host
        collective's name derives from the module path (rank-identical
        by construction) unless ``sync_name`` is set.

        Outputs are tagged ``hvd_bn_norm`` for
        :func:`bn_remat_policy`-scoped rematerialization."""
        use_running_average: bool = False
        momentum: float = 0.9
        epsilon: float = 1e-5
        dtype: Any = None
        param_dtype: Any = jnp.float32
        scale_init: Callable = nn.initializers.ones
        bias_init: Callable = nn.initializers.zeros
        axis_name: str = None        # in-jit sync BN (psum)
        sync_group: Any = None       # host-plane sync BN (docs/GROUPS.md)
        sync_name: str = None
        virtual_batch_size: int = None  # ghost BN
        fuse_relu: bool = False

        @nn.compact
        def __call__(self, x):
            from jax.ad_checkpoint import checkpoint_name

            C = x.shape[-1]
            scale = self.param("scale", self.scale_init, (C,),
                               self.param_dtype)
            bias = self.param("bias", self.bias_init, (C,),
                              self.param_dtype)
            ra_mean = self.variable("batch_stats", "mean",
                                    lambda: jnp.zeros(C, jnp.float32))
            ra_var = self.variable("batch_stats", "var",
                                   lambda: jnp.ones(C, jnp.float32))
            if self.use_running_average:
                a = scale * jax.lax.rsqrt(ra_var.value + self.epsilon)
                b = bias - ra_mean.value * a
                y = x.astype(jnp.float32) * a + b
                if self.fuse_relu:
                    y = jnp.maximum(y, 0.0)
                return y.astype(self.dtype or x.dtype)
            groups = 1
            if self.virtual_batch_size:
                N = x.shape[0]
                if N % self.virtual_batch_size:
                    raise ValueError(
                        "virtual_batch_size=%d does not divide the "
                        "batch %d" % (self.virtual_batch_size, N))
                groups = N // self.virtual_batch_size
            sync_name = self.sync_name or \
                "lean_bn/%s" % "/".join(self.scope.path)
            # x keeps its native shape through the op: a collapsed
            # (M, C) view through the custom-VJP boundary measured as
            # layout copies in the neighboring conv backward.
            y, mean, var = lean_batch_norm_train(
                x, scale, bias, self.epsilon,
                self.fuse_relu, groups, self.axis_name,
                self.sync_group, sync_name)
            if not self.is_initializing():
                m = self.momentum
                # Ghost groups contribute equally to the running stats
                # (mean-of-group-stats — the standard ghost-BN running
                # estimate).
                mean_u = mean if groups == 1 else mean.mean(axis=0)
                var_u = var if groups == 1 else var.mean(axis=0)
                ra_mean.value = m * ra_mean.value + (1 - m) * mean_u
                ra_var.value = m * ra_var.value + (1 - m) * var_u
            y = checkpoint_name(y, "hvd_bn_norm")
            return y.astype(self.dtype or x.dtype)
except ImportError:  # pragma: no cover - flax is baked into this env
    PallasBatchNorm = None
    LeanBatchNorm = None
