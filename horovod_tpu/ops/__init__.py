"""TPU kernels (Pallas) for hot ops the XLA fuser doesn't already own.

The reference's analogue layer is its CUDA machinery
(`horovod/common/ops/cuda_operations.cc`) — hand-written device code where
the framework needs more than the stock library gives. Here that role is
played by Pallas TPU kernels:

* :mod:`.flash_attention` — blockwise attention with online softmax in
  VMEM (O(L) memory), causal block skipping, custom VJP.
"""

from horovod_tpu.compat import ensure_jax_compat as _ensure_jax_compat

_ensure_jax_compat()

from .flash_attention import flash_attention  # noqa: F401
