"""Adaptive gradient clipping (AGC) — the trainable norm-free route.

PERF.md's round-3/round-10 measurements put the norm-free ResNet variant
at 37.3% MFU vs 27.9% for BatchNorm — the measured-fastest conv config on
the chip — but without normalization, plain SGD diverges at practical
learning rates. AGC (Brock et al., "High-Performance Large-Scale Image
Recognition Without Normalization", arxiv 2102.06171) is what makes the
NF route *trainable*: each parameter's gradient is clipped so its
UNIT-WISE norm never exceeds ``clipping`` times the matching parameter
norm,

    g_i <- g_i * min(1, clipping * max(||w_i||, eps) / ||g_i||)

where a "unit" is one output row of the parameter (one conv filter, one
linear column) — the granularity the NF paper found necessary (a single
per-tensor ratio lets one dead filter throttle the whole layer).

Pure function (``agc_clip``), an optax-style transformation
(``adaptive_grad_clip``) and the framework wiring
(``DistributedOptimizer(agc=...)`` in the jax and torch bindings,
``make_train_step(agc=...)``) all share these unit-norm rules:

* 1-D and scalars (biases, gains): whole-tensor norm;
* 2-D (in, out) linear kernels: norm over the input axis, per column;
* 3/4/5-D conv kernels ((spatial..., in, out) — NHWC/HWIO layouts):
  norm over all but the last (output-channel) axis.

Clipping runs AFTER the gradient allreduce (clip the true global
gradient, not each rank's shard — per-rank clipping would make ranks
disagree on the update) and composes with wire compression and process
groups untouched. It does NOT compose with the sharded weight update:
1/N flat shards destroy the unit structure, and the wrappers reject the
combination loudly.
"""

import jax
import jax.numpy as jnp


def unitwise_norm(x):
    """Per-unit L2 norms of a parameter or gradient, shaped to broadcast
    against ``x`` (output-channel units; whole-tensor for <=1-D)."""
    x = jnp.asarray(x)
    if x.ndim <= 1:
        return jnp.sqrt(jnp.sum(x.astype(jnp.float32) ** 2))
    axes = tuple(range(x.ndim - 1))
    return jnp.sqrt(jnp.sum(x.astype(jnp.float32) ** 2, axis=axes,
                            keepdims=True))


def _clip_one(g, p, clipping, eps):
    g_norm = unitwise_norm(g)
    p_norm = unitwise_norm(p)
    max_norm = clipping * jnp.maximum(p_norm, eps)
    # Where g_norm == 0 the ratio is irrelevant (g is 0); guard the
    # division so the where's taken branch is always finite.
    scale = max_norm / jnp.maximum(g_norm, 1e-16)
    clipped = g * scale.astype(g.dtype)
    return jnp.where(g_norm > max_norm, clipped, g)


def agc_clip(grads, params, clipping=0.01, eps=1e-3):
    """Clips a gradient pytree against the matching parameter pytree
    (NF-paper defaults: clipping=0.01, eps=1e-3). Leaf-wise; shapes
    must match pairwise."""
    return jax.tree_util.tree_map(
        lambda g, p: _clip_one(g, p, clipping, eps), grads, params)


def adaptive_grad_clip(clipping=0.01, eps=1e-3):
    """AGC as an optax ``GradientTransformation`` (requires params):
    chain it before the optimizer —
    ``optax.chain(adaptive_grad_clip(0.01), optax.sgd(...))`` — or let
    ``hvd.jax.DistributedOptimizer(agc=0.01)`` place it after the
    gradient allreduce."""
    import optax

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError(
                "adaptive_grad_clip needs params: the clip threshold is "
                "relative to each parameter's unit-wise norm — call "
                "update(grads, state, params)")
        return agc_clip(updates, params, clipping, eps), state

    return optax.GradientTransformation(init_fn, update_fn)
