"""Python half of the live metrics plane (docs/METRICS.md).

The native core (``native/metrics.h``) keeps the registry — counters,
gauges, fixed-bucket histograms, piggybacked per-rank summaries — and
exposes JSON snapshots through the C API. This module turns those into:

* ``hvd.metrics()`` / ``hvd.job_metrics()`` dicts,
* Prometheus text rendering (``render_prometheus``),
* a per-worker HTTP endpoint (``HVD_TPU_METRICS_PORT`` + rank) serving
  ``/metrics`` (Prometheus) and, on rank 0, ``/job`` (the aggregated
  job view ``bin/hvd-top`` polls),
* min/max/mean aggregation across ranks (``aggregate``).

The HTTP server is a plain stdlib thread: ctypes calls into the core
release the GIL, so the endpoint keeps answering even while the main
thread is blocked inside a hung collective — which is exactly when a
live job view matters.
"""

import json
import os
import threading

_PREFIX = "hvdtpu_"


def _basics():
    from .common.basics import get_basics
    return get_basics()


def metrics():
    """This worker's live metrics registry as a dict:
    ``{"counters": {...}, "gauges": {...}, "histograms": {name:
    {"bounds", "counts", "sum", "count"}}, "rank_lag_seconds": [...]}``.
    Counters are monotonic for the life of the process; callable before
    init and after shutdown (zeros / last values)."""
    return json.loads(_basics().metrics_json())


def job_metrics():
    """Rank 0's job-wide view: ``{"size", "generation", "per_rank":
    {rank: summary}, "age_seconds": {rank: s}, "rank_lag_seconds":
    [...]}``; ``{}`` on non-coordinator ranks."""
    return json.loads(_basics().job_metrics_json())


def aggregate(per_rank):
    """min/max/mean (+ argmax rank) per summary field across the
    ``per_rank`` dict of a job view — straggler identification for
    free: the rank arg-maxing a latency/lag field is the one the job
    waits on."""
    out = {}
    if not per_rank:
        return out
    fields = set()
    for vals in per_rank.values():
        fields.update(vals)
    for f in sorted(fields):
        rows = [(float(vals.get(f, 0.0)), r)
                for r, vals in per_rank.items()]
        values = [v for v, _ in rows]
        vmax, argmax = max(rows)
        out[f] = {"min": min(values), "max": vmax,
                  "mean": sum(values) / len(values),
                  "argmax_rank": int(argmax)}
    return out


def _fmt(v):
    """Prometheus float formatting: integers stay integral."""
    f = float(v)
    return "%d" % f if f == int(f) and abs(f) < 1e15 else repr(f)


def render_prometheus(snapshot, labels=None):
    """Renders a ``metrics()`` snapshot as Prometheus text exposition
    (one ``hvdtpu_``-prefixed family per counter/gauge; histograms as
    cumulative ``_bucket{le=...}`` + ``_sum``/``_count``). ``labels``
    is an optional dict rendered into every sample (e.g. rank)."""
    label_str = ""
    if labels:
        label_str = ",".join('%s="%s"' % (k, labels[k])
                             for k in sorted(labels))
    lines = []

    def sample(name, value, extra=""):
        inner = ",".join(x for x in (label_str, extra) if x)
        label_part = "{%s}" % inner if inner else ""
        lines.append("%s%s %s" % (_PREFIX + name, label_part, _fmt(value)))

    for name, value in sorted(snapshot.get("counters", {}).items()):
        lines.append("# TYPE %s%s counter" % (_PREFIX, name))
        sample(name, value)
    for name, value in sorted(snapshot.get("gauges", {}).items()):
        lines.append("# TYPE %s%s gauge" % (_PREFIX, name))
        sample(name, value)
    for name, h in sorted(snapshot.get("histograms", {}).items()):
        lines.append("# TYPE %s%s histogram" % (_PREFIX, name))
        cumulative = 0
        for bound, count in zip(h["bounds"], h["counts"]):
            cumulative += count
            sample(name + "_bucket", cumulative, 'le="%s"' % _fmt(bound))
        cumulative += h["counts"][len(h["bounds"])]
        sample(name + "_bucket", cumulative, 'le="+Inf"')
        sample(name + "_sum", h["sum"])
        sample(name + "_count", h["count"])
    # Coordinator-side group-labeled negotiation counters
    # (docs/GROUPS.md): one series per process group id.
    per_group = snapshot.get("per_group") or {}
    if per_group:
        lines.append("# TYPE %sgroup_negotiated_total counter" % _PREFIX)
        for gid in sorted(per_group, key=int):
            inner = 'group="%s"' % gid
            if label_str:
                inner = label_str + "," + inner
            lines.append("%sgroup_negotiated_total{%s} %s" % (
                _PREFIX, inner,
                _fmt(per_group[gid].get("negotiated_total", 0))))
    # Coordinator-only per-rank announce lag (straggler table). The rank
    # label here names the ATTRIBUTED rank, not the serving worker, so
    # the base labels are deliberately not applied.
    lag = snapshot.get("rank_lag_seconds") or []
    if any(lag):
        lines.append("# TYPE %srank_announce_lag_seconds_total counter"
                     % _PREFIX)
        for r, v in enumerate(lag):
            lines.append('%srank_announce_lag_seconds_total{rank="%d"} %s'
                         % (_PREFIX, r, _fmt(v)))
    return "\n".join(lines) + "\n"


def render_job_prometheus(job):
    """Per-rank worker-summary series from a job view, Prometheus text
    (``hvdtpu_worker_<field>{rank=...}``) — appended to rank 0's
    ``/metrics`` so one scrape target carries the whole job."""
    lines = []
    per_rank = job.get("per_rank") or {}
    fields = set()
    for vals in per_rank.values():
        fields.update(vals)
    for f in sorted(fields):
        lines.append("# TYPE %sworker_%s gauge" % (_PREFIX, f))
        for r in sorted(per_rank, key=int):
            lines.append('%sworker_%s{rank="%s"} %s' % (
                _PREFIX, f, r, _fmt(per_rank[r].get(f, 0.0))))
    return ("\n".join(lines) + "\n") if lines else ""


def _make_handler():
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            path = self.path.split("?")[0].rstrip("/") or "/"
            try:
                if path in ("/", "/metrics"):
                    snap = metrics()
                    rank = int(snap.get("gauges", {}).get("rank", -1))
                    body = render_prometheus(
                        snap, labels={"rank": rank} if rank >= 0 else None)
                    job = job_metrics()
                    if job:
                        body += render_job_prometheus(job)
                    self._reply(200, body,
                                "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/json":
                    self._reply(200, json.dumps(metrics()),
                                "application/json")
                elif path == "/job":
                    job = job_metrics()
                    if job:
                        job["aggregate"] = aggregate(job.get("per_rank", {}))
                    self._reply(200, json.dumps(job), "application/json")
                else:
                    self._reply(404, "not found\n", "text/plain")
            except Exception as e:  # scrape must never kill the worker
                self._reply(500, "error: %s\n" % e, "text/plain")

        def _reply(self, code, body, ctype):
            data = body.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, fmt, *args):
            pass  # scrapes must not spam worker stderr

    return Handler


_server = None
_server_port = None
_server_lock = threading.Lock()


def start_server(port):
    """Starts (or moves) the metrics HTTP endpoint on `port`."""
    global _server, _server_port
    from http.server import ThreadingHTTPServer

    with _server_lock:
        if _server is not None and _server_port == port:
            return _server_port
        _stop_locked()
        httpd = ThreadingHTTPServer(("0.0.0.0", port), _make_handler())
        httpd.daemon_threads = True
        thread = threading.Thread(target=httpd.serve_forever,
                                  name="hvd-metrics-http", daemon=True)
        thread.start()
        _server, _server_port = httpd, port
        return port


def _stop_locked():
    global _server, _server_port
    if _server is not None:
        _server.shutdown()
        _server.server_close()
        _server = None
        _server_port = None


def stop_server():
    with _server_lock:
        _stop_locked()


def server_port():
    return _server_port


def on_init():
    """Called after every successful hvd.init() (including elastic
    re-inits, where this worker's rank — and therefore its port slot —
    may have changed). Serves at HVD_TPU_METRICS_PORT + rank; no env,
    no server."""
    base = os.environ.get("HVD_TPU_METRICS_PORT")
    if not base:
        return
    try:
        base_port = int(base)
    except ValueError:
        return
    if base_port <= 0:
        return
    from . import rank
    try:
        start_server(base_port + rank())
    except OSError as e:
        # An observability endpoint must never kill the training job: a
        # stale worker or unrelated process squatting on the port slot
        # costs the scrape, not the run.
        import sys
        sys.stderr.write(
            "[hvd-metrics] could not bind metrics port %d (%s); "
            "continuing WITHOUT the HTTP endpoint\n"
            % (base_port + rank(), e))
