"""Pluggable gradient compression (docs/COMPRESSION.md).

The modes here are *wire* codecs: the tensor (and the native core's
fusion buffer) stays float32 end to end — only the bytes each transport
hop moves are re-encoded. Selectable per optimizer / per collective
(``hvd.DistributedOptimizer(compression="int8")``,
``hvd.allreduce(x, compression="bf16")``) and job-wide via
``HVD_TPU_COMPRESSION``; the mode rides the negotiation protocol, so
mixed-mode ranks are rejected by name and a mode change is a response-
cache miss.

* ``none`` — bitwise-identical behavior to an uncompressed build.
* ``bf16`` — each f32 element rides the wire as round-to-nearest
  bfloat16: 2x fewer bytes per hop. Reduction still accumulates in f32
  on both data planes, so the loss is one rounding per hop — but the
  summation is no longer bit-identical to the uncompressed sum (exact
  sum-order caveats in docs/COMPRESSION.md).
* ``int8`` — EQuARX-style block-scaled quantization (PAPERS.md, arxiv
  2506.17615): per :data:`BLOCK`-element block an f32 scale
  (``max|x| / 127``) is carried in-band ahead of the int8 payload,
  ~3.9x fewer bytes per hop, per-element error bounded by ``scale/2``.

Numpy reference quantizers live here (the native codec in
``native/compression.cc`` implements the same layout; tests pin them
against each other) plus the jax block quantizers the in-jit ring
allreduce (:func:`horovod_tpu.parallel.ring.ring_allreduce`) fuses into
its per-hop compute.

Integer and embedding-lookup tensors must NOT be compressed — lossy
quantization silently corrupts them; ``hvd-lint`` flags it statically
(rule ``compression-on-integer-tensor``) and the core degrades non-f32
payloads to ``none`` at enqueue so the wire can never desync.
"""

import os

import numpy as np

# Mode ids — must match native/compression.h CompressionMode.
NONE = 0
BF16 = 1
INT8 = 2

# Elements per int8 quantization block (one in-band f32 scale each);
# must match native/compression.h kCompressionBlock.
BLOCK = 256

ENV_VAR = "HVD_TPU_COMPRESSION"


class Mode(object):
    """One wire-compression mode (hashable, comparable by id)."""

    __slots__ = ("mode", "name")

    def __init__(self, mode, name):
        self.mode = mode
        self.name = name

    def __repr__(self):
        return "Compression.%s" % self.name

    def __eq__(self, other):
        if isinstance(other, Mode):
            return self.mode == other.mode
        if isinstance(other, str):
            return self.name == other
        if isinstance(other, int):
            return self.mode == other
        return NotImplemented

    def __hash__(self):
        return hash(self.mode)


class Compression(object):
    """The selectable modes, as attributes (``Compression.int8``) —
    strings ("int8") and ints (2) resolve to the same objects."""

    none = Mode(NONE, "none")
    bf16 = Mode(BF16, "bf16")
    int8 = Mode(INT8, "int8")


_BY_KEY = {
    None: Compression.none,
    "": Compression.none,
    "none": Compression.none, "0": Compression.none, NONE: Compression.none,
    "bf16": Compression.bf16, "1": Compression.bf16, BF16: Compression.bf16,
    "int8": Compression.int8, "2": Compression.int8, INT8: Compression.int8,
}


def default_mode():
    """The job-wide mode from ``HVD_TPU_COMPRESSION`` (none when unset
    or unparseable — an env typo must not silently quantize)."""
    v = os.environ.get(ENV_VAR, "").strip().lower()
    return _BY_KEY.get(v, Compression.none)


def resolve(spec):
    """Maps a user-facing ``compression=`` value to a :class:`Mode`.

    ``None`` defers to the env default; strings/ints/Modes map directly.
    Legacy codec classes (objects with a ``compress`` attribute, e.g.
    ``hvd.jax.Compression.fp16``) are NOT accepted here — the framework
    bindings intercept those before the wire layer.
    """
    if isinstance(spec, Mode):
        return spec
    if spec is None:
        return default_mode()
    if hasattr(spec, "compress"):
        raise TypeError(
            "legacy codec objects (%r) belong to the framework binding "
            "layer; pass 'none'/'bf16'/'int8' (or Compression.<mode>) "
            "for wire compression" % (spec,))
    key = spec.lower().strip() if isinstance(spec, str) else spec
    try:
        return _BY_KEY[key]
    except (KeyError, TypeError):
        raise ValueError(
            "unknown compression mode %r (expected 'none', 'bf16' or "
            "'int8')" % (spec,))


def resolve_wire_arg(compression, none_codec=None):
    """Maps a ``DistributedOptimizer(compression=...)`` argument to a
    wire :class:`Mode` under sharded mode, shared by all three framework
    wrappers so the accepted set cannot drift between them: legacy
    tensor codecs are rejected (they would change the dtype the
    shard-local optimizer sees), EXCEPT the binding's no-op ``none``
    codec (``none_codec``), which — being the wrappers' DEFAULT
    argument — defers to the job-wide ``HVD_TPU_COMPRESSION`` default
    exactly like passing nothing (to force uncompressed wire under an
    env default, pass ``compression='none'`` explicitly,
    docs/ZERO.md)."""
    if compression is not None and hasattr(compression, "compress"):
        if none_codec is None or compression is not none_codec:
            raise ValueError(
                "sharded_update takes wire compression modes "
                "('none'/'bf16'/'int8'), not legacy codec objects")
        compression = None
    return resolve(compression)


def wire_bytes(count, mode):
    """Wire bytes `count` f32 elements occupy under `mode` — the same
    pure function of (count, mode) both ring endpoints size buffers
    with (native/compression.cc CompressedSize)."""
    mode = resolve(mode)
    if mode.mode == BF16:
        return 2 * count
    if mode.mode == INT8:
        nblocks = (count + BLOCK - 1) // BLOCK
        return 4 * nblocks + count
    return 4 * count


# --- numpy reference quantizers (tests pin the native codec to these) ---


def quantize_int8(x, block=BLOCK):
    """Block-scaled int8 quantization of a float array.

    Returns ``(q, scales)``: ``q`` int8 with ``x.size`` elements,
    ``scales`` f32 with one ``max|block| / 127`` entry per block (the
    last block may be short). Symmetric range [-127, 127] — -128 is
    never produced — so ``|x - dequantize| <= scales[b] / 2`` holds per
    element, the bound the round-trip tests assert.

    Nonfinite inputs (an overflowed gradient step) make the block's
    in-band SCALE NaN, so the block decodes nonfinite — isfinite /
    loss-scale skip-step guards downstream of the allreduce still fire
    (matching the native codec and bf16's NaN preservation).
    """
    flat = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    n = flat.size
    nblocks = (n + block - 1) // block
    padded = np.zeros(nblocks * block, np.float32)
    padded[:n] = flat
    blocks = padded.reshape(nblocks, block)
    with np.errstate(invalid="ignore", over="ignore"):
        amax = np.max(np.abs(blocks), axis=1)  # NaN-propagating max
        scales = np.where(np.isfinite(amax),
                          np.where(amax > 0, amax / 127.0, 0.0),
                          np.float32(np.nan)).astype(np.float32)
        finite_scale = np.where(np.isfinite(scales) & (scales > 0),
                                scales, 1.0)
        inv = np.where(np.isfinite(scales) & (scales > 0),
                       1.0 / finite_scale, 0.0)
        q = np.clip(np.rint(np.nan_to_num(blocks * inv[:, None])),
                    -127, 127).astype(np.int8)
    return q.reshape(-1)[:n], scales


def dequantize_int8(q, scales, block=BLOCK):
    """Inverse of :func:`quantize_int8` (up to the codec's rounding)."""
    flat = np.ascontiguousarray(q, dtype=np.int8).reshape(-1)
    n = flat.size
    nblocks = (n + block - 1) // block
    padded = np.zeros(nblocks * block, np.int8)
    padded[:n] = flat
    out = padded.reshape(nblocks, block).astype(np.float32) * \
        np.asarray(scales, np.float32)[:, None]
    return out.reshape(-1)[:n]


def bf16_roundtrip(x):
    """f32 -> bfloat16 (round-to-nearest-even) -> f32, in numpy bit
    arithmetic — what one bf16 wire hop does to a value. NaNs quiet to
    a canonical NaN instead of rounding (the RNE increment would carry
    an all-ones-mantissa NaN out into a FINITE value), matching the
    native codec (half.h FloatToBFloat16)."""
    bits = np.ascontiguousarray(x, dtype=np.float32).view(np.uint32)
    is_nan = (bits & np.uint32(0x7FFFFFFF)) > np.uint32(0x7F800000)
    lsb = (bits >> 16) & 1
    with np.errstate(over="ignore"):
        rounded = (bits + 0x7FFF + lsb) & np.uint32(0xFFFF0000)
    quiet_nan = ((bits >> 16) | np.uint32(0x40)).astype(np.uint32) << 16
    return np.where(is_nan, quiet_nan, rounded).astype(
        np.uint32).view(np.float32)


# --- jax block quantizers (fused into the ring's per-hop compute) ---


def quantize_int8_jax(x, block=BLOCK):
    """jax version of :func:`quantize_int8` for a 1-D f32 array whose
    length is a multiple of `block` (the ring pads its chunks).
    Returns ``(q int8 [nblocks, block], scales f32 [nblocks])``.
    Nonfinite blocks get a NaN scale (see :func:`quantize_int8`)."""
    import jax.numpy as jnp

    xb = x.reshape(-1, block)
    amax = jnp.max(jnp.abs(xb), axis=1)  # NaN-propagating max
    ok = jnp.isfinite(amax)
    scales = jnp.where(ok, jnp.where(amax > 0, amax / 127.0, 0.0),
                       jnp.nan)
    pos = ok & (scales > 0)
    inv = jnp.where(pos, 1.0 / jnp.where(pos, scales, 1.0), 0.0)
    q = jnp.clip(jnp.round(jnp.nan_to_num(xb * inv[:, None])), -127,
                 127).astype(jnp.int8)
    return q, scales.astype(jnp.float32)


def dequantize_int8_jax(q, scales):
    """Inverse of :func:`quantize_int8_jax`; returns 1-D f32."""
    import jax.numpy as jnp

    return (q.astype(jnp.float32) * scales[:, None]).reshape(-1)
