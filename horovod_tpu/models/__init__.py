"""Model zoo backing the reference's benchmark/example configs
(`BASELINE.json` `configs`; reference examples/ directory):

* :mod:`.resnet`   — ResNet-18/34/50/101/152 (flax), the flagship
  benchmark model (reference `examples/tensorflow2_synthetic_benchmark.py`,
  `examples/pytorch_imagenet_resnet50.py`).
* :mod:`.mnist`    — 2-layer CNN (reference `examples/tensorflow2_mnist.py`).
* :mod:`.word2vec` — skip-gram with negative sampling; sparse embedding
  gradients exercise the allgather path (reference
  `examples/tensorflow_word2vec.py`).
* :mod:`.transformer` — decoder-only transformer with optional ring
  attention for long-context sequence parallelism (TPU-first extension).
* :mod:`.imagenet_extras` — VGG-16 and Inception V3, the other models in
  the reference's published 512-GPU scaling table
  (`docs/benchmarks.rst:13-14`).

All models are written TPU-first: NHWC conv layouts, bfloat16 compute with
float32 parameters, static shapes, no data-dependent Python control flow.
"""

from .resnet import (ResNet, ResNet18, ResNet34, ResNet50, ResNet50GN,  # noqa: F401
                     ResNet50Lean, ResNet50NF, ResNet50PBN, ResNet101,
                     ResNet101NF, ResNet152)
from .mnist import MnistCNN  # noqa: F401
from .word2vec import SkipGram  # noqa: F401
from .transformer import Transformer, TransformerConfig  # noqa: F401
from .imagenet_extras import VGG16, InceptionV3  # noqa: F401
