"""Decoder-only transformer with pluggable long-context attention.

The reference has no model layer (it only moves gradients); this model
exists to exercise the TPU-first sequence-parallel path
(`horovod_tpu.parallel.ring`) end-to-end: with ``attention="ring"`` or
``"ulysses"`` the module must run inside ``shard_map`` with the sequence
dimension sharded over ``sp_axis`` — each device holds [B, L/n, ...] and
attention is exact over the full sequence.

TPU-first: bf16 compute / f32 params, static shapes, pre-norm blocks,
rotary position embeddings computed from *global* positions so sequence
shards agree.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel.ring import ring_attention, ulysses_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    num_layers: int = 12
    num_heads: int = 12
    embed_dim: int = 768
    mlp_dim: int = 3072
    max_seq_len: int = 8192
    attention: str = "dense"      # dense | flash | ring | ulysses
    # GQA/MQA: number of kv heads (None = num_heads, plain MHA). Must
    # divide num_heads; query head h reads kv head h // (H//G) — the
    # llama convention. Shrinks the k/v projections and lets the flash
    # kernels run the grouped-rows layout (one kv fetch per head
    # group, in-kernel dK/dV group reduction).
    num_kv_heads: Optional[int] = None
    # Fuse rotary embedding into the flash/ring/ulysses kernels' q/k
    # load path (positions derived in-kernel from global offsets —
    # the explicit `positions` input is then unused by attention, so
    # it only works for the standard layouts those offsets describe).
    # The dense path always rotates outside.
    rope_fused: bool = False
    rope_base: float = 10000.0
    sp_axis: Optional[str] = None  # mesh axis holding the sequence shards
    # Ring schedule: "zigzag" is the causal load-balanced layout
    # (parallel.ring.zigzag_shard the tokens/positions/labels; the
    # explicit global `positions` input makes rotary correct for any
    # layout). Only meaningful with attention="ring".
    sp_schedule: str = "contiguous"
    # Megatron-style tensor parallelism: when set, the module runs
    # inside shard_map with attention heads and the MLP hidden dim
    # sharded over this axis (num_heads/mlp_dim are the LOCAL sizes —
    # build with `cfg.local(tp_size)`, place full params with
    # parallel.tensor_parallel.tp_param_specs), and the attention-out
    # / mlp-out projections psum their partial products across it.
    tp_axis: Optional[str] = None
    # Per-head width; defaults to embed_dim // num_heads. Set
    # explicitly when num_heads is a LOCAL (tp-sharded) count.
    head_dim: Optional[int] = None
    # Switch-style mixture-of-experts: when moe_experts is set, every
    # `moe_every`-th block swaps its dense MLP for a MoeMlp
    # (parallel/expert.py); ep_axis/ep_size shard the expert dim inside
    # shard_map (tokens should then shard over (dp, ep)). Initialize
    # with ep_axis=None/ep_size=1 (full shapes), apply with the
    # ep-sized config — the tp `local()` pattern.
    moe_experts: Optional[int] = None
    moe_every: int = 2
    moe_capacity_factor: float = 1.25
    moe_top_k: int = 1            # 1 = Switch; 2 = GShard-style
    ep_axis: Optional[str] = None
    ep_size: int = 1
    dtype: Any = jnp.bfloat16

    def __post_init__(self):
        if self.moe_experts is not None and self.tp_axis is not None:
            # The MoE branch neither psums like the dense row-parallel
            # mlp_out nor shards experts by tp — combining them would
            # silently diverge activations across tp shards.
            raise ValueError("moe_experts cannot be combined with "
                             "tp_axis (MoE blocks are ep-parallel, "
                             "not tensor-parallel)")

    def local(self, tp_size):
        """The per-shard config for `tp_size`-way tensor parallelism."""
        if self.num_heads % tp_size or self.mlp_dim % tp_size:
            raise ValueError(
                "tp_size=%d must divide both num_heads=%d and "
                "mlp_dim=%d" % (tp_size, self.num_heads, self.mlp_dim))
        kv = self.num_kv_heads
        if kv is not None:
            if kv % tp_size:
                raise ValueError(
                    "tp_size=%d must divide num_kv_heads=%d (tensor "
                    "parallelism shards the kv heads too)"
                    % (tp_size, kv))
            kv = kv // tp_size
        return dataclasses.replace(
            self, num_heads=self.num_heads // tp_size,
            num_kv_heads=kv,
            mlp_dim=self.mlp_dim // tp_size,
            head_dim=self.head_dim or self.embed_dim // self.num_heads)


def _rotary(x, positions, base=10000.0):
    """Rotary embedding over the last dim; positions [B, L] global."""
    d = x.shape[-1]
    half = d // 2
    freq = 1.0 / (base ** (jnp.arange(half, dtype=jnp.float32) / half))
    ang = positions[..., None].astype(jnp.float32) * freq  # [B, L, half]
    ang = ang[:, :, None, :]                               # [B, L, 1, half]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos],
        axis=-1).astype(x.dtype)


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        head_dim = cfg.head_dim or cfg.embed_dim // cfg.num_heads
        G = cfg.num_kv_heads or cfg.num_heads
        if cfg.num_heads % G:
            raise ValueError(
                "num_kv_heads=%d must divide num_heads=%d"
                % (G, cfg.num_heads))
        heads = lambda n, name: nn.DenseGeneral(  # noqa: E731
            (n, head_dim), dtype=cfg.dtype,
            param_dtype=jnp.float32, use_bias=False, name=name)
        q = heads(cfg.num_heads, "query")(x)
        k = heads(G, "key")(x)
        v = heads(G, "value")(x)
        fused = (cfg.rope_fused and
                 cfg.attention in ("flash", "ring", "ulysses"))
        if not fused:
            q = _rotary(q, positions, cfg.rope_base)
            k = _rotary(k, positions, cfg.rope_base)
        rb = cfg.rope_base if fused else None
        if cfg.attention == "ring":
            o = ring_attention(q, k, v, cfg.sp_axis, causal=True,
                               schedule=cfg.sp_schedule, rotary_base=rb)
        elif cfg.attention == "ulysses":
            o = ulysses_attention(q, k, v, cfg.sp_axis, causal=True,
                                  rotary_base=rb)
        elif cfg.attention == "flash":
            from horovod_tpu.ops import flash_attention
            o = flash_attention(q, k, v, causal=True, rotary_base=rb)
        else:
            if G != cfg.num_heads:
                k = jnp.repeat(k, cfg.num_heads // G, axis=2)
                v = jnp.repeat(v, cfg.num_heads // G, axis=2)
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                           preferred_element_type=jnp.float32)
            s = s * (head_dim ** -0.5)
            L = s.shape[-1]
            mask = lax.broadcasted_iota(jnp.int32, (L, L), 0) >= \
                lax.broadcasted_iota(jnp.int32, (L, L), 1)
            s = jnp.where(mask[None, None], s, -jnp.inf)
            p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
            o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        out = nn.DenseGeneral(cfg.embed_dim, axis=(-2, -1), dtype=cfg.dtype,
                              param_dtype=jnp.float32, use_bias=False,
                              name="out")(o)
        if cfg.tp_axis is not None:
            # Each tp shard projected only its local heads: the row-
            # parallel output is a partial sum (Megatron-style).
            out = lax.psum(out, cfg.tp_axis)
        return out


class Block(nn.Module):
    cfg: TransformerConfig
    moe: bool = False

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        norm = lambda name: nn.RMSNorm(  # noqa: E731
            dtype=cfg.dtype, param_dtype=jnp.float32, name=name)
        # Under rope_fused=True with a kernel attention (flash/ring/
        # ulysses), `positions` is IGNORED: the kernels apply rotary
        # in-kernel from global row offsets, which assumes the standard
        # contiguous 0..L-1 layout. Custom position ids (packing, shifted
        # windows) require rope_fused=False.
        x = x + Attention(cfg, name="attn")(norm("norm1")(x), positions)
        h = norm("norm2")(x)
        if self.moe:
            from horovod_tpu.parallel.expert import MoeMlp
            h = MoeMlp(num_experts=cfg.moe_experts, mlp_dim=cfg.mlp_dim,
                       capacity_factor=cfg.moe_capacity_factor,
                       ep_axis=cfg.ep_axis, ep_size=cfg.ep_size,
                       top_k=cfg.moe_top_k, dtype=cfg.dtype,
                       name="moe_mlp")(h)
            return x + h
        h = nn.Dense(cfg.mlp_dim, dtype=cfg.dtype, param_dtype=jnp.float32,
                     use_bias=False, name="mlp_in")(h)
        h = nn.silu(h)
        h = nn.Dense(cfg.embed_dim, dtype=cfg.dtype, param_dtype=jnp.float32,
                     use_bias=False, name="mlp_out")(h)
        if cfg.tp_axis is not None:
            # Column-parallel mlp_in -> row-parallel mlp_out: the out
            # product over the local hidden slice is a partial sum.
            h = lax.psum(h, cfg.tp_axis)
        return x + h


class Transformer(nn.Module):
    """tokens [B, L_local] (+ global positions when sequence-sharded) ->
    logits [B, L_local, vocab].

    ``return_hidden=True`` skips the lm_head projection and returns the
    final normed hidden states — pair with
    `horovod_tpu.ops.losses.chunked_softmax_cross_entropy` (and the
    lm_head kernel from the params tree) to train without ever
    materializing the [B, L, vocab] f32 logits."""
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, positions=None, return_hidden=False):
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1], dtype=jnp.int32)[None],
                tokens.shape)
        x = nn.Embed(cfg.vocab_size, cfg.embed_dim, param_dtype=jnp.float32,
                     dtype=cfg.dtype, name="embed")(tokens)
        for i in range(cfg.num_layers):
            moe = (cfg.moe_experts is not None and
                   i % cfg.moe_every == cfg.moe_every - 1)
            x = Block(cfg, moe=moe, name="block_%d" % i)(x, positions)
        x = nn.RMSNorm(dtype=cfg.dtype, param_dtype=jnp.float32,
                       name="norm_f")(x)
        if return_hidden:
            return x
        logits = nn.Dense(cfg.vocab_size, dtype=cfg.dtype,
                          param_dtype=jnp.float32, use_bias=False,
                          name="lm_head")(x)
        return logits.astype(jnp.float32)
