"""VGG-16 and Inception V3 in flax — the reference's other headline
benchmark models (its published 512-GPU scaling table is Inception V3 /
ResNet-101 / VGG-16, `docs/benchmarks.rst:13-14`, README.rst:75).

Same TPU-first conventions as `resnet.py`: NHWC, bf16 compute with f32
params/statistics, static shapes.
"""

from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp


class VGG16(nn.Module):
    """VGG-16 (configuration D): 13 conv + 3 FC layers."""
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, kernel_size=(3, 3), dtype=self.dtype,
                       param_dtype=jnp.float32)
        x = x.astype(self.dtype)
        for i, (filters, reps) in enumerate(
                [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]):
            for j in range(reps):
                x = nn.relu(conv(filters, name="conv%d_%d" % (i, j))(x))
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        for j, width in enumerate([4096, 4096]):
            x = nn.relu(nn.Dense(width, dtype=self.dtype,
                                 param_dtype=jnp.float32,
                                 name="fc%d" % j)(x))
            x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


class _ConvBN(nn.Module):
    """Conv + BatchNorm + ReLU, the Inception building block.
    `norm="pallas"` swaps in the fused-stats PallasBatchNorm
    (ops/batch_norm.py) — Inception is the zoo's most BN-bound model,
    so it is the second measurement target for that kernel."""
    filters: int
    kernel: tuple
    strides: tuple = (1, 1)
    padding: Any = "SAME"
    dtype: Any = jnp.bfloat16
    norm: str = "batch"
    bn_axis_name: Optional[str] = None  # sync BN: psum stats over this mesh axis

    @nn.compact
    def __call__(self, x, train):
        x = nn.Conv(self.filters, self.kernel, self.strides,
                    padding=self.padding, use_bias=False,
                    dtype=self.dtype, param_dtype=jnp.float32)(x)
        if self.norm == "pallas":
            from horovod_tpu.ops.batch_norm import PallasBatchNorm
            bn_cls = PallasBatchNorm
        else:
            bn_cls = nn.BatchNorm
        x = bn_cls(use_running_average=not train, momentum=0.9,
                   epsilon=1e-3, dtype=self.dtype,
                   param_dtype=jnp.float32,
                   axis_name=self.bn_axis_name)(x)
        return nn.relu(x)


def _avgpool3(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionV3(nn.Module):
    """Inception V3 (Szegedy et al. 2015), aux head omitted (the
    reference synthetic benchmarks train the main head only)."""
    norm: str = "batch"
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    bn_axis_name: Optional[str] = None  # sync BN over this mesh axis

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(_ConvBN, dtype=self.dtype, norm=self.norm,
                      bn_axis_name=self.bn_axis_name)
        x = x.astype(self.dtype)
        # Stem: 299x299x3 -> 35x35x192
        x = cbn(32, (3, 3), (2, 2), "VALID")(x, train)
        x = cbn(32, (3, 3), padding="VALID")(x, train)
        x = cbn(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = cbn(80, (1, 1), padding="VALID")(x, train)
        x = cbn(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))

        def inception_a(x, pool_features):
            b1 = cbn(64, (1, 1))(x, train)
            b5 = cbn(48, (1, 1))(x, train)
            b5 = cbn(64, (5, 5))(b5, train)
            b3 = cbn(64, (1, 1))(x, train)
            b3 = cbn(96, (3, 3))(b3, train)
            b3 = cbn(96, (3, 3))(b3, train)
            bp = cbn(pool_features, (1, 1))(_avgpool3(x), train)
            return jnp.concatenate([b1, b5, b3, bp], axis=-1)

        def inception_b(x):  # grid 35 -> 17
            b3 = cbn(384, (3, 3), (2, 2), "VALID")(x, train)
            bd = cbn(64, (1, 1))(x, train)
            bd = cbn(96, (3, 3))(bd, train)
            bd = cbn(96, (3, 3), (2, 2), "VALID")(bd, train)
            bp = nn.max_pool(x, (3, 3), strides=(2, 2))
            return jnp.concatenate([b3, bd, bp], axis=-1)

        def inception_c(x, c7):
            b1 = cbn(192, (1, 1))(x, train)
            b7 = cbn(c7, (1, 1))(x, train)
            b7 = cbn(c7, (1, 7))(b7, train)
            b7 = cbn(192, (7, 1))(b7, train)
            bd = cbn(c7, (1, 1))(x, train)
            bd = cbn(c7, (7, 1))(bd, train)
            bd = cbn(c7, (1, 7))(bd, train)
            bd = cbn(c7, (7, 1))(bd, train)
            bd = cbn(192, (1, 7))(bd, train)
            bp = cbn(192, (1, 1))(_avgpool3(x), train)
            return jnp.concatenate([b1, b7, bd, bp], axis=-1)

        def inception_d(x):  # grid 17 -> 8
            b3 = cbn(192, (1, 1))(x, train)
            b3 = cbn(320, (3, 3), (2, 2), "VALID")(b3, train)
            b7 = cbn(192, (1, 1))(x, train)
            b7 = cbn(192, (1, 7))(b7, train)
            b7 = cbn(192, (7, 1))(b7, train)
            b7 = cbn(192, (3, 3), (2, 2), "VALID")(b7, train)
            bp = nn.max_pool(x, (3, 3), strides=(2, 2))
            return jnp.concatenate([b3, b7, bp], axis=-1)

        def inception_e(x):
            b1 = cbn(320, (1, 1))(x, train)
            b3 = cbn(384, (1, 1))(x, train)
            b3 = jnp.concatenate([cbn(384, (1, 3))(b3, train),
                                  cbn(384, (3, 1))(b3, train)], axis=-1)
            bd = cbn(448, (1, 1))(x, train)
            bd = cbn(384, (3, 3))(bd, train)
            bd = jnp.concatenate([cbn(384, (1, 3))(bd, train),
                                  cbn(384, (3, 1))(bd, train)], axis=-1)
            bp = cbn(192, (1, 1))(_avgpool3(x), train)
            return jnp.concatenate([b1, b3, bd, bp], axis=-1)

        x = inception_a(x, 32)
        x = inception_a(x, 64)
        x = inception_a(x, 64)
        x = inception_b(x)
        for c7 in (128, 160, 160, 192):
            x = inception_c(x, c7)
        x = inception_d(x)
        x = inception_e(x)
        x = inception_e(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)
