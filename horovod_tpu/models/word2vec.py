"""Skip-gram word2vec with negative sampling.

Capability parity: reference `examples/tensorflow_word2vec.py` (the
BASELINE.json config that "exercises allgather + broadcast") — its
embedding gradients are IndexedSlices, which the reference allreduces via
the sparse allgather path (`horovod/tensorflow/__init__.py:65-76`).

TPU-first: embedding lookups are one-hot-free `jnp.take` gathers (static
shapes), NCE loss against `num_sampled` shared negative samples per batch.
Sparse gradients surface as rows of the dense embedding table; the jax
binding's `allreduce_sparse` gathers (indices, values) across ranks instead
of densifying — see `horovod_tpu/jax/sparse.py`.
"""

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class SkipGram(nn.Module):
    """Skip-gram embedding + NCE output layer."""
    vocab_size: int = 50000
    embedding_dim: int = 200
    dtype: Any = jnp.float32

    def setup(self):
        self.embedding = nn.Embed(self.vocab_size, self.embedding_dim,
                                  param_dtype=jnp.float32,
                                  embedding_init=nn.initializers.uniform(2.0))
        self.nce_weight = self.param(
            "nce_weight",
            nn.initializers.truncated_normal(1.0 / self.embedding_dim ** 0.5),
            (self.vocab_size, self.embedding_dim), jnp.float32)
        self.nce_bias = self.param("nce_bias", nn.initializers.zeros,
                                   (self.vocab_size,), jnp.float32)

    def __call__(self, center_ids):
        """Embeds a batch of center-word ids -> [batch, embedding_dim]."""
        return self.embedding(center_ids)

    def nce_loss(self, center_ids, context_ids, negative_ids):
        """Sampled-softmax/NCE loss.

        center_ids [B], context_ids [B] (positives), negative_ids [K]
        (shared negatives) — all int32, static shapes.
        """
        emb = self.embedding(center_ids)                        # [B, D]
        pos_w = jnp.take(self.nce_weight, context_ids, axis=0)  # [B, D]
        pos_b = jnp.take(self.nce_bias, context_ids, axis=0)    # [B]
        neg_w = jnp.take(self.nce_weight, negative_ids, axis=0)  # [K, D]
        neg_b = jnp.take(self.nce_bias, negative_ids, axis=0)    # [K]

        pos_logit = jnp.sum(emb * pos_w, axis=-1) + pos_b        # [B]
        neg_logit = emb @ neg_w.T + neg_b[None, :]               # [B, K]

        pos_loss = -jax.nn.log_sigmoid(pos_logit)
        neg_loss = -jnp.sum(jax.nn.log_sigmoid(-neg_logit), axis=-1)
        return jnp.mean(pos_loss + neg_loss)

    def nearest(self, word_ids, k=8):
        """Cosine-nearest neighbours for eval (reference word2vec eval loop)."""
        norm = self.embedding.embedding / (jnp.linalg.norm(
            self.embedding.embedding, axis=1, keepdims=True) + 1e-8)
        q = jnp.take(norm, word_ids, axis=0)
        sim = q @ norm.T
        return jax.lax.top_k(sim, k + 1)[1][:, 1:]
