"""2-layer MNIST CNN — capability parity with the reference's MNIST
examples (`examples/tensorflow2_mnist.py:21-33`: two conv layers, two dense
layers; the canonical single-process/CPU functional config in
BASELINE.json)."""

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class MnistCNN(nn.Module):
    """conv(32,3x3) -> conv(64,3x3) -> maxpool -> dense(128) -> dense(10)."""
    num_classes: int = 10
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype,
                    param_dtype=jnp.float32)(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3), dtype=self.dtype,
                    param_dtype=jnp.float32)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype, param_dtype=jnp.float32)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)
