"""ResNet v1.5 in flax — the flagship benchmark model.

Capability parity: the reference benchmarks ResNet-50 via
`examples/tensorflow2_synthetic_benchmark.py:24-37` (Keras applications
ResNet50) and `examples/pytorch_imagenet_resnet50.py`; its headline scaling
numbers are ResNet-101 (`docs/benchmarks.rst:13-14,43`).

TPU-first choices (not inherited from the reference):
* NHWC layout — the natural layout for TPU convolutions; XLA tiles the
  channel dim onto the 128-lane MXU minor dimension.
* bfloat16 compute / float32 params + batch-norm statistics: matmul/conv
  inputs are cast to bf16 (MXU native), accumulation and state stay f32.
* Static shapes everywhere; stride-2 projection shortcuts (v1.5: the 3x3
  conv carries the stride, matching the torchvision model the reference
  benchmarks).
"""

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic two-conv residual block (ResNet-18/34)."""
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)
    # Fused norm+activation factory (norm="lean"): the norm module
    # applies the ReLU itself so its backward recomputes the mask from
    # the pre-activation sign instead of storing it. None = norm then
    # act separately (every other norm path).
    norm_act: Optional[ModuleDef] = None

    def _norm_act(self, y):
        if self.norm_act is not None:
            return self.norm_act()(y)
        return self.act(self.norm()(y))

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self._norm_act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3(stride) -> 1x1 bottleneck (ResNet-50/101/152, v1.5)."""
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)
    norm_act: Optional[ModuleDef] = None  # see ResNetBlock

    def _norm_act(self, y):
        if self.norm_act is not None:
            return self.norm_act()(y)
        return self.act(self.norm()(y))

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self._norm_act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self._norm_act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ResNet v1.5 over NHWC inputs.

    `norm="group"` swaps BatchNorm for GroupNorm(32) — the PERF.md
    roofline experiment: BN's cross-batch statistics force f32
    convert+reduce passes over every activation (the measured HBM
    bottleneck), while GN's within-sample stats stay in the compute
    dtype with f32 reduce accumulation only.

    `norm="lean"` is the round-10 traffic-lean graph-level BN
    (ops/batch_norm.LeanBatchNorm): one-pass variadic-reduce stats, a
    custom VJP that recomputes x_hat (and, for the norm+ReLU pairs, the
    ReLU mask) instead of storing them, never leaving XLA's fusion
    graph — the shape the round-4 island-tax measurement demanded."""
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    norm: str = "batch"
    # Cross-replica (sync) BN: psum batch statistics over this mesh
    # axis (the flax, Pallas, and lean norm paths all support it). The
    # standard choice at small per-chip batch, where per-device BN
    # statistics get noisy.
    bn_axis_name: Optional[str] = None
    # Host-plane sync-BN scope (norm="lean"/"pallas" via the lean path):
    # a hvd.ProcessGroup (e.g. hvd.batch_group() under a 2-D mesh) or
    # the string "world" — statistics ride the host collectives
    # group-scoped (docs/GROUPS.md).
    bn_sync_group: Any = None
    # Ghost BN (norm="lean"/"pallas"): virtual batch each normalization
    # group sees; None = the whole per-replica batch.
    bn_virtual_batch_size: Optional[int] = None
    # BN-scoped remat (norm="lean"): recompute the normalize-pass
    # outputs in the backward instead of saving them
    # (ops.batch_norm.bn_remat_policy applied per residual block).
    bn_remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        norm_act = None
        if self.norm == "none":
            # Normalizer-free roofline probe: measures the conv-only
            # ceiling (NF-ResNet-style models train like this with
            # weight standardization + scalers, which add no
            # activation-pass traffic).
            def norm(name=None, scale_init=None):
                return lambda y: y
        elif self.norm == "group":
            norm = partial(nn.GroupNorm, num_groups=32, epsilon=1e-5,
                           dtype=self.dtype, param_dtype=jnp.float32)
        elif self.norm == "pallas":
            # Fused Pallas BN statistics (ops/batch_norm.py): one
            # bf16-read f32-accumulate kernel per stats pass, attacking
            # the convert_reduce_fusion HBM share in PERF.md.
            from horovod_tpu.ops.batch_norm import PallasBatchNorm
            norm = partial(PallasBatchNorm, use_running_average=not train,
                           momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                           param_dtype=jnp.float32,
                           axis_name=self.bn_axis_name,
                           virtual_batch_size=self.bn_virtual_batch_size)
        elif self.norm == "lean":
            # Traffic-lean graph-level BN (round 10, ops/batch_norm.py).
            from horovod_tpu.ops.batch_norm import LeanBatchNorm
            norm = partial(LeanBatchNorm, use_running_average=not train,
                           momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                           param_dtype=jnp.float32,
                           axis_name=self.bn_axis_name,
                           sync_group=self.bn_sync_group,
                           virtual_batch_size=self.bn_virtual_batch_size)
            # The norm+ReLU pairs fuse (backward mask recomputed from
            # the pre-activation sign); block-final norms and the
            # post-residual-add ReLUs stay separate.
            norm_act = partial(norm, fuse_relu=True)
        else:
            norm = partial(nn.BatchNorm, use_running_average=not train,
                           momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                           param_dtype=jnp.float32,
                           axis_name=self.bn_axis_name)
        act = nn.relu

        block_cls = self.block_cls
        if self.bn_remat:
            from horovod_tpu.ops.batch_norm import bn_remat_policy
            block_cls = nn.remat(block_cls, policy=bn_remat_policy())

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 name="conv_init")(x)
        if norm_act is not None:
            x = norm_act(name="bn_init")(x)
        else:
            x = act(norm(name="bn_init")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block_cls(self.num_filters * 2 ** i, conv=conv,
                              norm=norm, act=act, strides=strides,
                              norm_act=norm_act)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=ResNetBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3],
                    block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3],
                    block_cls=BottleneckBlock)
ResNet50GN = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                     block_cls=BottleneckBlock, norm="group")
ResNet50PBN = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                      block_cls=BottleneckBlock, norm="pallas")
ResNet50NF = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                     block_cls=BottleneckBlock, norm="none")
ResNet50Lean = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                       block_cls=BottleneckBlock, norm="lean")
ResNet101NF = partial(ResNet, stage_sizes=[3, 4, 23, 3],
                      block_cls=BottleneckBlock, norm="none")
