"""ResNet v1.5 in flax — the flagship benchmark model.

Capability parity: the reference benchmarks ResNet-50 via
`examples/tensorflow2_synthetic_benchmark.py:24-37` (Keras applications
ResNet50) and `examples/pytorch_imagenet_resnet50.py`; its headline scaling
numbers are ResNet-101 (`docs/benchmarks.rst:13-14,43`).

TPU-first choices (not inherited from the reference):
* NHWC layout — the natural layout for TPU convolutions; XLA tiles the
  channel dim onto the 128-lane MXU minor dimension.
* bfloat16 compute / float32 params + batch-norm statistics: matmul/conv
  inputs are cast to bf16 (MXU native), accumulation and state stay f32.
* Static shapes everywhere; stride-2 projection shortcuts (v1.5: the 3x3
  conv carries the stride, matching the torchvision model the reference
  benchmarks).
"""

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic two-conv residual block (ResNet-18/34)."""
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3(stride) -> 1x1 bottleneck (ResNet-50/101/152, v1.5)."""
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ResNet v1.5 over NHWC inputs.

    `norm="group"` swaps BatchNorm for GroupNorm(32) — the PERF.md
    roofline experiment: BN's cross-batch statistics force f32
    convert+reduce passes over every activation (the measured HBM
    bottleneck), while GN's within-sample stats stay in the compute
    dtype with f32 reduce accumulation only."""
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    norm: str = "batch"
    # Cross-replica (sync) BN: psum batch statistics over this mesh
    # axis (both the flax and the Pallas norm paths support it). The
    # standard choice at small per-chip batch, where per-device BN
    # statistics get noisy.
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        if self.norm == "none":
            # Normalizer-free roofline probe: measures the conv-only
            # ceiling (NF-ResNet-style models train like this with
            # weight standardization + scalers, which add no
            # activation-pass traffic).
            def norm(name=None, scale_init=None):
                return lambda y: y
        elif self.norm == "group":
            norm = partial(nn.GroupNorm, num_groups=32, epsilon=1e-5,
                           dtype=self.dtype, param_dtype=jnp.float32)
        elif self.norm == "pallas":
            # Fused Pallas BN statistics (ops/batch_norm.py): one
            # bf16-read f32-accumulate kernel per stats pass, attacking
            # the convert_reduce_fusion HBM share in PERF.md.
            from horovod_tpu.ops.batch_norm import PallasBatchNorm
            norm = partial(PallasBatchNorm, use_running_average=not train,
                           momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                           param_dtype=jnp.float32,
                           axis_name=self.bn_axis_name)
        else:
            norm = partial(nn.BatchNorm, use_running_average=not train,
                           momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                           param_dtype=jnp.float32,
                           axis_name=self.bn_axis_name)
        act = nn.relu

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i, conv=conv,
                                   norm=norm, act=act, strides=strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=ResNetBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3],
                    block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3],
                    block_cls=BottleneckBlock)
ResNet50GN = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                     block_cls=BottleneckBlock, norm="group")
ResNet50PBN = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                      block_cls=BottleneckBlock, norm="pallas")
ResNet50NF = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                     block_cls=BottleneckBlock, norm="none")
