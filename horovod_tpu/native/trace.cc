#include "trace.h"

#include <unistd.h>

#include <cctype>
#include <cinttypes>
#include <cstdlib>
#include <cstring>
#include <sys/stat.h>
#include <sys/types.h>

#include "metrics.h"

namespace hvdtpu {

namespace {

// JSON string escape (names come from user tensor names).
void AppendEscaped(std::string* out, const char* s) {
  for (const char* p = s; *p; ++p) {
    unsigned char c = static_cast<unsigned char>(*p);
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\n': *out += "\\n"; break;
      case '\r': *out += "\\r"; break;
      case '\t': *out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          *out += buf;
        } else {
          out->push_back(static_cast<char>(c));
        }
    }
  }
}

void AppendSpanJson(std::string* out, const TraceSpan& s) {
  char buf[160];
  *out += "{\"n\":\"";
  AppendEscaped(out, s.name);
  std::snprintf(buf, sizeof(buf),
                "\",\"p\":%d,\"g\":%u,\"c\":%" PRIu64
                ",\"pe\":%d,\"b\":%" PRId64 ",\"s\":%" PRId64
                ",\"e\":%" PRId64 ",\"f\":%u}",
                s.phase, s.group, s.cycle, s.peer, s.bytes, s.t_start,
                s.t_end, static_cast<unsigned>(s.flags));
  *out += buf;
}

uint64_t RoundUpPow2(uint64_t v) {
  uint64_t p = 1;
  while (p < v) p <<= 1;
  return p;
}

}  // namespace

const char* TracePhaseName(int p) {
  switch (p) {
    case TRACE_ENQUEUE: return "enqueue";
    case TRACE_NEGOTIATE: return "negotiate";
    case TRACE_FUSE: return "fuse";
    case TRACE_EXEC: return "exec";
    case TRACE_WIRE_HOP: return "wire_hop";
    case TRACE_ENCODE: return "encode";
    case TRACE_DECODE: return "decode";
    case TRACE_CALLBACK: return "callback";
    case TRACE_REQUEST: return "request";
  }
  return "unknown";
}

Trace::Trace() : epoch_(std::chrono::steady_clock::now()) {}

int64_t Trace::NowNs() const {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now() - epoch_)
      .count();
}

void Trace::Configure(int rank, int world_size, int64_t generation) {
  rank_.store(rank, std::memory_order_relaxed);
  world_size_.store(world_size, std::memory_order_relaxed);
  generation_.store(generation, std::memory_order_relaxed);

  const char* trace_env = std::getenv("HVD_TPU_TRACE");
  bool on = !(trace_env && std::strcmp(trace_env, "0") == 0);

  if (!ring_) {
    uint64_t cap = 32768;
    const char* ring_env = std::getenv("HVD_TPU_TRACE_RING");
    if (ring_env && *ring_env) {
      long long v = std::atoll(ring_env);
      if (v >= 64 && v <= (1ll << 22)) cap = static_cast<uint64_t>(v);
    }
    cap = RoundUpPow2(cap);
    ring_.reset(new TraceSlot[cap]);
    ring_mask_ = cap - 1;
  }

  {
    std::lock_guard<std::mutex> lock(bundle_mutex_);
    const char* bdir = std::getenv("HVD_TPU_BUNDLE_DIR");
    bundle_dir_ = bdir ? bdir : "";
  }

  {
    std::lock_guard<std::mutex> lock(shard_mutex_);
    const char* tdir = std::getenv("HVD_TPU_TRACE_DIR");
    trace_dir_ = (on && tdir) ? tdir : "";
    if (!trace_dir_.empty() && shard_file_ == nullptr) {
      ::mkdir(trace_dir_.c_str(), 0777);  // best-effort; may pre-exist
      std::string path =
          trace_dir_ + "/trace_rank" + std::to_string(rank) + ".jsonl";
      shard_file_ = std::fopen(path.c_str(), "w");
    }
    if (shard_file_ != nullptr) {
      WriteShardHeaderLocked();
      if (!drainer_running_) {
        drainer_stop_.store(false, std::memory_order_relaxed);
        drainer_thread_ = std::thread(&Trace::DrainerLoop, this);
        drainer_running_ = true;
      }
    }
  }

  enabled_.store(on, std::memory_order_relaxed);
}

// lockorder: requires(shard_mutex_)
void Trace::WriteShardHeaderLocked() {
  char buf[256];
  std::snprintf(buf, sizeof(buf),
                "{\"hvd_trace_shard\":1,\"rank\":%d,\"size\":%d,"
                "\"generation\":%" PRId64 ",\"pid\":%d,\"ring\":%" PRIu64
                "}\n",
                rank_.load(std::memory_order_relaxed),
                world_size_.load(std::memory_order_relaxed),
                generation_.load(std::memory_order_relaxed),
                static_cast<int>(::getpid()), ring_mask_ + 1);
  std::fputs(buf, shard_file_);
  std::fflush(shard_file_);
}

void Trace::Record(const char* name, int phase, int64_t start_ns,
                   int64_t end_ns, int64_t bytes, uint32_t group, int peer,
                   uint64_t cycle, uint8_t flags) {
  if (!enabled_.load(std::memory_order_relaxed) || !ring_) return;
  uint64_t idx = head_.fetch_add(1, std::memory_order_relaxed);
  TraceSlot& slot = ring_[idx & ring_mask_];
  slot.seq.store(TraceSlot::kBusy, std::memory_order_relaxed);
  // Order the busy marker before the payload stores: a reader that
  // observes any payload word then re-checks seq (acquire fence) must
  // see at least the busy marker and reject the torn slot.
  std::atomic_thread_fence(std::memory_order_release);
  slot.t_start.store(start_ns, std::memory_order_relaxed);
  slot.t_end.store(end_ns, std::memory_order_relaxed);
  slot.cycle.store(cycle, std::memory_order_relaxed);
  slot.bytes.store(bytes, std::memory_order_relaxed);
  uint64_t meta = static_cast<uint64_t>(static_cast<uint8_t>(phase)) |
                  (static_cast<uint64_t>(flags) << 8) |
                  (static_cast<uint64_t>(group & 0xffff) << 16) |
                  (static_cast<uint64_t>(static_cast<uint32_t>(peer)) << 32);
  slot.meta.store(meta, std::memory_order_relaxed);
  char padded[TraceSlot::kNameWords * 8];
  std::memset(padded, 0, sizeof(padded));
  if (name) {
    size_t n = std::strlen(name);
    if (n > sizeof(padded) - 1) n = sizeof(padded) - 1;
    std::memcpy(padded, name, n);
  }
  for (int w = 0; w < TraceSlot::kNameWords; ++w) {
    uint64_t word;
    std::memcpy(&word, padded + w * 8, 8);
    slot.name[w].store(word, std::memory_order_relaxed);
  }
  slot.seq.store(idx + 1, std::memory_order_release);
  spans_total.fetch_add(1, std::memory_order_relaxed);
}

bool Trace::ReadSlot(uint64_t idx, TraceSpan* out) const {
  const TraceSlot& slot = ring_[idx & ring_mask_];
  uint64_t s1 = slot.seq.load(std::memory_order_acquire);
  if (s1 != idx + 1) return false;
  out->t_start = slot.t_start.load(std::memory_order_relaxed);
  out->t_end = slot.t_end.load(std::memory_order_relaxed);
  out->cycle = slot.cycle.load(std::memory_order_relaxed);
  out->bytes = slot.bytes.load(std::memory_order_relaxed);
  uint64_t meta = slot.meta.load(std::memory_order_relaxed);
  out->phase = static_cast<int>(meta & 0xff);
  out->flags = static_cast<uint8_t>((meta >> 8) & 0xff);
  out->group = static_cast<uint32_t>((meta >> 16) & 0xffff);
  out->peer = static_cast<int>(static_cast<int32_t>(meta >> 32));
  for (int w = 0; w < TraceSlot::kNameWords; ++w) {
    uint64_t word = slot.name[w].load(std::memory_order_relaxed);
    std::memcpy(out->name + w * 8, &word, 8);
  }
  out->name[sizeof(out->name) - 1] = '\0';
  std::atomic_thread_fence(std::memory_order_acquire);
  return slot.seq.load(std::memory_order_relaxed) == idx + 1;
}

void Trace::OpenSpan(const std::string& key, int64_t start_ns) {
  if (!enabled_.load(std::memory_order_relaxed)) return;
  std::lock_guard<std::mutex> lock(open_mutex_);
  open_spans_[key] = start_ns;
}

int64_t Trace::CloseSpan(const std::string& key) {
  std::lock_guard<std::mutex> lock(open_mutex_);
  auto it = open_spans_.find(key);
  if (it == open_spans_.end()) return -1;
  int64_t start = it->second;
  open_spans_.erase(it);
  return start;
}

void Trace::NoteControlFrame(uint32_t tag, bool send, uint64_t bytes) {
  if (!enabled_.load(std::memory_order_relaxed)) return;
  std::lock_guard<std::mutex> lock(frame_mutex_);
  control_frames_.push_back(FrameNote{NowNs(), tag, send, bytes});
  while (control_frames_.size() > kControlFrameLog) {
    control_frames_.pop_front();
  }
}

void Trace::UpdateClockSample(int64_t t1, int64_t t2, int64_t t3,
                              int64_t t4) {
  // offset maps local time onto the reference: t_ref = t_local + offset.
  int64_t offset = ((t2 - t1) + (t3 - t4)) / 2;
  int64_t uncertainty = ((t4 - t1) - (t3 - t2)) / 2;
  if (uncertainty < 0) return;  // asymmetric nonsense (clock slew mid-sample)
  int64_t now = NowNs();
  int64_t cur_unc = clock_uncertainty_ns_.load(std::memory_order_relaxed);
  int64_t cur_at = clock_sampled_at_ns_.load(std::memory_order_relaxed);
  bool stale = (now - cur_at) > kClockStaleNs;
  if (cur_unc >= 0 && !stale && uncertainty >= cur_unc) return;
  clock_offset_ns_.store(offset, std::memory_order_relaxed);
  clock_uncertainty_ns_.store(uncertainty, std::memory_order_relaxed);
  clock_sampled_at_ns_.store(now, std::memory_order_relaxed);
}

// lockorder: requires(shard_mutex_)
void Trace::DrainLocked() {
  if (shard_file_ == nullptr || !ring_) return;
  uint64_t cap = ring_mask_ + 1;
  uint64_t head = head_.load(std::memory_order_acquire);
  if (head - drain_cursor_ > cap) {
    uint64_t lost = head - drain_cursor_ - cap;
    spans_dropped.fetch_add(lost, std::memory_order_relaxed);
    drain_cursor_ = head - cap;
  }
  // Emit a clock record when the estimate moved since the last emit.
  int64_t unc = clock_uncertainty_ns_.load(std::memory_order_relaxed);
  if (unc >= 0) {
    int64_t off = clock_offset_ns_.load(std::memory_order_relaxed);
    if (off != last_clock_emitted_) {
      char buf[160];
      std::snprintf(buf, sizeof(buf),
                    "{\"clock\":{\"offset_ns\":%" PRId64
                    ",\"uncertainty_ns\":%" PRId64 ",\"at_ns\":%" PRId64
                    "}}\n",
                    off, unc, NowNs());
      std::fputs(buf, shard_file_);
      last_clock_emitted_ = off;
    }
  }
  std::string line;
  while (drain_cursor_ < head) {
    TraceSpan span;
    if (!ReadSlot(drain_cursor_, &span)) {
      // Unpublished (writer mid-flight) or overwritten by a racing
      // wrap. A racing wrap means the head moved past cursor + cap —
      // the next drain's overrun accounting picks the loss up; a
      // mid-flight writer means everything after it is younger, so
      // stop either way and retry next wake.
      break;
    }
    line.clear();
    AppendSpanJson(&line, span);
    line += '\n';
    std::fputs(line.c_str(), shard_file_);
    ++drain_cursor_;
  }
  std::fflush(shard_file_);
}

void Trace::DrainerLoop() {
  while (!drainer_stop_.load(std::memory_order_relaxed)) {
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
    std::lock_guard<std::mutex> lock(shard_mutex_);
    DrainLocked();
  }
}

void Trace::FlushShard() {
  std::lock_guard<std::mutex> lock(shard_mutex_);
  DrainLocked();
}

void Trace::Shutdown() {
  std::thread t;
  {
    std::lock_guard<std::mutex> lock(shard_mutex_);
    if (drainer_running_) {
      drainer_stop_.store(true, std::memory_order_relaxed);
      t = std::move(drainer_thread_);
      drainer_running_ = false;
    }
  }
  if (t.joinable()) t.join();
  std::lock_guard<std::mutex> lock(shard_mutex_);
  DrainLocked();
  if (shard_file_ != nullptr) {
    std::fclose(shard_file_);
    shard_file_ = nullptr;
  }
}

std::vector<TraceSpan> Trace::SnapshotSpans() const {
  std::vector<TraceSpan> out;
  if (!ring_) return out;
  uint64_t cap = ring_mask_ + 1;
  uint64_t head = head_.load(std::memory_order_acquire);
  uint64_t start = head > cap ? head - cap : 0;
  out.reserve(static_cast<size_t>(head - start));
  for (uint64_t i = start; i < head; ++i) {
    TraceSpan span;
    if (ReadSlot(i, &span)) out.push_back(span);
  }
  return out;
}

std::string Trace::DumpBundle(const char* reason,
                              const std::string& pending_json) {
  if (bundles_written.load(std::memory_order_relaxed) >=
      static_cast<uint64_t>(kMaxBundles)) {
    return "";
  }
  std::lock_guard<std::mutex> lock(bundle_mutex_);
  if (bundle_dir_.empty()) return "";
  ::mkdir(bundle_dir_.c_str(), 0777);  // best-effort; may pre-exist

  std::string safe_reason;
  for (const char* p = reason ? reason : "unknown"; *p; ++p) {
    char c = *p;
    safe_reason += (std::isalnum(static_cast<unsigned char>(c)) || c == '_' ||
                    c == '-')
                       ? c
                       : '_';
  }
  uint64_t n = bundles_written.fetch_add(1, std::memory_order_relaxed);
  std::string path = bundle_dir_ + "/hvd_bundle_rank" +
                     std::to_string(rank_.load(std::memory_order_relaxed)) +
                     "_" + safe_reason + "_" + std::to_string(n) + "_" +
                     std::to_string(static_cast<int>(::getpid())) + ".json";

  std::string out;
  out.reserve(1 << 16);
  char buf[256];
  std::snprintf(buf, sizeof(buf),
                "{\"hvd_bundle\":1,\"reason\":\"%s\",\"rank\":%d,"
                "\"world_size\":%d,\"generation\":%" PRId64
                ",\"pid\":%d,\"now_ns\":%" PRId64 ",",
                safe_reason.c_str(), rank_.load(std::memory_order_relaxed),
                world_size_.load(std::memory_order_relaxed),
                generation_.load(std::memory_order_relaxed),
                static_cast<int>(::getpid()), NowNs());
  out += buf;
  std::snprintf(buf, sizeof(buf),
                "\"clock\":{\"offset_ns\":%" PRId64
                ",\"uncertainty_ns\":%" PRId64 "},",
                clock_offset_ns_.load(std::memory_order_relaxed),
                clock_uncertainty_ns_.load(std::memory_order_relaxed));
  out += buf;
  std::snprintf(buf, sizeof(buf),
                "\"counters\":{\"trace_spans_total\":%" PRIu64
                ",\"trace_spans_dropped_total\":%" PRIu64
                ",\"bundles_written_total\":%" PRIu64 "},",
                spans_total.load(std::memory_order_relaxed),
                spans_dropped.load(std::memory_order_relaxed),
                bundles_written.load(std::memory_order_relaxed));
  out += buf;

  out += "\"pending\":";
  out += pending_json.empty() ? "null" : pending_json;
  out += ',';

  out += "\"control_frames\":[";
  {
    std::lock_guard<std::mutex> flock(frame_mutex_);
    bool first = true;
    for (const FrameNote& f : control_frames_) {
      if (!first) out += ',';
      first = false;
      char tag[5];
      for (int i = 0; i < 4; ++i) {
        char c = static_cast<char>((f.tag >> (8 * i)) & 0xff);
        tag[i] = (c >= 0x20 && c < 0x7f) ? c : '.';
      }
      tag[4] = '\0';
      std::snprintf(buf, sizeof(buf),
                    "{\"t\":%" PRId64
                    ",\"tag\":\"%s\",\"dir\":\"%s\",\"bytes\":%" PRIu64 "}",
                    f.t_ns, tag, f.send ? "send" : "recv", f.bytes);
      out += buf;
    }
  }
  out += "],";

  out += "\"open_spans\":[";
  {
    std::lock_guard<std::mutex> olock(open_mutex_);
    bool first = true;
    for (const auto& kv : open_spans_) {
      if (!first) out += ',';
      first = false;
      out += "{\"key\":\"";
      AppendEscaped(&out, kv.first.c_str());
      std::snprintf(buf, sizeof(buf), "\",\"since_ns\":%" PRId64 "}",
                    kv.second);
      out += buf;
    }
  }
  out += "],";

  out += "\"metrics\":";
  out += GlobalMetrics().SnapshotJson();
  out += ',';

  out += "\"spans\":[";
  {
    std::vector<TraceSpan> spans = SnapshotSpans();
    bool first = true;
    for (const TraceSpan& s : spans) {
      if (!first) out += ',';
      first = false;
      AppendSpanJson(&out, s);
    }
  }
  out += "]}\n";

  std::FILE* f = std::fopen(path.c_str(), "w");
  if (f == nullptr) return "";
  std::fwrite(out.data(), 1, out.size(), f);
  std::fclose(f);
  FlushShard();
  return path;
}

Trace& GlobalTrace() {
  static Trace* trace = new Trace();
  return *trace;
}

}  // namespace hvdtpu
