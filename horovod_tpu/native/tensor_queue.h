// Thread-safe table of pending TensorTableEntry + FIFO of outgoing Requests.
// Producer side: framework API threads enqueue; consumer side: the single
// background coordination thread pops per cycle.
//
// Capability parity with /root/reference horovod/common/tensor_queue.{h,cc}.
#ifndef HVD_TPU_TENSOR_QUEUE_H
#define HVD_TPU_TENSOR_QUEUE_H

#include <deque>
#include <mutex>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "message.h"

namespace hvdtpu {

class TensorQueue {
 public:
  // Rejects duplicate names (DUPLICATE_NAME_ERROR).
  Status AddToTensorQueue(TensorTableEntry entry, Request message);

  // Pops every queued Request accumulated since last cycle.
  void PopMessagesFromQueue(std::deque<Request>& messages);

  // Re-queues a message (e.g. tensor deferred because a peer isn't ready).
  void PushMessageToQueue(const Request& message);

  void GetTensorEntriesFromResponse(const Response& response,
                                    std::vector<TensorTableEntry>& entries);

  const TensorTableEntry& GetTensorEntry(const std::string& name) const;
  bool HasEntry(const std::string& name) const;

  // On shutdown: fails every pending entry's callback with `status`.
  void FinalizeTensorQueue(const Status& status);

  int64_t GetTensorDataForAutotuner(const std::deque<Request>& messages,
                                    int64_t& total_bytes);

 private:
  mutable std::mutex mutex_;
  std::unordered_map<std::string, TensorTableEntry>
      tensor_table_;               // guarded_by(mutex_)
  std::deque<Request> message_queue_;  // guarded_by(mutex_)
};

}  // namespace hvdtpu

#endif  // HVD_TPU_TENSOR_QUEUE_H
