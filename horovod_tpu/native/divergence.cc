#include "divergence.h"

#include <algorithm>
#include <set>
#include <sstream>

#include "logging.h"

namespace hvdtpu {

namespace {

constexpr uint64_t kFnvPrime = 1099511628211ULL;

uint64_t FoldByte(uint64_t h, uint8_t b) { return (h ^ b) * kFnvPrime; }

uint64_t FoldCall(uint64_t digest, uint8_t op, uint8_t dtype, uint8_t ndim,
                  const std::string& name) {
  uint64_t h = digest;
  h = FoldByte(h, op);
  h = FoldByte(h, dtype);
  h = FoldByte(h, ndim);
  for (char c : name) h = FoldByte(h, static_cast<uint8_t>(c));
  return FoldByte(h, 0xFFu);  // terminator: "ab"+"c" != "a"+"bc"
}

const char* OpName(uint8_t op) {
  return Request::RequestTypeName(static_cast<Request::RequestType>(op));
}

std::string JoinRanks(const std::set<int>& ranks) {
  std::ostringstream os;
  bool first = true;
  for (int r : ranks) {
    if (!first) os << ", ";
    os << r;
    first = false;
  }
  return os.str();
}

}  // namespace

// ---------------- CallTracker ----------------

void CallTracker::Record(uint8_t op, uint8_t dtype, int ndim,
                         const std::string& name) {
  std::lock_guard<std::mutex> lk(mutex_);
  seq_ += 1;
  digest_ = FoldCall(digest_, op, dtype, static_cast<uint8_t>(ndim), name);
  CallRecord rec;
  rec.seq = seq_;
  rec.op = op;
  rec.dtype = dtype;
  rec.ndim = static_cast<uint8_t>(ndim);
  rec.name = name;
  ring_.push_back(std::move(rec));
  if (ring_.size() > kRingCapacity) ring_.pop_front();
}

void CallTracker::Snapshot(uint64_t* seq, uint64_t* digest) const {
  std::lock_guard<std::mutex> lk(mutex_);
  if (seq != nullptr) *seq = seq_;
  if (digest != nullptr) *digest = digest_;
}

std::vector<CallRecord> CallTracker::RecordsSince(uint64_t after_seq,
                                                  std::size_t limit,
                                                  uint64_t up_to_seq) const {
  std::lock_guard<std::mutex> lk(mutex_);
  std::vector<CallRecord> out;
  for (const auto& rec : ring_) {
    if (rec.seq > after_seq && rec.seq <= up_to_seq) out.push_back(rec);
  }
  if (out.size() > limit) {  // keep the most recent `limit`
    out.erase(out.begin(), out.end() - limit);
  }
  return out;
}

void CallTracker::Reset() {
  std::lock_guard<std::mutex> lk(mutex_);
  seq_ = 0;
  digest_ = 14695981039346656037ULL;
  ring_.clear();
}

// ---------------- DivergenceDetector ----------------

void DivergenceDetector::Configure(int world_size, int64_t progress_calls,
                                   double grace_seconds) {
  world_size_ = world_size;
  progress_calls_ = progress_calls;
  grace_seconds_ = grace_seconds;
  ranks_.assign(static_cast<std::size_t>(world_size), RankState());
  pending_.clear();
}

void DivergenceDetector::Observe(int rank, uint64_t seq, uint64_t digest,
                                 const std::vector<CallRecord>& recent) {
  if (rank < 0 || rank >= static_cast<int>(ranks_.size())) return;
  RankState& st = ranks_[rank];
  if (seq >= st.seq) {  // ignore stale reports (digest must match seq)
    st.seq = seq;
    st.digest = digest;
  }
  for (const auto& rec : recent) {
    if (!st.log.empty() && rec.seq <= st.log.back().seq) continue;
    st.log.push_back(rec);
  }
  while (st.log.size() > CallTracker::kRingCapacity) st.log.pop_front();
}

bool DivergenceDetector::ShouldForceFullCycle(
    const std::unordered_map<std::string, std::vector<Request>>& pending) {
  if (grace_seconds_ <= 0.0 && progress_calls_ <= 0) return false;
  if (pending.empty()) return false;
  auto now = Clock::now();
  // Forcing is rate-limited: while stalled, one extra round trip every
  // 200ms keeps the seq/digest view fresh without turning the idle cycle
  // pace into a busy loop.
  if (now - last_forced_ < std::chrono::milliseconds(200)) return false;
  double age_floor =
      grace_seconds_ > 0.0 ? std::min(grace_seconds_ / 2.0, 1.0) : 1.0;
  for (const auto& kv : pending) {
    auto it = pending_.find(kv.first);
    if (it == pending_.end()) continue;
    double age = std::chrono::duration<double>(now - it->second.first_seen)
                     .count();
    if (age >= age_floor) {
      last_forced_ = now;
      return true;
    }
  }
  return false;
}

std::vector<DivergenceDetector::Diagnosis> DivergenceDetector::Check(
    const std::unordered_map<std::string, std::vector<Request>>& pending,
    const GroupTable* groups) {
  std::vector<Diagnosis> out;
  if (ranks_.empty()) return out;
  auto now = Clock::now();

  // Sync the pending bookkeeping with the live table: first sight stamps
  // the clock and snapshots every rank's known seq.
  for (const auto& kv : pending) {
    if (pending_.count(kv.first)) continue;
    PendingState st;
    st.first_seen = now;
    st.seq_at_announce.reserve(ranks_.size());
    for (const auto& rank : ranks_) st.seq_at_announce.push_back(rank.seq);
    pending_.emplace(kv.first, std::move(st));
  }
  for (auto it = pending_.begin(); it != pending_.end();) {
    if (pending.count(it->first) == 0) {
      it = pending_.erase(it);
    } else {
      ++it;
    }
  }

  // Submitter / missing sets per pending tensor.
  std::unordered_map<std::string, std::set<int>> submitters;
  for (const auto& kv : pending) {
    std::set<int>& s = submitters[kv.first];
    for (const auto& req : kv.second) s.insert(req.request_rank());
  }

  for (const auto& kv : pending) {
    const std::string& name = kv.first;
    const PendingState& st = pending_.at(name);
    const std::set<int>& sub = submitters[name];
    const Request& first = kv.second.front();
    double age =
        std::chrono::duration<double>(now - st.first_seen).count();

    // Group scope: only the GROUP's members owe this tensor. A
    // group-scoped divergence must name the group and its members, not
    // implicate (or wait on) the rest of the world.
    const uint32_t gid = first.group_id();
    std::string scope;
    std::set<int> missing;
    if (gid != 0 && groups != nullptr) {
      std::vector<int> members = groups->Members(gid);
      if (members.empty()) {
        // The id never registered HERE. The controller's
        // late-registration sweep covers the benign race (this
        // process's new_group just hasn't run yet); once the tensor has
        // aged past the grace window it is provably NOT that race —
        // this process skipped the new_group call entirely (a
        // registration-order divergence). Error by name instead of
        // hanging forever.
        if (grace_seconds_ > 0.0 && age >= grace_seconds_) {
          std::ostringstream msg;
          msg << "collective protocol divergence at '" << name << "' ("
              << OpName(static_cast<uint8_t>(first.request_type())) << " "
              << DataTypeName(first.tensor_type())
              << "): submitted by rank(s) [" << JoinRanks(sub)
              << "] in process group " << gid << ", but this coordinator "
              << "never registered that group after " << static_cast<int>(age)
              << "s — some rank skipped (or reordered) its hvd.new_group "
              << "call; every rank must create groups with the identical "
              << "rank lists in the identical order (docs/GROUPS.md).";
          out.push_back({name, first.tensor_name(), gid, msg.str()});
        }
        continue;
      }
      for (int r : members) {
        if (sub.count(r) == 0) missing.insert(r);
      }
      scope = " in process group " + std::to_string(gid) + " " +
              groups->DescribeMembers(gid);
    } else {
      for (int r = 0; r < world_size_; ++r) {
        if (sub.count(r) == 0) missing.insert(r);
      }
    }
    if (missing.empty()) continue;

    // Progress rule: a missing rank kept submitting other collectives.
    for (int r : missing) {
      uint64_t at = st.seq_at_announce.size() > static_cast<std::size_t>(r)
                        ? st.seq_at_announce[r]
                        : 0;
      if (progress_calls_ > 0 &&
          ranks_[r].seq >= at + static_cast<uint64_t>(progress_calls_)) {
        std::ostringstream msg;
        msg << "collective protocol divergence at '" << name << "' ("
            << OpName(static_cast<uint8_t>(first.request_type())) << " "
            << DataTypeName(first.tensor_type()) << scope
            << "): submitted by rank(s) ["
            << JoinRanks(sub) << "] but rank " << r << " proceeded through "
            << (ranks_[r].seq - at)
            << " other collectives without submitting it; rank " << r
            << " went on to: " << DescribeRecentCalls(r, at, 4)
            << ". A rank-conditional collective or mismatched call order is "
               "the usual cause (run hvd-lint on the training script).";
        out.push_back({name, first.tensor_name(), gid, msg.str()});
        break;
      }
    }
    if (!out.empty() && out.back().key == name) continue;

    // Cross-stall rule: tensor aged past the grace window and every
    // missing rank is itself a submitter of a *different* aged pending
    // tensor — a mutual wait on diverged call sites, not mere slowness.
    if (grace_seconds_ <= 0.0 || age < grace_seconds_) continue;
    bool all_evidenced = true;
    std::ostringstream waits;
    for (int r : missing) {
      const std::string* waiting_on = nullptr;
      for (const auto& other : pending) {
        if (other.first == name) continue;
        if (submitters[other.first].count(r) == 0) continue;
        double other_age = std::chrono::duration<double>(
                               now - pending_.at(other.first).first_seen)
                               .count();
        if (other_age >= grace_seconds_) {
          waiting_on = &other.first;
          break;
        }
      }
      if (waiting_on == nullptr) {
        all_evidenced = false;
        break;
      }
      waits << " rank " << r << " is waiting on '" << *waiting_on << "';";
    }
    if (!all_evidenced) continue;
    std::ostringstream msg;
    msg << "collective protocol divergence at '" << name << "' ("
        << OpName(static_cast<uint8_t>(first.request_type())) << " "
        << DataTypeName(first.tensor_type()) << scope << "): rank(s) ["
        << JoinRanks(sub) << "] have waited " << static_cast<int>(age)
        << "s while the missing rank(s) wait on different collectives:"
        << waits.str()
        << " the ranks' collective call sequences have diverged "
           "(rank-conditional collective or mismatched call order; run "
           "hvd-lint on the training script).";
    out.push_back({name, first.tensor_name(), gid, msg.str()});
  }
  return out;
}

std::string DivergenceDetector::DescribeRecentCalls(
    int rank, uint64_t after_seq, std::size_t max_shown) const {
  const RankState& st = ranks_[rank];
  std::ostringstream os;
  std::size_t shown = 0;
  for (const auto& rec : st.log) {
    if (rec.seq <= after_seq) continue;
    if (shown == max_shown) {
      os << ", ...";
      break;
    }
    if (shown > 0) os << ", ";
    os << OpName(rec.op) << " '" << rec.name << "' ("
       << DataTypeName(static_cast<DataType>(rec.dtype)) << ", ndim "
       << static_cast<int>(rec.ndim) << ")";
    shown += 1;
  }
  if (shown == 0) return "(no recent call records received)";
  return os.str();
}

}  // namespace hvdtpu
