#include "controller.h"

#include <algorithm>
#include <cassert>
#include <cstdio>
#include <cstring>
#include <sstream>
#include <unordered_set>

#include "compression.h"
#include "logging.h"
#include "metrics.h"
#include "parameter_manager.h"
#include "trace.h"

namespace hvdtpu {

// Fused buffers are carved at 64-byte granularity so hierarchical ops can
// split them evenly across local ranks without misaligned segments.
static constexpr int64_t kFusionBufferAtomicUnit = 64;

Controller::Controller(ResponseCache& response_cache, TensorQueue& tensor_queue,
                       Timeline& timeline, ParameterManager& parameter_manager)
    : response_cache_(response_cache),
      tensor_queue_(tensor_queue),
      timeline_(timeline),
      parameter_manager_(parameter_manager) {}

int64_t Controller::TensorFusionThresholdBytes() const {
  int64_t proposed = parameter_manager_.TensorFusionThresholdBytes();
  if (proposed <= 0) return 0;
  // Round so a fused buffer splits into local_size_ aligned chunks.
  int64_t unit = kFusionBufferAtomicUnit * local_size_;
  if (parameter_manager_.HierarchicalAllreduce() && proposed % unit != 0) {
    proposed = std::max<int64_t>(unit, (proposed / unit) * unit);
  }
  return proposed;
}

void Controller::SynchronizeParameters() {
  ParameterManager::Params params;
  std::memset(&params, 0, sizeof(params));
  if (is_coordinator()) params = parameter_manager_.GetParams();
  std::string blob(reinterpret_cast<char*>(&params), sizeof(params));
  BroadcastBlob(&blob);
  if (!is_coordinator() && blob.size() == sizeof(params)) {
    std::memcpy(&params, blob.data(), sizeof(params));
    parameter_manager_.SetParams(params);
  }
}

bool Controller::IncrementTensorCount(const Request& msg, int rank) {
  // Pending-table key is group-qualified: the same tensor name active
  // in two groups at once is two independent negotiations.
  const std::string key =
      GroupQualifiedName(msg.group_id(), msg.tensor_name());
  auto it = message_table_.find(key);
  auto now = std::chrono::steady_clock::now();
  if (it == message_table_.end()) {
    timeline_.NegotiateStart(key, msg.request_type());
    it = message_table_.emplace(key, std::vector<Request>()).first;
    negotiate_started_[key] = now;
    if (metrics_plane_enabled_) GlobalMetrics().AddRankLag(rank, 0.0);
  } else if (metrics_plane_enabled_) {
    // Announce lag: how long this rank kept the tensor waiting after its
    // first announcement. Per-rank accumulation is the straggler signal
    // the job view surfaces (the slow rank's total dominates). Gated on
    // the plane: AddRankLag takes the registry's rank mutex (shared with
    // snapshot builds), which metrics-off jobs must never touch.
    auto started = negotiate_started_.find(key);
    if (started != negotiate_started_.end()) {
      GlobalMetrics().AddRankLag(
          rank, std::chrono::duration<double>(now - started->second).count());
    }
  }
  timeline_.NegotiateRankReady(key, rank);
  // Readiness threshold: ALL ranks for the world group, the MEMBER set
  // for a process group (the bitmap sized to the group). Provably-bad
  // group reports (unknown id / non-member announcer / membership-digest
  // mismatch) go ready IMMEDIATELY so ConstructResponse rejects them by
  // name instead of leaving the count stuck below threshold forever.
  int expected = size_;
  std::vector<int> members;
  bool poisoned = false;
  if (msg.group_id() != 0) {
    if (group_table_ == nullptr) {
      poisoned = true;  // no registry at all: can never resolve
    } else {
      members = group_table_->Members(msg.group_id());
      if (members.empty()) {
        // Not registered in THIS process yet: new_group is per-process
        // and unsynchronized, so another rank's announcement can arrive
        // before the coordinator's own call lands. Leave the tensor
        // pending — the late-registration sweep in FinishCycle marks it
        // ready once the id resolves (a genuinely unknown id then ends
        // in the divergence/stall path, by name).
        expected = -1;
      } else {
        expected = static_cast<int>(members.size());
        poisoned =
            !std::binary_search(members.begin(), members.end(), rank) ||
            msg.group_digest() != group_table_->Digest(msg.group_id());
      }
    }
  }
  stall_inspector_.RecordUncachedTensorStart(
      key, rank, size_, members.empty() ? nullptr : &members);
  it->second.push_back(msg);
  return poisoned || static_cast<int>(it->second.size()) == expected;
}

Response Controller::ConstructResponse(const std::string& key) {
  auto it = message_table_.find(key);
  assert(it != message_table_.end());
  std::vector<Request> requests = std::move(it->second);
  message_table_.erase(it);
  stall_inspector_.RemoveUncachedTensor(key);
  timeline_.NegotiateEnd(key);
  auto started = negotiate_started_.find(key);
  if (started != negotiate_started_.end()) {
    GlobalMetrics().negotiation_seconds.Observe(
        std::chrono::duration<double>(std::chrono::steady_clock::now() -
                                      started->second)
            .count());
    negotiate_started_.erase(started);
  }

  const Request& first = requests[0];
  const uint32_t gid = first.group_id();
  std::vector<int> members;
  if (gid != 0 && group_table_ != nullptr) {
    members = group_table_->Members(gid);
  }
  std::ostringstream error;
  bool error_found = false;

  // Group validation first: a membership problem explains every other
  // mismatch, so it must own the error message.
  if (gid != 0) {
    if (members.empty()) {
      error << "Unknown process group " << gid << " for tensor '"
            << first.tensor_name()
            << "'; every rank must create groups with hvd.new_group(...) "
            << "in the same order before using them.";
      error_found = true;
    } else {
      uint64_t digest = group_table_->Digest(gid);
      for (const auto& req : requests) {
        if (req.group_digest() != digest) {
          error << "Mixed membership for process group " << gid
                << ": rank " << req.request_rank()
                << " created it with a different rank list than this "
                << "coordinator's " << group_table_->DescribeMembers(gid)
                << "; every rank must pass the identical ranks to "
                << "hvd.new_group.";
          error_found = true;
          break;
        }
        if (!std::binary_search(members.begin(), members.end(),
                                req.request_rank())) {
          error << "rank " << req.request_rank() << " announced tensor '"
                << first.tensor_name() << "' in process group " << gid
                << " whose members are "
                << group_table_->DescribeMembers(gid)
                << "; only members may submit group collectives.";
          error_found = true;
          break;
        }
      }
    }
  }

  // All ranks must agree on op type, dtype, and scaling.
  for (const auto& req : requests) {
    if (error_found) break;
    if (req.request_type() != first.request_type()) {
      error << "Mismatched collective operations: rank "
            << first.request_rank() << " did "
            << Request::RequestTypeName(first.request_type())
            << " while rank " << req.request_rank() << " did "
            << Request::RequestTypeName(req.request_type()) << ".";
      // A sharded-vs-replicated split is the mixed-execution-mode case
      // (docs/ZERO.md): name both ranks AND both modes, exactly like
      // mixed compression, so the fix is obvious from the message.
      auto is_pair = [&](Request::RequestType a, Request::RequestType b) {
        return (first.request_type() == a && req.request_type() == b) ||
               (first.request_type() == b && req.request_type() == a);
      };
      if (is_pair(Request::ALLREDUCE, Request::REDUCESCATTER)) {
        int sharded_rank = first.request_type() == Request::REDUCESCATTER
                               ? first.request_rank()
                               : req.request_rank();
        int replicated_rank = first.request_type() == Request::ALLREDUCE
                                  ? first.request_rank()
                                  : req.request_rank();
        error << " Mixed execution modes: rank " << sharded_rank
              << " runs sharded_update (reduce-scatter) while rank "
              << replicated_rank
              << " runs the replicated update (allreduce); pass the same "
              << "sharded_update= (or HVD_TPU_SHARDED_UPDATE) on every "
              << "rank.";
      }
      error_found = true;
      break;
    }
    if (req.tensor_type() != first.tensor_type()) {
      error << "Mismatched data types: one rank had "
            << DataTypeName(first.tensor_type()) << " while another had "
            << DataTypeName(req.tensor_type()) << ".";
      error_found = true;
      break;
    }
    if (req.prescale_factor() != first.prescale_factor() ||
        req.postscale_factor() != first.postscale_factor()) {
      error << "Mismatched prescale/postscale factors across ranks.";
      error_found = true;
      break;
    }
    if (req.compression() != first.compression()) {
      // Lossy codecs must be job-uniform: a rank decoding bf16 frames
      // as raw f32 would be silent corruption, so reject by name,
      // naming BOTH ranks and their modes.
      error << "Mismatched compression modes: rank " << first.request_rank()
            << " requested "
            << CompressionModeName(
                   static_cast<CompressionMode>(first.compression()))
            << " while rank " << req.request_rank() << " requested "
            << CompressionModeName(
                   static_cast<CompressionMode>(req.compression()))
            << "; pass the same compression= (or HVD_TPU_COMPRESSION) on "
            << "every rank.";
      error_found = true;
      break;
    }
  }

  if (!error_found && (first.request_type() == Request::ALLREDUCE ||
                       first.request_type() == Request::BROADCAST ||
                       first.request_type() == Request::REDUCESCATTER)) {
    for (const auto& req : requests) {
      if (req.tensor_shape() != first.tensor_shape()) {
        TensorShape a(first.tensor_shape()), b(req.tensor_shape());
        error << "Mismatched " << Request::RequestTypeName(first.request_type())
              << " tensor shapes: one rank sent " << a.DebugString()
              << " while another sent " << b.DebugString() << ".";
        error_found = true;
        break;
      }
    }
  }

  if (!error_found && first.request_type() == Request::BROADCAST) {
    for (const auto& req : requests) {
      if (req.root_rank() != first.root_rank()) {
        error << "Mismatched broadcast root ranks: one rank specified "
              << first.root_rank() << " while another specified "
              << req.root_rank() << ".";
        error_found = true;
        break;
      }
    }
    if (!error_found && gid != 0 &&
        !std::binary_search(members.begin(), members.end(),
                            first.root_rank())) {
      error << "Broadcast root rank " << first.root_rank()
            << " is not a member of process group " << gid << " "
            << group_table_->DescribeMembers(gid) << ".";
      error_found = true;
    }
  }

  std::vector<int64_t> tensor_sizes;
  if (!error_found && first.request_type() == Request::ALLGATHER) {
    // All dims but the first must match; gather per-rank first dims —
    // indexed by GROUP position for group collectives (the executing
    // ring lays blocks out in group order).
    tensor_sizes.resize(requests.size(), 0);
    for (const auto& req : requests) {
      if (req.tensor_shape().size() != first.tensor_shape().size() ||
          req.tensor_shape().empty()) {
        error << "Mismatched allgather tensor ranks (dimensionality).";
        error_found = true;
        break;
      }
      for (std::size_t d = 1; d < req.tensor_shape().size(); ++d) {
        if (req.tensor_shape()[d] != first.tensor_shape()[d]) {
          error << "Mismatched allgather non-first dimensions.";
          error_found = true;
          break;
        }
      }
      if (error_found) break;
      int slot = req.request_rank();
      if (gid != 0) {
        slot = group_table_ != nullptr
                   ? group_table_->IndexOf(gid, req.request_rank())
                   : -1;
      }
      if (slot < 0 || slot >= static_cast<int>(tensor_sizes.size())) {
        error << "Invalid request rank " << req.request_rank() << ".";
        error_found = true;
        break;
      }
      tensor_sizes[slot] = req.tensor_shape()[0];
    }
  }

  Response response;
  response.add_tensor_name(first.tensor_name());
  response.set_group_id(gid);
  if (error_found) {
    response.set_response_type(Response::ERROR);
    if (gid != 0 && group_table_ != nullptr) {
      // Every group-scoped rejection names the group — the fix is
      // almost always a membership or scoping mistake.
      error << " [process group " << gid << ", ranks "
            << group_table_->DescribeMembers(gid) << "]";
    }
    response.set_error_message(error.str());
    return response;
  }
  if (gid != 0) {
    GlobalMetrics().AddGroupNegotiated(gid, 1);
  }
  response.set_tensor_type(first.tensor_type());
  response.set_devices(first.device());
  response.set_compression(first.compression());
  switch (first.request_type()) {
    case Request::ALLREDUCE: {
      response.set_response_type(Response::ALLREDUCE);
      TensorShape shape(first.tensor_shape());
      response.add_tensor_size(shape.num_elements());
      break;
    }
    case Request::ALLGATHER:
      response.set_response_type(Response::ALLGATHER);
      response.set_tensor_sizes(tensor_sizes);
      break;
    case Request::BROADCAST: {
      response.set_response_type(Response::BROADCAST);
      TensorShape shape(first.tensor_shape());
      response.add_tensor_size(shape.num_elements());
      break;
    }
    case Request::REDUCESCATTER: {
      // Total element count rides the response; the executing op and
      // the Python binding derive the per-rank shard partition from it
      // with the same PartitionChunks math (shard i owns chunk i).
      response.set_response_type(Response::REDUCESCATTER);
      TensorShape shape(first.tensor_shape());
      response.add_tensor_size(shape.num_elements());
      break;
    }
  }
  return response;
}

void Controller::FuseResponses(std::deque<Response>& responses,
                               ResponseList& response_list) {
  int64_t threshold = TensorFusionThresholdBytes();
  while (!responses.empty()) {
    Response response = std::move(responses.front());
    responses.pop_front();
    if (response.response_type() == Response::ALLREDUCE && threshold > 0) {
      int64_t dtype_size =
          static_cast<int64_t>(DataTypeSize(response.tensor_type()));
      int64_t total_bytes = 0;
      for (int64_t n : response.tensor_sizes()) total_bytes += n * dtype_size;
      // Look-ahead scan: merge any later allreduce with identical
      // (dtype, devices) while under threshold; preserve order of the rest.
      std::deque<Response> skipped;
      while (!responses.empty()) {
        Response next = std::move(responses.front());
        responses.pop_front();
        bool merged = false;
        if (next.response_type() == Response::ALLREDUCE &&
            next.tensor_type() == response.tensor_type() &&
            next.compression() == response.compression() &&
            // Tensors only fuse WITHIN a group: a fused buffer rides one
            // ring, and different groups ride different rings.
            next.group_id() == response.group_id() &&
            next.devices() == response.devices()) {
          int64_t next_bytes = 0;
          for (int64_t n : next.tensor_sizes()) next_bytes += n * dtype_size;
          if (total_bytes + next_bytes <= threshold) {
            total_bytes += next_bytes;
            for (const auto& nm : next.tensor_names())
              response.add_tensor_name(nm);
            for (int64_t n : next.tensor_sizes()) response.add_tensor_size(n);
            merged = true;
          }
        }
        if (!merged) skipped.push_back(std::move(next));
      }
      responses = std::move(skipped);
    }
    response_list.add_response(std::move(response));
  }
}

ResponseList Controller::FinishCycle(std::deque<Response> responses,
                                     std::vector<Request>& non_cached_messages,
                                     bool should_shut_down) {
  // Counted below only when the cycle carried work: idle empty-queue
  // cycles also pass through here (the round trip still happens as the
  // readiness heartbeat), and counting them would make the fast/full
  // split report pacing, not workload (cycles_fast_ likewise counts
  // only op-carrying fast cycles).
  const bool had_local_work = !responses.empty() ||
                              !non_cached_messages.empty();
  ResponseList response_list;
  if (is_coordinator()) {
    std::vector<std::string> ready_names;
    for (auto& msg : non_cached_messages) {
      if (IncrementTensorCount(msg, rank_)) {
        ready_names.push_back(
            GroupQualifiedName(msg.group_id(), msg.tensor_name()));
      }
    }
    // The coordinator's own call stream enters the detector directly (its
    // RequestList is never serialized).
    if (call_tracker_ != nullptr) {
      divergence_.Observe(rank_, cycle_call_seq_, cycle_call_digest_,
                          call_tracker_->RecordsSince(reported_call_seq_, 32,
                                                      cycle_call_seq_));
      reported_call_seq_ = cycle_call_seq_;
    }
    // Gather worker RequestLists (rank 0's own slot is unused).
    std::vector<std::string> blobs;
    GatherBlobs(std::string(), &blobs);
    // Clock-alignment T2: the reference clock's reading right after the
    // gather returned (the workers stamped T1 just before sending).
    const int64_t clock_t2 = GlobalTrace().NowNs();
    for (int r = 1; r < size_; ++r) {
      RequestList list;
      if (!list.ParseFrom(blobs[r].data(), blobs[r].size())) {
        LOG(ERROR) << "Failed to parse RequestList from rank " << r;
        continue;
      }
      if (list.shutdown()) should_shut_down = true;
      divergence_.Observe(r, list.call_seq(), list.call_digest(),
                          list.recent_calls());
      if (!list.metrics_summary().empty()) {
        GlobalMetrics().SetRankSummary(r, list.metrics_summary());
      }
      for (const auto& msg : list.requests()) {
        if (IncrementTensorCount(msg, r)) {
          ready_names.push_back(
              GroupQualifiedName(msg.group_id(), msg.tensor_name()));
        }
      }
    }
    // Late-registration sweep: group tensors whose id was unknown when
    // their announcements arrived (see IncrementTensorCount) go ready
    // as soon as this process's registry resolves the id and every
    // member has announced. ShouldForceFullCycle keeps full cycles
    // coming while anything is pending, so the sweep always gets to
    // run even after the announcements went quiet.
    for (const auto& kv : message_table_) {
      const Request& first = kv.second.front();
      if (first.group_id() == 0 || group_table_ == nullptr) continue;
      int gsize = group_table_->Size(first.group_id());
      if (gsize > 0 && static_cast<int>(kv.second.size()) >= gsize) {
        ready_names.push_back(kv.first);
      }
    }
    for (const auto& key : ready_names) {
      // A key can go ready twice in one cycle (two provably-bad group
      // reports poisoning it, or the announcement path plus the sweep);
      // the first ConstructResponse consumed it.
      if (message_table_.count(key) == 0) continue;
      responses.push_back(ConstructResponse(key));
    }
    // Workload profile for the autotuner's search space: did this cycle
    // negotiate wire compression, a reduce-scatter, or a subgroup
    // collective? A first sighting after convergence triggers a re-arm
    // (parameter_manager.h) so tuning re-scores under the new regime.
    {
      bool comp = false, rs = false, grp = false;
      for (const auto& resp : responses) {
        comp = comp || resp.compression() != 0;
        rs = rs || resp.response_type() == Response::REDUCESCATTER;
        grp = grp || resp.group_id() != 0;
      }
      if (comp || rs || grp) {
        parameter_manager_.ObserveWorkload(comp, rs, grp);
      }
    }
    // Divergence cross-check: fail provably diverged pending tensors NOW
    // with a named call site, instead of letting them hang to the stall
    // timeout (divergence.h documents the two proof rules).
    bool diverged = false;
    for (const auto& diag : divergence_.Check(message_table_,
                                              group_table_)) {
      diverged = true;
      LOG(ERROR) << diag.message;
      GlobalMetrics().divergence_errors_total.fetch_add(
          1, std::memory_order_relaxed);
      message_table_.erase(diag.key);
      stall_inspector_.RemoveUncachedTensor(diag.key);
      timeline_.NegotiateEnd(diag.key);
      negotiate_started_.erase(diag.key);
      // The ERROR response carries the BARE tensor name plus the group
      // id — entry lookup on every rank is (name, group)-scoped.
      Response error;
      error.add_tensor_name(diag.tensor_name);
      error.set_group_id(diag.group_id);
      error.set_response_type(Response::ERROR);
      error.set_error_message(diag.message);
      responses.push_back(std::move(error));
    }
    if (diverged) {
      // Flight recorder: the coordinator holds the proof (pending table
      // + call records); the workers hold their own in-flight evidence
      // — dump here, flag them to dump on parse.
      GlobalTrace().DumpBundle("divergence", PendingNegotiationJson());
      pending_trace_flags_ |= ResponseList::kFlagDumpBundle;
    }
    response_list.set_shutdown(should_shut_down);
    FuseResponses(responses, response_list);
    // Autotune bootstrap: consume any pending re-arm NOW (after fusion,
    // before the broadcast) and stamp the (epoch, profile) word on the
    // list — workers mirror the re-arm at parse time in this same
    // cycle, so the whole ring re-enters tuning in lockstep.
    response_list.set_autotune_wire(
        parameter_manager_.WireEpochForBroadcast());
    // Clock-alignment T3 (right before the broadcast) + any armed
    // bundle-dump flag ride the same tail.
    response_list.set_clock(clock_t2, GlobalTrace().NowNs());
    response_list.set_trace_flags(pending_trace_flags_);
    pending_trace_flags_ = 0;
    std::string blob;
    response_list.SerializeTo(&blob);
    BroadcastBlob(&blob);
  } else {
    RequestList message_list;
    message_list.set_shutdown(should_shut_down);
    if (call_tracker_ != nullptr) {
      message_list.set_call_seq(cycle_call_seq_);
      message_list.set_call_digest(cycle_call_digest_);
      message_list.set_recent_calls(
          call_tracker_->RecordsSince(reported_call_seq_, 32,
                                      cycle_call_seq_));
      reported_call_seq_ = cycle_call_seq_;
    }
    if (metrics_plane_enabled_) {
      auto now = std::chrono::steady_clock::now();
      if (std::chrono::duration<double>(now - last_summary_attach_).count() >=
          metrics_sync_seconds_) {
        message_list.set_metrics_summary(GlobalMetrics().Summary());
        last_summary_attach_ = now;
      }
    }
    for (auto& msg : non_cached_messages) {
      message_list.add_request(msg);
    }
    std::string blob;
    message_list.SerializeTo(&blob);
    // Clock-alignment T1/T4 bracket the gather+broadcast round trip;
    // the coordinator's T2/T3 stamps ride the ResponseList tail back.
    Trace& trace = GlobalTrace();
    const int64_t clock_t1 = trace.NowNs();
    GatherBlobs(blob, nullptr);
    std::string response_blob;
    BroadcastBlob(&response_blob);
    const int64_t clock_t4 = trace.NowNs();
    if (!response_list.ParseFrom(response_blob.data(), response_blob.size())) {
      LOG(FATAL) << "Failed to parse ResponseList from coordinator";
    }
    if (response_list.autotune_wire() != ResponseList::kAutotuneAbsent) {
      parameter_manager_.NoteWireEpoch(response_list.autotune_wire());
    }
    if (response_list.clock_t2() >= 0 && response_list.clock_t3() >= 0) {
      trace.UpdateClockSample(clock_t1, response_list.clock_t2(),
                              response_list.clock_t3(), clock_t4);
    }
    if (response_list.trace_flags() & ResponseList::kFlagDumpBundle) {
      // The coordinator saw a stall escalation / divergence this cycle;
      // dump while the evidence is still in this rank's ring.
      trace.DumpBundle("escalation", std::string());
    }
  }
  // Work on ANY rank makes this a full work cycle (the final list is
  // identical everywhere; a worker whose own queue was empty still
  // executed a real negotiation for the ranks that had work).
  if (had_local_work || !response_list.responses().empty()) {
    cycles_full_ += 1;
    GlobalMetrics().cycles_full_total.fetch_add(1, std::memory_order_relaxed);
  }
  GlobalMetrics().pending_negotiation.store(
      static_cast<int64_t>(message_table_.size()), std::memory_order_relaxed);
  return response_list;
}

ResponseList Controller::ComputeResponseList(
    bool this_process_requested_shutdown) {
  CacheCoordinator cache_coordinator(response_cache_.num_active_bits());

  // Snapshot BEFORE the queue pop (see cycle_call_seq_ in controller.h:
  // the pop then provably contains every call the snapshot counts).
  if (call_tracker_ != nullptr) {
    call_tracker_->Snapshot(&cycle_call_seq_, &cycle_call_digest_);
  }

  std::deque<Request> message_queue_tmp;
  tensor_queue_.PopMessagesFromQueue(message_queue_tmp);
  Metrics& metrics = GlobalMetrics();
  metrics.queue_depth.store(static_cast<int64_t>(message_queue_tmp.size()),
                            std::memory_order_relaxed);

  std::vector<Request> non_cached_messages;
  // bit -> locally-hit message, pending global agreement.
  std::unordered_map<uint32_t, Request> hit_messages;

  bool cache_on = response_cache_.capacity() > 0 &&
                  parameter_manager_.CacheEnabled();
  for (auto& message : message_queue_tmp) {
    if (cache_on) {
      auto state = response_cache_.cached(message);
      if (state == ResponseCache::CacheState::HIT) {
        uint32_t bit = response_cache_.peek_cache_bit(message);
        cache_coordinator.record_hit(bit);
        metrics.cache_hit_total.fetch_add(1, std::memory_order_relaxed);
        stall_inspector_.RecordCachedTensorStart(GroupQualifiedName(
            message.group_id(), message.tensor_name()));
        hit_messages.emplace(bit, std::move(message));
        continue;
      }
      if (state == ResponseCache::CacheState::INVALID) {
        uint32_t bit = response_cache_.peek_cache_bit(message);
        cache_coordinator.record_invalid_bit(bit);
        metrics.cache_invalid_total.fetch_add(1, std::memory_order_relaxed);
      } else {
        metrics.cache_miss_total.fetch_add(1, std::memory_order_relaxed);
      }
    }
    cache_coordinator.set_uncached_in_queue(true);
    non_cached_messages.push_back(std::move(message));
  }
  // Process groups (docs/GROUPS.md): every cached tensor belonging to a
  // group this rank is NOT a member of is vacuously ready here — record
  // its bit as a hit so the cross-rank AND reduces to an AND over the
  // group's actual members. Without this, a group tensor could never
  // take the fast path (non-members would always zero its bit).
  if (cache_on) {
    std::vector<uint32_t> foreign_bits;
    response_cache_.NonMemberBits(&foreign_bits);
    for (uint32_t bit : foreign_bits) cache_coordinator.record_hit(bit);
  }
  // Periodic stall inspection — must run every cycle type (stalls surface
  // precisely when no negotiation is happening): warn about tensors waiting
  // on missing ranks, invalidate stalled cached tensors so they renegotiate,
  // and escalate to coordinated shutdown past the threshold.
  if (stall_inspector_.ShouldPerformCheck()) {
    if (cache_on) {
      std::vector<uint32_t> stalled_bits;
      stall_inspector_.InvalidateStalledCachedTensors(response_cache_,
                                                      stalled_bits);
      for (uint32_t bit : stalled_bits) {
        cache_coordinator.record_invalid_bit(bit);
      }
    }
    if (is_coordinator() &&
        stall_inspector_.CheckForStalledTensors(size_)) {
      this_process_requested_shutdown = true;
      // Flight recorder: capture the pending table (missing ranks by
      // name) before the coordinated shutdown tears it down, and arm
      // the broadcast flag so every worker dumps too.
      GlobalTrace().DumpBundle("stall_escalation", PendingNegotiationJson());
      pending_trace_flags_ |= ResponseList::kFlagDumpBundle;
    }
    stall_inspector_.UpdateCheckTime();
  }
  // An armed bundle flag rides full-cycle broadcasts only — break the
  // all-cached fast path until FinishCycle ships it.
  if (is_coordinator() && pending_trace_flags_ != 0) {
    cache_coordinator.set_uncached_in_queue(true);
  }
  // Quiescent-stall escape hatch: when every rank is blocked waiting, no
  // rank has uncached work, so cycles ride the fast bit-sync and the
  // coordinator would never see fresh seq/digest reports to cross-check.
  // An aged pending tensor makes the coordinator force a full round trip
  // (the flag is OR-synced, so all ranks follow); workers then ship their
  // call-tracker state on otherwise-empty RequestLists. Rate-limited
  // inside ShouldForceFullCycle.
  if (is_coordinator() &&
      divergence_.ShouldForceFullCycle(message_table_)) {
    cache_coordinator.set_uncached_in_queue(true);
  }
  // Autotune re-arm delivery: the bootstrap word rides full-cycle
  // broadcasts only, so a pending re-arm must break the all-cached fast
  // path until the next FinishCycle ships it.
  if (is_coordinator() && parameter_manager_.RearmPending()) {
    cache_coordinator.set_uncached_in_queue(true);
  }
  // Metrics freshness: all-cached steady state (and total quiescence)
  // never sends RequestLists, so piggybacked summaries would freeze at
  // their last full cycle — precisely when a live job view matters. The
  // coordinator forces one full round trip per sync interval; the
  // OR-synced uncached flag brings every rank along, and workers attach
  // their summaries to the otherwise-empty lists.
  if (is_coordinator() && metrics_plane_enabled_ && size_ > 1) {
    auto now = std::chrono::steady_clock::now();
    if (std::chrono::duration<double>(now - last_metrics_force_).count() >=
        metrics_sync_seconds_) {
      cache_coordinator.set_uncached_in_queue(true);
      last_metrics_force_ = now;
    }
  }

  cache_coordinator.set_should_shut_down(this_process_requested_shutdown);

  bool should_shut_down = this_process_requested_shutdown;
  std::deque<Response> cached_responses;
  bool all_cached = false;

  if (cache_on) {
    cache_coordinator.sync(this, timeline_.Initialized());
    should_shut_down = cache_coordinator.should_shut_down();

    // Locally-hit tensors that lost the global AND wait for the other ranks:
    // re-queue them for a later cycle. Invalidated ones renegotiate now.
    for (auto& kv : hit_messages) {
      if (cache_coordinator.cache_hits().count(kv.first)) continue;
      if (cache_coordinator.invalid_bits().count(kv.first)) {
        stall_inspector_.RemoveCachedTensor(GroupQualifiedName(
            kv.second.group_id(), kv.second.tensor_name()));
        non_cached_messages.push_back(std::move(kv.second));
      } else {
        tensor_queue_.PushMessageToQueue(kv.second);
      }
    }

    // Materialize + LRU-touch globally-hit responses before any erase can
    // perturb bit numbering. Identical motion on every rank keeps future
    // evictions consistent.
    for (uint32_t bit : cache_coordinator.cache_hits()) {
      cached_responses.push_back(response_cache_.get_response(bit));
      stall_inspector_.RemoveCachedTensor(GroupQualifiedName(
          cached_responses.back().group_id(),
          cached_responses.back().tensor_names()[0]));
    }

    // Drop invalidated entries identically on every rank, then re-pack bits.
    std::vector<uint32_t> invalid(cache_coordinator.invalid_bits().begin(),
                                  cache_coordinator.invalid_bits().end());
    std::sort(invalid.rbegin(), invalid.rend());
    for (uint32_t bit : invalid) response_cache_.erase_response(bit);
    response_cache_.update_cache_bits();

    // A cycle that invalidated bits anywhere must run the FULL
    // negotiation: invalidated local hits were just moved into
    // non_cached_messages to renegotiate, and the fast-path return
    // below would silently DROP them — the op's rank never reaches
    // the coordinator's count and the job livelocks with a permanent
    // "missing ranks" stall (hit live: a stall-inspector cache
    // invalidation during a straggler wait; reference analogue of the
    // invalid_in_queue gate in common/response_cache.cc's
    // CoordinateCacheAndState flow).
    all_cached = !cache_coordinator.uncached_in_queue() &&
                 !cache_coordinator.invalid_in_queue();
  }

  if (cache_on && all_cached) {
    // Fast path: everything queued this cycle was globally cached; no
    // coordinator round trip. Every rank builds the identical list locally.
    if (!cached_responses.empty()) {
      cycles_fast_ += 1;
      metrics.cycles_fast_total.fetch_add(1, std::memory_order_relaxed);
    }
    ResponseList response_list;
    response_list.set_shutdown(should_shut_down);
    FuseResponses(cached_responses, response_list);
    return response_list;
  }

  return FinishCycle(std::move(cached_responses), non_cached_messages,
                     should_shut_down);
}

std::string Controller::PendingNegotiationJson() const {
  if (!is_coordinator()) return "{}";
  auto now = std::chrono::steady_clock::now();
  std::string out = "{\"pending\":[";
  bool first_entry = true;
  for (const auto& kv : message_table_) {
    if (!first_entry) out += ',';
    first_entry = false;
    out += "{\"name\":\"";
    for (char c : kv.first) {
      if (c == '"' || c == '\\') out += '\\';
      out += c;
    }
    out += "\",\"reported\":[";
    std::unordered_set<int> reported;
    bool first_rank = true;
    for (const auto& req : kv.second) {
      reported.insert(req.request_rank());
      if (!first_rank) out += ',';
      first_rank = false;
      out += std::to_string(req.request_rank());
    }
    out += "],\"missing\":[";
    const Request& head = kv.second.front();
    std::vector<int> members;
    if (head.group_id() != 0 && group_table_ != nullptr) {
      members = group_table_->Members(head.group_id());
    }
    if (members.empty()) {
      for (int r = 0; r < size_; ++r) members.push_back(r);
    }
    first_rank = true;
    for (int r : members) {
      if (reported.count(r)) continue;
      if (!first_rank) out += ',';
      first_rank = false;
      out += std::to_string(r);
    }
    out += "],\"age_seconds\":";
    double age = 0.0;
    auto it = negotiate_started_.find(kv.first);
    if (it != negotiate_started_.end()) {
      age = std::chrono::duration<double>(now - it->second).count();
    }
    char buf[32];
    std::snprintf(buf, sizeof(buf), "%.3f", age);
    out += buf;
    out += '}';
  }
  out += "]}";
  return out;
}

}  // namespace hvdtpu
