#include "parameter_manager.h"

#include <chrono>

#include "bayesian_optimization.h"
#include "logging.h"

namespace hvdtpu {

static double NowMicros() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

ParameterManager::ParameterManager() = default;
ParameterManager::~ParameterManager() = default;

void ParameterManager::Initialize(int32_t rank,
                                  const std::string& autotune_log_file) {
  rank_ = rank;
  if (rank == 0 && !autotune_log_file.empty()) {
    log_.open(autotune_log_file, std::ios::out | std::ios::trunc);
    if (log_.is_open()) {
      log_ << "fusion_mb,cycle_time_ms,cache_enabled,hierarchical_allreduce,"
              "hierarchical_allgather,score_bytes_per_us\n";
    }
  }
  // Categorical combos to sweep: (cache, hier_allreduce, hier_allgather).
  // Fixed knobs collapse their dimension.
  categorical_combos_.clear();
  std::vector<bool> cache_opts =
      cache_fixed_ ? std::vector<bool>{cache_enabled_}
                   : std::vector<bool>{true, false};
  std::vector<bool> har_opts =
      hier_ar_fixed_ ? std::vector<bool>{hierarchical_allreduce_}
                     : std::vector<bool>{false, true};
  std::vector<bool> hag_opts =
      hier_ag_fixed_ ? std::vector<bool>{hierarchical_allgather_}
                     : std::vector<bool>{false, true};
  for (bool c : cache_opts) {
    for (bool ar : har_opts) {
      for (bool ag : hag_opts) {
        categorical_combos_.push_back({c, ar, ag});
      }
    }
  }
  optimizers_.clear();
  for (std::size_t i = 0; i < categorical_combos_.size(); ++i) {
    optimizers_.push_back(std::make_unique<BayesianOptimizer>(
        std::vector<std::pair<double, double>>{{0.0, 64.0}, {1.0, 100.0}},
        /*seed=*/1234 + i));
  }
}

void ParameterManager::SetAutoTuning(bool active) {
  active_ = active;
  if (active) {
    warmup_remaining_ = 3;
    cycles_in_sample_ = 0;
    bytes_in_sample_ = 0;
    sample_count_ = 0;
    combo_index_ = 0;
    samples_in_combo_ = 0;
    ReadyTune();
  }
}

int64_t ParameterManager::TensorFusionThresholdBytes() const {
  return static_cast<int64_t>(fusion_mb_ * 1024.0 * 1024.0);
}

void ParameterManager::SetTensorFusionThresholdBytes(int64_t threshold,
                                                     bool fixed) {
  fusion_mb_ = static_cast<double>(threshold) / (1024.0 * 1024.0);
  fusion_fixed_ = fusion_fixed_ || fixed;
}

double ParameterManager::CycleTimeMs() const { return cycle_time_ms_; }

void ParameterManager::SetCycleTimeMs(double cycle_time_ms, bool fixed) {
  cycle_time_ms_ = cycle_time_ms;
  cycle_fixed_ = cycle_fixed_ || fixed;
}

bool ParameterManager::CacheEnabled() const { return cache_enabled_; }

void ParameterManager::SetCacheEnabled(bool enabled, bool fixed) {
  cache_enabled_ = enabled;
  cache_fixed_ = cache_fixed_ || fixed;
}

bool ParameterManager::HierarchicalAllreduce() const {
  return hierarchical_allreduce_;
}

void ParameterManager::SetHierarchicalAllreduce(bool enabled, bool fixed) {
  hierarchical_allreduce_ = enabled;
  hier_ar_fixed_ = hier_ar_fixed_ || fixed;
}

bool ParameterManager::HierarchicalAllgather() const {
  return hierarchical_allgather_;
}

void ParameterManager::SetHierarchicalAllgather(bool enabled, bool fixed) {
  hierarchical_allgather_ = enabled;
  hier_ag_fixed_ = hier_ag_fixed_ || fixed;
}

void ParameterManager::ReadyTune() {
  // Apply the next sample point of the current categorical combo.
  if (combo_index_ >= categorical_combos_.size()) return;
  const auto& combo = categorical_combos_[combo_index_];
  if (!cache_fixed_) cache_enabled_ = combo[0];
  if (!hier_ar_fixed_) hierarchical_allreduce_ = combo[1];
  if (!hier_ag_fixed_) hierarchical_allgather_ = combo[2];
  auto next = optimizers_[combo_index_]->NextSample();
  if (!fusion_fixed_) fusion_mb_ = next[0];
  if (!cycle_fixed_) cycle_time_ms_ = next[1];
}

void ParameterManager::LogSample(double score) {
  if (!log_.is_open()) return;
  log_ << fusion_mb_ << "," << cycle_time_ms_ << "," << cache_enabled_ << ","
       << hierarchical_allreduce_ << "," << hierarchical_allgather_ << ","
       << score << "\n";
  log_.flush();
}

bool ParameterManager::Update(const std::vector<std::string>& tensor_names,
                              int64_t bytes) {
  if (!active_) return false;
  if (cycles_in_sample_ == 0 && bytes_in_sample_ == 0) {
    sample_start_us_ = NowMicros();
  }
  bytes_in_sample_ += bytes;
  ++cycles_in_sample_;
  (void)tensor_names;
  if (cycles_in_sample_ < kCyclesPerSample) return false;

  double elapsed_us = NowMicros() - sample_start_us_;
  double score = elapsed_us > 0
                     ? static_cast<double>(bytes_in_sample_) / elapsed_us
                     : 0.0;
  cycles_in_sample_ = 0;
  bytes_in_sample_ = 0;

  if (warmup_remaining_ > 0) {
    --warmup_remaining_;
    return false;
  }
  return Tune(score);
}

bool ParameterManager::Tune(double score) {
  LogSample(score);
  if (score > best_score_) {
    best_score_ = score;
    best_fusion_mb_ = fusion_mb_;
    best_cycle_ms_ = cycle_time_ms_;
    best_cache_ = cache_enabled_;
    best_hier_ar_ = hierarchical_allreduce_;
    best_hier_ag_ = hierarchical_allgather_;
  }
  optimizers_[combo_index_]->AddSample({fusion_mb_, cycle_time_ms_}, score);
  ++sample_count_;
  ++samples_in_combo_;
  if (samples_in_combo_ >= kSamplesPerCombo) {
    samples_in_combo_ = 0;
    ++combo_index_;
  }
  if (sample_count_ >= kMaxSamples ||
      combo_index_ >= categorical_combos_.size()) {
    // Converged: adopt the best configuration and stop tuning.
    if (!fusion_fixed_) fusion_mb_ = best_fusion_mb_;
    if (!cycle_fixed_) cycle_time_ms_ = best_cycle_ms_;
    if (!cache_fixed_) cache_enabled_ = best_cache_;
    if (!hier_ar_fixed_) hierarchical_allreduce_ = best_hier_ar_;
    if (!hier_ag_fixed_) hierarchical_allgather_ = best_hier_ag_;
    active_ = false;
    LOG(INFO) << "autotune converged: fusion_mb=" << fusion_mb_
              << " cycle_ms=" << cycle_time_ms_
              << " cache=" << cache_enabled_
              << " score=" << best_score_ << " bytes/us";
    return true;
  }
  ReadyTune();
  return true;
}

ParameterManager::Params ParameterManager::GetParams() const {
  Params p;
  p.fusion_mb = fusion_mb_;
  p.cycle_time_ms = cycle_time_ms_;
  p.cache_enabled = cache_enabled_ ? 1 : 0;
  p.hierarchical_allreduce = hierarchical_allreduce_ ? 1 : 0;
  p.hierarchical_allgather = hierarchical_allgather_ ? 1 : 0;
  p.active = active_ ? 1 : 0;
  return p;
}

void ParameterManager::SetParams(const Params& p) {
  fusion_mb_ = p.fusion_mb;
  cycle_time_ms_ = p.cycle_time_ms;
  cache_enabled_ = p.cache_enabled != 0;
  hierarchical_allreduce_ = p.hierarchical_allreduce != 0;
  hierarchical_allgather_ = p.hierarchical_allgather != 0;
  active_ = p.active != 0;
}

}  // namespace hvdtpu
